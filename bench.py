"""Headline benchmark: llama training-step MFU on the attached accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: BASELINE.json north star — Llama-family ≥45% MFU on v5e (the
reference has no checked-in ML perf numbers, SURVEY.md §6). vs_baseline is
achieved_MFU / 0.45 on TPU.

Sized for one v5e chip (16 GiB HBM): ~315M-param llama, bf16 weights, f32
adam moments, batch 8 × seq 1024, remat on.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _tpu_configs():
    """Candidate configs, best-first; the runner falls back on OOM.
    Larger dims feed the MXU better (VERDICT r02: dim-1024/315M leaves
    utilization on the table); save_attn remat (the default) keeps
    attention out of the recompute path."""
    from ray_tpu.models import llama
    return [
        # ~560M @ dim 1536: ~8 GB params+opt in HBM, activations remat'd
        (llama.LlamaConfig(
            vocab_size=32000, dim=1536, n_layers=14, n_heads=16,
            n_kv_heads=8, mlp_dim=6144, max_seq_len=1024,
            dtype=jnp.bfloat16, remat=True, use_flash=True,
            attn_block_q=512, attn_block_k=512), 8, 1024),
        # r02-proven fallback (~315M @ dim 1024, MFU 0.3657 pre-kernels)
        (llama.LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=16, n_heads=16,
            n_kv_heads=8, mlp_dim=4096, max_seq_len=1024,
            dtype=jnp.bfloat16, remat=True, use_flash=True,
            attn_block_q=512, attn_block_k=512), 8, 1024),
    ]


def _model_and_batch(on_tpu: bool, candidate: int = 0):
    from ray_tpu.models import llama
    if on_tpu:
        cfg, batch, seq = _tpu_configs()[candidate]
    else:  # CPU smoke configuration — numbers are not meaningful
        cfg = llama.llama_tiny(n_layers=2, dim=64, mlp_dim=128,
                               max_seq_len=128)
        batch, seq = 2, 128
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, seq + 1)), jnp.int32)
    return cfg, tokens


def _run_candidate(on_tpu: bool, candidate: int):
    import optax
    from ray_tpu.models import llama

    cfg, tokens = _model_and_batch(on_tpu, candidate)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    opt = optax.adamw(3e-4, weight_decay=0.0)
    opt_state = opt.init(params)

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = llama.apply(p, tokens[:, :-1], cfg)
            return llama.cross_entropy_loss(logits, tokens[:, 1:])
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup (compile) + 2 stabilization steps; float() forces a full sync —
    # on the remote-relay TPU platform block_until_ready alone does not
    for _ in range(3):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    float(loss)
    return cfg, tokens, params, opt_state, train_step


def _probe_accelerator(timeout_s: float = 90.0) -> bool:
    """The axon tunnel HANGS jax.devices() when unhealthy — probe it in
    a killable child first so a dead tunnel yields a fast, recorded
    failure instead of an eternal hang."""
    import os
    import subprocess
    import sys
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             # honor JAX_PLATFORMS even though the axon sitecustomize
             # overrides it at import (CPU smoke runs need this)
             "import os, jax; p = os.environ.get('JAX_PLATFORMS'); "
             "p and jax.config.update('jax_platforms', p); "
             "print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
            env=dict(os.environ))
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def trace_arg(argv) -> "str | None":
    """Shared --trace <out.json> parsing for the bench CLIs."""
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 < len(argv):
            return argv[i + 1]
    return None


def flight_report(trace_out, trace_t0) -> None:
    """Shared bench --trace tail: export the flight recording of the
    measured section (cluster-stitched when a runtime is up, local ring
    otherwise) and print the wait/dispatch breakdown JSON line next to
    the throughput numbers. No-op unless --trace was given; never fails
    the bench."""
    if not trace_out:
        return
    try:
        from ray_tpu.core import flight
        from ray_tpu.core import runtime as rt_mod
        rt = rt_mod.get_runtime_if_exists()
        rep = flight.capture_report(rt, trace_t0, trace_out)
        print(json.dumps({
            "metric": "flight_trace",
            "out": trace_out,
            "events": rep["events"],
            "wait_s": rep["wait_s"],
            "counts": rep["counts"],
        }))
    except Exception as e:  # noqa: BLE001 — tracing must not fail a bench
        print(json.dumps({"metric": "flight_trace", "error": str(e)[:200]}))


def repin_jax_platforms():
    """Honor JAX_PLATFORMS after import: the axon sitecustomize
    overrides the jax config (not the env var) at import time, so CPU
    smoke runs must re-apply it (same recipe as tests/conftest.py)."""
    import os
    p = os.environ.get("JAX_PLATFORMS")
    if p:
        import jax
        jax.config.update("jax_platforms", p)


def main():
    from ray_tpu.parallel.mesh import tpu_topology
    repin_jax_platforms()

    if not _probe_accelerator():
        print(json.dumps({
            "metric": "llama_train_mfu", "value": None,
            "unit": "fraction_of_peak_bf16",
            "vs_baseline": None,
            "error": "accelerator unreachable (tunnel probe timed out)",
        }))
        raise SystemExit(3)

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    topo = tpu_topology([dev])
    n_candidates = len(_tpu_configs()) if on_tpu else 1
    for candidate in range(n_candidates):
        try:
            cfg, tokens, params, opt_state, train_step = _run_candidate(
                on_tpu, candidate)
            break
        except Exception as e:  # OOM on the big config -> proven fallback
            if candidate + 1 >= n_candidates or \
                    "RESOURCE_EXHAUSTED" not in repr(e).upper():
                raise
    batch, seqp1 = tokens.shape
    seq = seqp1 - 1

    n_steps = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    loss_v = float(loss)  # sync point inside the timed region
    dt = (time.perf_counter() - t0) / n_steps

    tokens_per_step = batch * seq
    flops_per_step = cfg.flops_per_token(seq) * tokens_per_step
    mfu = flops_per_step / dt / topo.peak_flops_bf16
    print(json.dumps({
        "metric": "llama_train_mfu",
        "value": round(float(mfu), 4),
        "unit": f"fraction_of_peak_bf16 ({topo.generation}, "
                f"{tokens_per_step / dt:.0f} tok/s, loss={loss_v:.3f})",
        "vs_baseline": round(float(mfu) / 0.45, 4),
    }))


if __name__ == "__main__":
    main()
