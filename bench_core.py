"""Core runtime microbenchmarks (reference harness parity:
python/ray/_private/ray_perf.py:95 via release/microbenchmark).

Prints one JSON line per metric plus a combined gate line. Baselines are
the reference's checked-in 2.47.0 numbers (BASELINE.md): single-client
tasks 961/s, 1:1 actor calls sync 1960/s, async 8220/s, gets 10841/s,
put 19.56 GiB/s.

``--quick`` runs a few-hundred-op smoke of the control-plane metrics only
(no put/collective/training hedges): same JSON line format, finishes in
seconds, and is wired into the test suite as a slow-marked regression
canary (tests/test_control_fastpath.py) so control-plane throughput
collapses are visible in-tree, not only in the external bench harness.
"""
import json
import time

import numpy as np


def timed(n, fn):
    t0 = time.perf_counter()
    fn()
    return n / (time.perf_counter() - t0)


def main(quick: bool = False, trace_out: str | None = None):
    import ray_tpu as ray

    # size the pool to the machine: on few-core hosts extra workers just
    # contend (the reference's ray_perf tunes workers per host the same
    # way); prestart them all so cold-start never lands in a timed region
    import os

    from ray_tpu.core.config import cfg
    n_cpus = min(4, max(2, (os.cpu_count() or 2)))
    # production posture for a long-lived cluster: prefault the store (put
    # bandwidth measures memcpy, not first-touch page zeroing) and hand
    # out zero-copy pinned views on get (plasma semantics)
    cfg.override(worker_prestart=n_cpus, store_prefault=True,
                 zero_copy_get=True)
    ray.init(num_cpus=n_cpus, object_store_memory=1 << 30)

    @ray.remote
    def nop():
        return None

    @ray.remote
    class Actor:
        def nop(self):
            return None

        def step(self, x):
            return x

    results = {}

    # --quick: few hundred ops per metric, control-plane metrics only
    n_sync = 100 if quick else 500
    n_async = 400 if quick else 2000
    n_gets = 500 if quick else 3000

    # warmup: worker pool spin-up + code ship; then QUIESCE — on this
    # 1-core box a prestarted worker still finishing its imports steals
    # most of the core from any timed section (wall 3x cpu measured)
    ray.get([nop.remote() for _ in range(20)], timeout=120)
    time.sleep(0.5 if quick else 3.0)

    # --trace: flight-record the measured section (everything after the
    # warmup) and report the wait/dispatch breakdown with the numbers
    trace_t0 = time.monotonic_ns() if trace_out else None

    # single client tasks sync
    def tasks_sync():
        for _ in range(n_sync):
            ray.get(nop.remote(), timeout=60)
    results["single_client_tasks_sync"] = (timed(n_sync, tasks_sync), 961)

    # single client tasks async (batch submit, one drain)
    def tasks_async():
        ray.get([nop.remote() for _ in range(n_async)], timeout=120)
    results["single_client_tasks_async"] = (timed(n_async, tasks_async), 6787)

    a = Actor.remote()
    ray.get(a.nop.remote(), timeout=60)

    def actor_sync():
        for _ in range(n_sync):
            ray.get(a.nop.remote(), timeout=60)
    results["1_1_actor_calls_sync"] = (timed(n_sync, actor_sync), 1960)

    def actor_async():
        ray.get([a.nop.remote() for _ in range(n_async)], timeout=120)
    results["1_1_actor_calls_async"] = (timed(n_async, actor_async), 8220)

    # single client get (small object, repeated)
    ref = ray.put(b"x" * 1024)

    def gets():
        for _ in range(n_gets):
            ray.get(ref, timeout=60)
    results["single_client_get_calls"] = (timed(n_gets, gets), 10841)

    # await-based burst: refs awaited concurrently through the shared
    # completion multiplexer (ObjectRef.__await__ -> core/completion.py)
    # — tracks the async completion fast path the serve handles ride
    import asyncio

    n_await = 200 if quick else 1000

    async def _await_burst():
        await asyncio.gather(*[nop.remote() for _ in range(n_await)])

    def await_burst():
        asyncio.run(_await_burst())
    results["async_burst"] = (timed(n_await, await_burst), 6787)

    # compiled-DAG roundtrip vs the equivalent uncompiled actor chain:
    # the "baseline" here is OUR OWN uncompiled rate measured in the same
    # run, so vs_baseline is the compile speedup (acceptance bar: >= 2x)
    from ray_tpu.dag import InputNode
    d1, d2 = Actor.remote(), Actor.remote()
    ray.get([d1.step.remote(0), d2.step.remote(0)], timeout=60)
    n_dag = 100 if quick else 400

    def chain():
        for i in range(n_dag):
            ray.get(d2.step.remote(d1.step.remote(i)), timeout=60)
    uncompiled_rate = timed(n_dag, chain)
    with InputNode() as inp:
        out = d2.step.bind(d1.step.bind(inp))
    cdag = out.experimental_compile(max_inflight=2)
    cdag.execute(0).get()

    def dag_loop():
        for i in range(n_dag):
            cdag.execute(i).get()
    dag_rate = timed(n_dag, dag_loop)
    cdag.teardown()
    results["compiled_dag_roundtrip"] = (dag_rate, uncompiled_rate)

    if quick:
        _flight_report(trace_out, trace_t0)
        ray.shutdown()
        _report(results)
        return

    # put throughput, steady state. Dropped refs free asynchronously, so
    # between passes poll until the store is EMPTY again — this both
    # guarantees heap regions recycle (each pass rewrites the same bytes,
    # the long-lived-cluster steady state) and rules out silently timing
    # the disk-spill path (spill only triggers above 80% occupancy, which
    # an empty store per 512 MiB pass can never reach). The first ~3
    # passes on this VM crawl on host-side lazy page machinery; time the
    # converged tail and report its true median (zeros chunk = the same
    # workload as the reference's ray_perf put benchmark).
    from ray_tpu.core.api import _runtime
    store = _runtime().store

    resident = store.bytes_in_use()  # earlier benches' live refs

    def settle_empty():
        deadline = time.perf_counter() + 10.0
        while store.bytes_in_use() > resident:
            if time.perf_counter() > deadline:
                raise RuntimeError("put bench: store did not drain; "
                                   "rates would include spill/evict paths")
            time.sleep(0.02)

    # Timed region = the put call alone; the settle between puts (waiting
    # for the async ref-drop free) is a benchmark artifact, not part of
    # the put path a user times. With the store drained, first-fit hands
    # every put the same recycled heap region.
    chunk = np.zeros(128 * 1024 * 1024, dtype=np.uint8)
    rates = []
    for _ in range(12):
        t0 = time.perf_counter()
        ray.put(chunk)
        rates.append((128 / 1024) / (time.perf_counter() - t0))
        settle_empty()
    tail = sorted(rates[5:])  # drop warmup; report the converged median
    gibs = tail[len(tail) // 2]
    results["single_client_put_gigabytes"] = (gibs, 19.56)

    # store-backed collective broadcast (driver rank 0 -> 1 actor rank):
    # bulk bytes ride the object store, the rendezvous actor passes refs
    # only. No reference microbenchmark exists for this; the baseline is a
    # 1 GiB/s target (DCN-class link speed, the bar the store path must
    # clear to be worth using for cross-host weight shuttling).
    from ray_tpu.util import collective as col

    @ray.remote
    class Rank:
        def init_collective_group(self, world, rank, backend, group):
            from ray_tpu.util import collective as c
            c.init_collective_group(world, rank, backend, group)

        def recv_broadcast(self, group, n):
            import numpy as np
            from ray_tpu.util import collective as c
            out = c.broadcast(np.zeros(1), 0, group)
            return out.nbytes

    actor = Rank.remote()
    ref = actor.init_collective_group.remote(2, 1, "shm", "bench")
    col.init_collective_group(2, 0, "shm", "bench")
    ray.get(ref, timeout=60)
    payload = np.zeros(256 * 1024 * 1024, dtype=np.uint8)
    # warmup small
    r = actor.recv_broadcast.remote("bench", 1)
    col.broadcast(np.zeros(2 * 1024 * 1024, dtype=np.uint8), 0, "bench")
    ray.get(r, timeout=60)

    def bcast():
        r = actor.recv_broadcast.remote("bench", len(payload))
        col.broadcast(payload, 0, "bench")
        assert ray.get(r, timeout=120) == len(payload)
    results["collective_broadcast_gigabytes"] = (
        timed(1, bcast) * 256 / 1024, 1.0)
    col.destroy_collective_group("bench")

    _flight_report(trace_out, trace_t0)
    ray.shutdown()

    _report(results)

    # TPU-down hedge: pinned CPU-mesh training-step trend (bench_trend.py)
    # — catches sharded-step regressions even when the tunnel is dead
    try:
        import bench_trend
        tps = bench_trend.measure()
        base = (bench_trend.BASELINE_TOKENS_PER_SEC
                or bench_trend._PIN_FILE_DEFAULT)
        print(json.dumps({
            "metric": "cpu_mesh_tokens_per_sec",
            "value": round(tps, 1),
            "unit": "tokens/s (8-dev virtual CPU mesh, pinned config)",
            "vs_baseline": round(tps / base, 3),
        }))
    except Exception as e:  # noqa: BLE001 — the hedge must never fail core
        print(json.dumps({"metric": "cpu_mesh_tokens_per_sec",
                          "value": None, "unit": "tokens/s",
                          "error": str(e)[:200]}))

    # serving dispatch economy: DISPATCHES per generated token on a
    # pinned burst (a count, machine-independent; bench_trend.py);
    # ~1.1 would mean the engine fell back to a dispatch per token.
    try:
        import bench_trend
        dpt = bench_trend.measure_serve_dispatch()
        pin = bench_trend.BASELINE_SERVE_DISPATCH_PER_TOKEN
        print(json.dumps({
            "metric": "serve_dispatches_per_token",
            "value": round(dpt, 4),
            "unit": "device dispatches per generated token (pinned burst)",
            "vs_baseline": round(pin / max(dpt, 1e-9), 3),
        }))
    except Exception as e:  # noqa: BLE001
        print(json.dumps({"metric": "serve_dispatches_per_token",
                          "value": None, "unit": "dispatches/token",
                          "error": str(e)[:200]}))


def _flight_report(trace_out, trace_t0):
    """--trace out.json: export the measured section's flight recording
    and print the wait/dispatch breakdown (shared bench.flight_report)."""
    if not trace_out:
        return  # keep the default path free of bench.py's jax import
    from bench import flight_report
    flight_report(trace_out, trace_t0)


# metrics whose vs_baseline is NOT a vs-reference ratio (self-relative
# speedup, or a tracking scenario with no reference analog): reported,
# but excluded from the worst-ratio gate line
_NON_GATING = {"compiled_dag_roundtrip", "async_burst"}


def _report(results):
    worst = 1e9
    for name, (value, base) in results.items():
        ratio = value / base
        if name not in _NON_GATING:
            worst = min(worst, ratio)
        print(json.dumps({
            "metric": name, "value": round(float(value), 2),
            "unit": ("ops/s (vs uncompiled actor chain)"
                     if name == "compiled_dag_roundtrip"
                     else "GiB/s" if "gigabytes" in name else "ops/s"),
            "vs_baseline": round(ratio, 3),
        }))
    print(json.dumps({
        "metric": "core_microbench_worst_ratio",
        "value": round(worst, 3),
        "unit": "min(ours/reference) across metrics",
        "vs_baseline": round(worst, 3),
    }))


if __name__ == "__main__":
    import sys
    argv = sys.argv[1:]
    out = None
    if "--trace" in argv:
        # lazy: importing bench pulls jax; only pay it when tracing
        from bench import trace_arg
        out = trace_arg(argv)
    main(quick="--quick" in argv, trace_out=out)
