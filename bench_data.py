"""Data-plane benchmark: streaming executor vs naive task-per-batch.

Prints one JSON line per metric ({"metric", "value", "unit",
"vs_baseline"} — the bench_core.py/bench_rl.py format), interleaved
A/B reps because this box's perf swings:

  data_streaming_throughput      blocks/s through a map_batches pipeline
      driven by the streaming executor (stage actors on sealed channels)
      vs the task-per-block executor on the SAME plan; vs_baseline =
      streaming/task blocks/s ratio (>= 1 means the channel plane pays
      for itself). The unit string carries the counter-verified
      dispatches/block for both paths (rtpu_data_* — streaming issues
      one run_loop call per stage worker for the whole run, the task
      path pays >= 1 dispatch per block by construction).
  data_streaming_peak_store_bytes   peak store occupancy while streaming
      a SKEWED-block-size workload through a deliberately slow consumer:
      credit backpressure parks producers at the ring limit, so the peak
      stays bounded while the task executor's submission window keeps
      max_tasks_in_flight whole blocks materialized; vs_baseline =
      task_peak/streaming_peak (>= 1 means streaming holds less).

``--quick``: fewer/shorter reps; same line format (wired into the test
suite as a slow-marked smoke so the bench itself can't rot).
``--trace out.json``: flight-record the measured section (stage spans,
per-block seal->wake flow arrows) via the shared bench.flight_report.
"""
import json
import os
import statistics
import sys
import time


def _counters():
    from ray_tpu.data.streaming import metrics_summary
    out = {}
    for path, rec in metrics_summary().get("path", {}).items():
        out[path] = (rec.get("blocks", 0.0), rec.get("dispatches", 0.0))
    return out


def _pipeline(n_rows: int, n_blocks: int):
    import numpy as np

    from ray_tpu import data

    def work(batch):
        # a small but real per-block compute so the bench measures the
        # data plane against useful work, not empty plumbing
        x = np.asarray(batch["id"], np.float64)
        for _ in range(4):
            x = np.sqrt(x * x + 1.0)
        return {"id": batch["id"], "y": x}

    return data.range(n_rows, override_num_blocks=n_blocks) \
        .map_batches(work)


def run_throughput(streaming: bool, n_rows: int, n_blocks: int) -> float:
    """One measured pass: blocks/s consuming the pipeline end to end."""
    ds = _pipeline(n_rows, n_blocks)
    ds._ctx.streaming_executor = "force" if streaming else "off"
    t0 = time.perf_counter()
    blocks = sum(1 for _ in ds.iter_batches(batch_size=None))
    dt = time.perf_counter() - t0
    assert blocks == n_blocks, (blocks, n_blocks)
    return blocks / dt


def run_skew_peak(streaming: bool, n_blocks: int,
                  rows_small: int, rows_big: int) -> int:
    """Peak store bytes streaming a skewed workload through a slow
    consumer (the memory-under-skew acceptance)."""
    import numpy as np

    from ray_tpu import data
    from ray_tpu.core.api import _runtime

    store = _runtime().store

    def make_read(i):
        rows = rows_big if i % 4 == 0 else rows_small
        def read(rows=rows, i=i):
            import numpy as _np
            import pyarrow as pa
            return pa.table({"x": _np.zeros(rows, _np.float64) + i})
        return read

    from ray_tpu.data.dataset import Dataset
    from ray_tpu.data.executor import Read
    ds = Dataset(Read([make_read(i) for i in range(n_blocks)]))
    ds._ctx.streaming_executor = "force" if streaming else "off"
    base = store.bytes_in_use()
    peak = 0
    for batch in ds.iter_batches(batch_size=None):
        peak = max(peak, store.bytes_in_use() - base)
        time.sleep(0.02)   # the slow consumer: producers must park
    return peak


def main(quick: bool = False):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu as ray
    from bench import flight_report, repin_jax_platforms, trace_arg
    repin_jax_platforms()

    reps = 2 if quick else 4
    n_rows = 40_000 if quick else 400_000
    n_blocks = 24 if quick else 64
    ray.init(num_cpus=float(max(os.cpu_count() or 2, 3)),
             object_store_memory=512 << 20)
    trace_t0 = time.monotonic_ns()

    # warmup both paths (worker spawn, imports)
    run_throughput(True, n_rows // 4, max(4, n_blocks // 4))
    run_throughput(False, n_rows // 4, max(4, n_blocks // 4))

    before = _counters()
    chan, task = [], []
    for _ in range(reps):
        chan.append(run_throughput(True, n_rows, n_blocks))
        task.append(run_throughput(False, n_rows, n_blocks))
    after = _counters()
    mc, mt = statistics.median(chan), statistics.median(task)

    def dpb(path: str) -> float:
        b0, d0 = before.get(path, (0.0, 0.0))
        b1, d1 = after.get(path, (0.0, 0.0))
        return (d1 - d0) / max(b1 - b0, 1e-9)

    print(json.dumps({
        "metric": "data_streaming_throughput",
        "value": round(mc, 1),
        "unit": (f"blocks/s streaming executor (task-per-block="
                 f"{mt:.1f}; dispatches/block chan={dpb('chan'):.3f} vs "
                 f"task={dpb('task'):.3f}; {n_blocks} blocks x "
                 f"{n_rows // n_blocks} rows, medians of {reps} "
                 f"interleaved reps, {os.cpu_count()} host cores)"),
        "vs_baseline": round(mc / max(mt, 1e-9), 3),
    }))

    skew_blocks = 16 if quick else 32
    small, big = (20_000, 400_000) if quick else (50_000, 1_000_000)
    speak, tpeak = [], []
    for _ in range(max(1, reps // 2)):
        speak.append(run_skew_peak(True, skew_blocks, small, big))
        tpeak.append(run_skew_peak(False, skew_blocks, small, big))
    ms, mt2 = statistics.median(speak), statistics.median(tpeak)
    from ray_tpu.data import DataContext
    window = DataContext.get_current().max_tasks_in_flight
    print(json.dumps({
        "metric": "data_streaming_peak_store_bytes",
        "value": int(ms),
        "unit": (f"peak store bytes, skewed blocks ({big}/{small} rows "
                 f"1:3), slow consumer; task-executor peak={int(mt2)} "
                 f"(window={window} blocks)"),
        "vs_baseline": round(mt2 / max(ms, 1.0), 3),
    }))

    flight_report(trace_arg(sys.argv), trace_t0)
    ray.shutdown()


if __name__ == "__main__":
    main(quick="--quick" in sys.argv[1:])
