"""Attention-kernel microbench: paged attention dispatch across the
three program families (prefill chunk / verify window / decode), at a
LONG block table (the regime ROADMAP item 1 targets).

Prints one JSON line per metric (folded into ``bench_trend.py
--history`` like every BENCH*_r* artifact):

- ``kernel_<family>_full_ms`` / ``kernel_<family>_bucket_ms`` — the
  plain-JAX fallback's per-dispatch wall at the full block-table width
  vs the power-of-two page bucket covering the live length
  (paged_engine._page_bucket). ``vs_baseline`` on the bucket metric is
  full/bucket (>1 = bucketing wins). CPU-meaningful: the fallback IS
  the CPU path.
- ``kernel_prefill_ttft_ratio`` — engine-level: median time-to-first-
  token for a short prompt on a max_pages=64 engine, page_buckets off
  vs auto, interleaved in-process (ABAB) so host noise hits both arms.
- on TPU additionally ``ragged_kernel_<family>_ms`` — the real Pallas
  ragged kernel per dispatch (on CPU the kernel only runs under
  interpret=True, whose wall measures the interpreter, so it is
  skipped).

``--quick`` shrinks reps to a smoke (wired as a slow-marked test).
"""
import json
import statistics
import sys
import time

import numpy as np

from bench import repin_jax_platforms


def _timed_ms(fn, reps):
    """Median per-call wall (ms); fn must block until the result is
    materialized."""
    out = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        out.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(out)


def _emit(metric, value, unit, vs=None):
    print(json.dumps({"metric": metric, "value": round(float(value), 4),
                      "unit": unit, "vs_baseline":
                      None if vs is None else round(float(vs), 4)}))


def _family_benches(quick: bool, on_tpu: bool):
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    cfg = llama.llama_tiny(vocab_size=512, dim=128, n_layers=2, n_heads=8,
                           n_kv_heads=4, mlp_dim=256, max_seq_len=1024)
    page, maxp, P = 16, 64, 128
    live_pages = 8                       # true length: 8 of 64 pages
    params = llama.init(jax.random.PRNGKey(0), cfg)
    caches = llama.init_paged_cache(cfg, P, page)
    rng = np.random.RandomState(0)
    reps = 5 if quick else 15
    bt_full = np.zeros((maxp,), np.int32)
    bt_full[:live_pages] = rng.permutation(np.arange(1, live_pages + 1))

    impl = "pallas ragged kernel" if on_tpu else "jnp fallback"

    def run_family(family, width):
        bt = jnp.asarray(bt_full[:width][None])
        if family == "prefill":
            chunk = jnp.asarray(rng.randint(1, 500, (1, 2 * page)),
                                jnp.int32)
            start = jnp.int32((live_pages - 2) * page)

            @jax.jit
            def fn(c):
                lg, _ = llama.prefill_paged_chunk(
                    params, chunk, c, bt[0], start, cfg, page_size=page)
                return lg
        elif family == "verify":
            toks = jnp.asarray(rng.randint(1, 500, (1, 8)), jnp.int32)
            starts = jnp.asarray([(live_pages - 1) * page + 2], jnp.int32)

            @jax.jit
            def fn(c):
                lg, _ = llama.verify_paged_rows(
                    params, toks, c, bt, starts, cfg, page_size=page)
                return lg
        else:                            # decode
            toks = jnp.asarray(rng.randint(1, 500, (1, 1)), jnp.int32)
            lens = jnp.asarray([(live_pages - 1) * page + 3], jnp.int32)

            @jax.jit
            def fn(c):
                lg, _ = llama.decode_paged(
                    params, toks, c, bt, lens, cfg, page_size=page)
                return lg
        np.asarray(fn(caches))           # compile outside the timed region
        return _timed_ms(lambda: np.asarray(fn(caches)), reps)

    for family in ("prefill", "verify", "decode"):
        full = run_family(family, maxp)
        bucket = run_family(family, live_pages)
        _emit(f"kernel_{family}_full_ms", full, f"ms/dispatch {impl}, "
              f"64-page table, {live_pages} live")
        _emit(f"kernel_{family}_bucket_ms", bucket,
              f"ms/dispatch {impl}, {live_pages}-page bucket",
              vs=full / bucket if bucket else None)
    if on_tpu:
        from ray_tpu.ops.ragged_paged_attention import ragged_paged_attention
        q = jnp.asarray(rng.randn(1, 2 * page, cfg.n_heads, cfg.head_dim),
                        jnp.float32)
        kp, vp = caches[0]["k"], caches[0]["v"]
        bt = jnp.asarray(bt_full[None])
        starts = jnp.asarray([(live_pages - 2) * page], jnp.int32)
        qlens = jnp.asarray([2 * page], jnp.int32)
        fn = jax.jit(lambda: ragged_paged_attention(
            q, kp, vp, bt, starts, qlens))
        np.asarray(fn())
        _emit("ragged_kernel_prefill_ms",
              _timed_ms(lambda: np.asarray(fn()), reps),
              "ms/call Pallas ragged kernel, 32q x 8 live pages")


def _engine_ttft(quick: bool):
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama

    def mk(buckets):
        return PagedInferenceEngine(PagedEngineConfig(
            model=llama.llama_tiny(vocab_size=512, max_seq_len=1024),
            max_batch_size=2, page_size=16, num_pages=256,
            max_pages_per_seq=64, chunk_size=32, page_buckets=buckets),
            rng_seed=0)

    rng = np.random.RandomState(1)
    prompt = list(rng.randint(1, 500, (24,)))
    sp = SamplingParams(max_tokens=1)
    eng_off, eng_on = mk("off"), mk("auto")
    for e in (eng_off, eng_on):          # compile both arms' programs
        e.generate([prompt], sp)
    reps = 3 if quick else 9
    offs, ons = [], []
    for _ in range(reps):                # interleaved: noise hits both
        t0 = time.perf_counter()
        eng_off.generate([prompt], sp)
        offs.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        eng_on.generate([prompt], sp)
        ons.append(time.perf_counter() - t0)
    off_med, on_med = statistics.median(offs), statistics.median(ons)
    _emit("kernel_prefill_ttft_full_ms", off_med * 1e3,
          "ms short-prompt TTFT, 64-page table, buckets off")
    _emit("kernel_prefill_ttft_ratio", off_med / on_med,
          "buckets-off / buckets-auto median TTFT (>1 = bucketing wins)",
          vs=off_med / on_med)


def main(quick: bool = False):
    repin_jax_platforms()
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    _family_benches(quick, on_tpu)
    _engine_ttft(quick)


if __name__ == "__main__":
    main(quick="--quick" in sys.argv[1:])
