"""RL rollout benchmark: Sebulba env-steps/s scaling + transport A/B.

Prints one JSON line per metric ({"metric", "value", "unit",
"vs_baseline"} — the bench_core.py/bench_serve.py format):

  rl_sebulba_env_steps_scaling   1 vs N env-runner actors on a
      LATENCY-BOUND env (CartPole + a fixed per-step delay — the env
      class actor scaling exists for: game servers / simulators whose
      step time dominates; a pure-compute env on a small host measures
      core count, not the substrate), medians over interleaved reps
      (this box's perf swings, so only interleaved medians are
      comparable); vs_baseline = ratio / 2.5 (the acceptance bar:
      >= 2.5x from 1 -> 4 actors)
  rl_fragment_transport_ab       sealed-channel RolloutQueue vs one
      actor call per fragment, same runner count, interleaved;
      vs_baseline = chan/actor env-steps/s ratio (>= 1 means the
      channel plane pays for itself) — the unit string carries the
      counter-verified dispatches/fragment for both transports
  rl_anakin_env_steps            fused jitted env+update throughput on
      the host mesh (tracking scenario, no reference baseline)

``--quick``: fewer/shorter reps; same line format (wired into the test
suite as a slow-marked smoke so the bench itself can't rot).

``--trace out.json``: flight-record the measured cluster section
(core/flight.py — fragment seal/wake, credit waits, weight pub/fetch)
and print a wait/dispatch breakdown line; opens in Perfetto.
"""
import json
import os
import statistics
import sys
import time


def _delayed_env():
    """CartPole with a fixed per-step delay: stands in for the env class
    Sebulba actor scaling targets (env servers, simulators, anything
    whose step latency dominates the runner's loop)."""
    import gymnasium as gym

    class DelayedStep(gym.Wrapper):
        def step(self, action):
            time.sleep(0.002)
            return self.env.step(action)

    return DelayedStep(gym.make("CartPole-v1"))


def _transport_counters():
    from ray_tpu.rl.podracer import metrics_summary
    out = {}
    for tr, rec in metrics_summary().get("transport", {}).items():
        out[tr] = (rec.get("fragments", 0.0), rec.get("dispatches", 0.0))
    return out


def run_sebulba(num_runners: int, transport: str, iters: int,
                rollout_len: int = 32, num_envs: int = 4,
                env=None) -> float:
    """One measured Sebulba session: returns steady-state env-steps/s
    (wall time over `iters` iterations, after a warmup iteration that
    absorbs actor spawn + jit compile)."""
    from ray_tpu.rl.podracer import SebulbaConfig, SebulbaTrainer
    cfg = SebulbaConfig(
        env=env if env is not None else "CartPole-v1",
        num_env_runners=num_runners, num_envs_per_runner=num_envs,
        rollout_len=rollout_len, ring=2, transport=transport,
        runner_resources={"CPU": 0.25})
    trainer = SebulbaTrainer(cfg)
    try:
        trainer.train(timeout_s=180)    # warmup: spawn + compile
        t0 = time.perf_counter()
        steps = 0
        for _ in range(iters):
            r = trainer.train(timeout_s=180)
        steps = (r["num_env_steps_sampled_lifetime"]
                 - num_runners * num_envs * rollout_len)
        return steps / (time.perf_counter() - t0)
    finally:
        trainer.stop(timeout_s=10)


def main(quick: bool = False):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # before any jax import: the anakin scenario shards over a
        # virtual host mesh, like the test suite's 8-device CPU mesh
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import ray_tpu as ray
    from ray_tpu.core.config import cfg as rcfg

    reps = 2 if quick else 3
    iters = 2 if quick else 6
    scale_n = 4
    rcfg.override(worker_prestart=scale_n)
    ray.init(num_cpus=float(max(os.cpu_count() or 2, scale_n + 1)),
             object_store_memory=512 << 20)
    trace_t0 = time.monotonic_ns()

    # ---- scaling: 1 vs N runners on the latency-bound env -------------- #
    ones, ns = [], []
    for _ in range(reps):
        ones.append(run_sebulba(1, "chan", iters, env=_delayed_env))
        ns.append(run_sebulba(scale_n, "chan", iters, env=_delayed_env))
    m1, mn = statistics.median(ones), statistics.median(ns)
    ratio = mn / max(m1, 1e-9)
    print(json.dumps({
        "metric": "rl_sebulba_env_steps_scaling",
        "value": round(ratio, 3),
        "unit": (f"x env-steps/s 1->{scale_n} env-runner actors, 2ms-step"
                 f" env (1r={m1:.0f} sps, {scale_n}r={mn:.0f} sps; medians"
                 f" of {reps} interleaved reps, {os.cpu_count()} host "
                 f"cores)"),
        "vs_baseline": round(ratio / 2.5, 3),
    }))

    # ---- transport A/B: sealed channel vs actor call per fragment ------ #
    ab_runners = 2
    chan, actor = [], []
    before = _transport_counters()
    for _ in range(reps):
        chan.append(run_sebulba(ab_runners, "chan", iters))
        actor.append(run_sebulba(ab_runners, "actor", iters))
    after = _transport_counters()
    mc, ma = statistics.median(chan), statistics.median(actor)

    def dpf(tr: str) -> float:
        f0, d0 = before.get(tr, (0.0, 0.0))
        f1, d1 = after.get(tr, (0.0, 0.0))
        return (d1 - d0) / max(f1 - f0, 1e-9)

    print(json.dumps({
        "metric": "rl_fragment_transport_ab",
        "value": round(mc, 1),
        "unit": (f"env-steps/s sealed-channel transport (actor-call="
                 f"{ma:.0f} sps; dispatches/fragment chan={dpf('chan'):.3f}"
                 f" vs actor={dpf('actor'):.3f}; {ab_runners} runners, "
                 f"medians of {reps} interleaved reps)"),
        "vs_baseline": round(mc / max(ma, 1e-9), 3),
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    ray.shutdown()

    # ---- anakin: fused jitted env+update on the host mesh -------------- #
    try:
        from ray_tpu.rl.podracer import AnakinConfig, AnakinTrainer
        acfg = AnakinConfig(batch_per_device=8 if quick else 32,
                            rollout_len=16)
        tr = AnakinTrainer(acfg)
        tr.train()                              # compile
        rates = []
        for _ in range(3 if quick else 10):
            rates.append(tr.train()["env_steps_per_sec"])
        rate = statistics.median(rates)
        print(json.dumps({
            "metric": "rl_anakin_env_steps",
            "value": round(rate, 1),
            "unit": (f"env-steps/s fused jitted env+update "
                     f"({tr._num_devices}-device host mesh, "
                     f"{acfg.batch_per_device} envs/device)"),
            "vs_baseline": None,
        }))
    except Exception as e:  # noqa: BLE001 — the hedge must never fail the bench
        print(json.dumps({"metric": "rl_anakin_env_steps", "value": None,
                          "unit": "env-steps/s", "error": str(e)[:200]}))


if __name__ == "__main__":
    main(quick="--quick" in sys.argv[1:])
