"""Serving benchmark: p50 TTFT + decode throughput of the paged engine.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.
North-star (BASELINE.json config 4): p50 TTFT < 200 ms with continuous
batching — vs_baseline = 0.2 / p50_s (>= 1.0 passes).

Workload: a burst of requests with mixed prompt lengths arrives at once
(worst case for TTFT: every prompt queues behind running decodes); chunked
prefill bounds how long any decode step stalls.

``--metrics``: after the run, print a second JSON line with
``serve.metrics_summary()`` (histogram-derived p50/p95/p99 TTFT,
inter-token, queue wait, KV utilization, token/request counters) — the
telemetry the engines recorded via ray_tpu.util.metrics during the burst.

``--shared-prefix``: run the prefix-cache scenario instead — a burst of
requests sharing one long system prompt with varied tails, caching on vs
off; reports hit rate, prompt tokens saved, and the TTFT delta the cache
buys (paged_engine.py enable_prefix_caching).

``--long-tail``: session-replay scenario for the cache heat plane —
Zipf-distributed sessions whose combined prefix working set is a
multiple of the page pool, so hot sessions stay cached while the long
tail churns through eviction. Emits a warm-TTFT + hit-rate line and a
per-chain heat-histogram line (fold both with ``bench_trend
--history``), counter-verified: per-chain totals == engine aggregates
== flushed ``rtpu_llm_prefix_cache_*`` counters. This is ROADMAP item
4's success-metric harness.

``--long-tail --tiered``: adds an A/B arm replaying the bit-identical
request stream with ``kv_spill`` on (host tier budget 10x the device
pool): evicted prefixes demote to the host spill tier and promote
back on revisit instead of re-prefilling cold. Asserts the two arms'
greedy outputs match token-for-token and counter-verifies the tiered
arm against ``rtpu_llm_prefix_spill_*`` and
``metrics_summary()["cache"]["spill"]``; emits a third JSON line with
the tiered hit rate (vs_baseline = tiered / untiered hit rate).

``--mesh-tp N``: tensor-parallel serving A/B — the same paged engine
single-chip vs sharded over a tp=N NamedSharding mesh
(PagedEngineConfig.mesh). Asserts greedy outputs are token-identical
across arms and that steady-state decode does ZERO involuntary
reshards (the engine's mesh_reshard_bytes counter stays 0: every
committed buffer still carries its pinned sharding after each
dispatch); reports tokens/s + TTFT for both arms and the accounted
host<->device transfer bytes (token ids in, tokens/logits out — the
only bytes that should move). On CPU the mesh is virtual
(forced-host-platform devices), so the ratio measures overhead, not
speedup.

``--pd-chan``: prefill/decode disaggregation handoff A/B — the PDProxy
actor-call handoff (one control dispatch carrying the payload ref per
request) vs the sealed-channel ring (PR 10's RingWriter; KV payloads
seal into shm, the decode replica's drain thread imports them, credit
backpressure throttles prefill admission). Asserts token-identical
outputs across arms and reports handoff control dispatches per KV
payload: the channel arm pays only the per-pair wiring calls,
amortized to ~0 over the request stream.

``--trace out.json``: flight-record the measured section (core/flight.py)
and print a wait/dispatch breakdown JSON line next to the numbers; the
trace file opens in Perfetto/chrome://tracing.
"""
import json
import sys
import time

import jax
import numpy as np


def main():
    if "--shared-prefix" in sys.argv:
        return _shared_prefix()
    if "--long-tail" in sys.argv:
        return _long_tail()
    if "--decode-plan" in sys.argv:
        return _decode_plan()
    if "--soak" in sys.argv:
        return _soak()
    if "--multi-tenant" in sys.argv:
        return _multi_tenant()
    if "--mesh-tp" in sys.argv:
        return _mesh_tp(int(sys.argv[sys.argv.index("--mesh-tp") + 1]))
    if "--pd-chan" in sys.argv:
        return _pd_chan()
    from bench import _probe_accelerator, repin_jax_platforms
    repin_jax_platforms()
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama

    if not _probe_accelerator():
        print(json.dumps({
            "metric": "serve_p50_ttft", "value": None, "unit": "seconds",
            "vs_baseline": None,
            "error": "accelerator unreachable (tunnel probe timed out)",
        }))
        raise SystemExit(3)

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        model = llama.LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, mlp_dim=4096, max_seq_len=2048,
            dtype=jax.numpy.bfloat16, remat=False, use_flash=False)
        # 32 slots: the whole burst admits at once (page pool holds
        # 65k tokens, the burst peaks at ~10k: 7680 prompt + 2048
        # decode); prefill_rows=8 packs the burst's ~45 chunks into ~6
        # dispatches
        cfg = PagedEngineConfig(
            model=model, max_batch_size=32, page_size=64, num_pages=1024,
            max_pages_per_seq=32, chunk_size=256, prefill_rows=8)
        n_requests, max_tokens = 32, 64
        prompt_lens = [64, 128, 256, 512]
    else:  # CPU smoke — numbers not meaningful
        model = llama.llama_tiny(vocab_size=258, max_seq_len=256)
        cfg = PagedEngineConfig(
            model=model, max_batch_size=4, page_size=8, num_pages=128,
            max_pages_per_seq=16, chunk_size=16)
        n_requests, max_tokens = 6, 8
        prompt_lens = [16, 32]

    eng = PagedInferenceEngine(cfg, rng_seed=0)
    rng = np.random.RandomState(0)

    # deploy-time warmup (vLLM-style): compile every program family the
    # burst will dispatch — a single mid-burst XLA compile costs tens of
    # requests' worth of TTFT on a remote-attached accelerator
    warm_s = eng.warmup()
    warm = eng.generate(
        [list(rng.randint(1, model.vocab_size, (prompt_lens[0],)))],
        SamplingParams(max_tokens=4))
    assert warm[0]["token_ids"]

    prompts = [list(rng.randint(1, model.vocab_size,
                                (prompt_lens[i % len(prompt_lens)],)))
               for i in range(n_requests)]
    sp = SamplingParams(max_tokens=max_tokens)

    trace_t0 = time.monotonic_ns()
    t0 = time.perf_counter()
    reqs = [eng.submit(p, sp) for p in prompts]
    while not all(r.done for r in reqs):
        eng.step()
    wall = time.perf_counter() - t0

    ttfts = sorted(r.first_token_t - r.submit_t for r in reqs)
    p50 = ttfts[len(ttfts) // 2]
    p99 = ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))]
    gen_tokens = sum(len(r.out_ids) for r in reqs)
    print(json.dumps({
        "metric": "serve_ttft_p50",
        "value": round(p50, 4),
        "unit": (f"s (p99={p99:.3f}s, {gen_tokens / wall:.0f} gen tok/s, "
                 f"{n_requests} reqs burst, warmup={warm_s:.1f}s, "
                 f"{jax.devices()[0].platform})"),
        "vs_baseline": round(0.2 / max(p50, 1e-9), 4),
    }))

    if "--metrics" in sys.argv:
        from ray_tpu.serve.metrics import metrics_summary
        print(json.dumps({"metric": "serve_metrics_summary",
                          "value": metrics_summary()}, default=str))

    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)

    _pd_interference(model, cfg, rng, max_tokens, prompt_lens, on_tpu)


def _shared_prefix():
    """Prefix-cache scenario: one shared system prompt + per-request
    tails (the dominant production traffic shape — system prompts,
    few-shot templates, multi-turn histories). Runs the identical burst
    with ``enable_prefix_caching`` on and off and prints ONE JSON line:
    TTFT p50 with caching on, the off-run p50, the cache hit rate and
    prompt tokens not recomputed. vs_baseline = p50_off / p50_on
    (>= 1.0 means caching pays for itself)."""
    import dataclasses

    from bench import _probe_accelerator, repin_jax_platforms
    repin_jax_platforms()
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama

    if not _probe_accelerator():
        print(json.dumps({
            "metric": "serve_prefix_cache_ttft_p50", "value": None,
            "unit": "seconds", "vs_baseline": None,
            "error": "accelerator unreachable (tunnel probe timed out)",
        }))
        raise SystemExit(3)

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        model = llama.LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, mlp_dim=4096, max_seq_len=2048,
            dtype=jax.numpy.bfloat16, remat=False, use_flash=False)
        cfg = PagedEngineConfig(
            model=model, max_batch_size=16, page_size=64, num_pages=1024,
            max_pages_per_seq=32, chunk_size=256, prefill_rows=8)
        n_requests, max_tokens, sys_len, tail_len = 16, 32, 1024, 64
    else:  # CPU smoke — numbers not meaningful
        model = llama.llama_tiny(vocab_size=258, max_seq_len=640)
        cfg = PagedEngineConfig(
            model=model, max_batch_size=8, page_size=16, num_pages=512,
            max_pages_per_seq=24, chunk_size=64)
        n_requests, max_tokens, sys_len, tail_len = 8, 8, 256, 16

    rng = np.random.RandomState(0)
    system = list(rng.randint(1, model.vocab_size, (sys_len,)))
    prompts = [system + list(rng.randint(1, model.vocab_size, (tail_len,)))
               for _ in range(n_requests)]
    sp = SamplingParams(max_tokens=max_tokens)

    def run(enable):
        eng = PagedInferenceEngine(
            dataclasses.replace(cfg, enable_prefix_caching=enable),
            rng_seed=0)
        eng.warmup()
        # warm the cache the way production traffic does: one request
        # with the shared system prompt has already been served
        eng.generate([system + [1] * 4], SamplingParams(max_tokens=2))
        t0 = time.perf_counter()
        reqs = [eng.submit(p, sp) for p in prompts]
        while not all(r.done for r in reqs):
            eng.step()
        wall = time.perf_counter() - t0
        ttfts = sorted(r.first_token_t - r.submit_t for r in reqs)
        outs = [list(r.out_ids) for r in reqs]
        return ttfts[len(ttfts) // 2], wall, eng.pool_stats(), outs

    trace_t0 = time.monotonic_ns()
    p50_on, wall_on, st, outs_on = run(True)
    p50_off, wall_off, _, outs_off = run(False)
    assert outs_on == outs_off, "prefix caching changed greedy outputs"
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    print(json.dumps({
        "metric": "serve_prefix_cache_ttft_p50",
        "value": round(p50_on, 4),
        "unit": (f"s (off={p50_off:.4f}s, hit_rate="
                 f"{st['prefix_hit_rate']:.3f}, tokens_saved="
                 f"{st['prefix_tokens_saved']}, wall {wall_on:.2f}s vs "
                 f"{wall_off:.2f}s off, {n_requests} reqs x {sys_len}-tok "
                 f"shared prefix, {jax.devices()[0].platform})"),
        "vs_baseline": round(p50_off / max(p50_on, 1e-9), 4),
    }))


def _long_tail():
    """Cache heat plane scenario: N sessions, request popularity drawn
    Zipf(alpha) so a few sessions dominate while a long tail barely
    repeats; every session's prefix is distinct and the combined
    working set is a multiple of the page pool, forcing the cache to
    keep the hot head resident and churn the tail through eviction.
    Reports the hit rate and warm-vs-cold TTFT (vs_baseline =
    cold_p50 / warm_p50 — what cache residency buys a revisited
    session), plus a per-chain heat histogram. Before printing, the
    per-chain table is counter-verified against the engine aggregates
    AND the flushed rtpu_llm_prefix_cache_* metric store — one page
    event, one attribution, no drift."""
    from bench import _probe_accelerator, repin_jax_platforms
    repin_jax_platforms()
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm import telemetry
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama
    from ray_tpu.util.metrics import collect_store

    if not _probe_accelerator():
        print(json.dumps({
            "metric": "serve_longtail_warm_ttft_p50", "value": None,
            "unit": "seconds", "vs_baseline": None,
            "error": "accelerator unreachable (tunnel probe timed out)",
        }))
        raise SystemExit(3)

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        model = llama.LlamaConfig(
            vocab_size=32000, dim=1024, n_layers=8, n_heads=16,
            n_kv_heads=8, mlp_dim=4096, max_seq_len=2048,
            dtype=jax.numpy.bfloat16, remat=False, use_flash=False)
        cfg = PagedEngineConfig(
            model=model, max_batch_size=16, page_size=64, num_pages=512,
            max_pages_per_seq=16, chunk_size=256, prefill_rows=8)
        n_sessions, n_requests = 96, 400
        prefix_len, tail_len, max_tokens = 512, 64, 8
    else:  # CPU smoke — numbers not meaningful, the shape is
        model = llama.llama_tiny(vocab_size=258, max_seq_len=256)
        cfg = PagedEngineConfig(
            model=model, max_batch_size=4, page_size=8, num_pages=192,
            max_pages_per_seq=16, chunk_size=32)
        n_sessions, n_requests = 72, 300
        prefix_len, tail_len, max_tokens = 64, 8, 4
    alpha = 1.1
    tiered = "--tiered" in sys.argv
    # working set: every session's prefix pages + a decode page; the
    # pool holds a fraction of it, so residency is earned by heat
    pages_per_prefix = prefix_len // cfg.page_size
    working_set = n_sessions * pages_per_prefix

    trace_t0 = time.monotonic_ns()

    def _run_arm(acfg, spill_budget_pages=None):
        # fresh rng per arm, same seed: every arm replays a
        # bit-identical session/order/tail stream
        rng = np.random.RandomState(0)
        sessions = [list(rng.randint(1, model.vocab_size,
                                     (prefix_len,)))
                    for _ in range(n_sessions)]
        # Zipf-ranked popularity over the session ids
        weights = 1.0 / np.arange(1, n_sessions + 1) ** alpha
        weights /= weights.sum()
        order = rng.choice(n_sessions, size=n_requests, p=weights)
        arm = PagedInferenceEngine(acfg, rng_seed=0)
        if spill_budget_pages is not None:
            arm.spill.max_bytes = (spill_budget_pages
                                   * arm.spill.page_nbytes)
        arm.warmup()
        sp = SamplingParams(max_tokens=max_tokens, temperature=0.0)
        seen: set = set()
        warm, cold, outs = [], [], []
        t0 = time.perf_counter()
        for sid in order:
            ids = sessions[sid] + list(
                rng.randint(1, model.vocab_size, (tail_len,)))
            r = arm.submit(ids, sp)
            while not r.done:
                arm.step()
            ttft = r.first_token_t - r.submit_t
            (warm if sid in seen else cold).append(ttft)
            seen.add(sid)
            outs.append(tuple(r.out_ids))
        arm_wall = time.perf_counter() - t0
        # force a final telemetry publish (chain gauges rate-limited)
        arm._chain_ship_t = 0.0
        telemetry.on_step(arm)
        return arm, warm, cold, outs, arm_wall

    eng, warm_ttfts, cold_ttfts, outs_u, wall = _run_arm(cfg)

    # -- counter verification: table == engine.stats == metric store -- #
    st, totals = eng.stats, eng.chains.totals()
    for tk, sk in (("hits", "prefix_hits"), ("misses", "prefix_misses"),
                   ("evictions", "prefix_evictions"),
                   ("tokens_saved", "prefix_tokens_saved")):
        assert totals[tk] == st[sk], \
            f"chain-table drift: {tk}={totals[tk]} vs {sk}={st[sk]}"
    store = collect_store()

    def _shipped(name):
        rec = store.get(name)
        return sum(rec["series"].values()) if rec else 0.0
    for name, sk in (
            ("rtpu_llm_prefix_cache_hits_total", "prefix_hits"),
            ("rtpu_llm_prefix_cache_misses_total", "prefix_misses"),
            ("rtpu_llm_prefix_cache_evictions_total",
             "prefix_evictions"),
            ("rtpu_llm_prefix_cache_tokens_saved_total",
             "prefix_tokens_saved")):
        assert int(_shipped(name)) == st[sk], \
            f"metric-store drift: {name}={_shipped(name)} vs {st[sk]}"

    acct = eng.prefix_accounting()
    warm_p50 = sorted(warm_ttfts)[len(warm_ttfts) // 2]
    cold_p50 = sorted(cold_ttfts)[len(cold_ttfts) // 2]
    print(json.dumps({
        "metric": "serve_longtail_warm_ttft_p50",
        "value": round(warm_p50, 4),
        "unit": (f"s (cold={cold_p50:.4f}s, hit_rate="
                 f"{acct['hit_rate']:.3f}, tokens_saved="
                 f"{acct['tokens_saved']}, evictions="
                 f"{acct['evictions']}, {n_requests} reqs over "
                 f"{n_sessions} zipf({alpha}) sessions, working set "
                 f"{working_set}p vs pool {cfg.num_pages}p, "
                 f"wall {wall:.1f}s, {jax.devices()[0].platform})"),
        "vs_baseline": round(cold_p50 / max(warm_p50, 1e-9), 4),
    }))
    # heat histogram: how concentrated cache value is across chains —
    # the shape tiering will exploit (spill the cold right half)
    rows = eng.chains.top(n_sessions)
    hist = {"buckets": [0, 1, 4, 16, 64, 256],
            "chains": [0] * 6, "hits": [0] * 6}
    for row in rows:
        b = sum(1 for lo in hist["buckets"][1:] if row["hits"] >= lo)
        hist["chains"][b] += 1
        hist["hits"][b] += row["hits"]
    print(json.dumps({
        "metric": "serve_longtail_heat_histogram",
        "value": hist,
        "unit": (f"chains/hits per hit-count bucket; tracked="
                 f"{eng.chains.stats()['tracked']}, overflow_assign="
                 f"{eng.chains.stats()['overflow_assignments']}, "
                 f"table_max_bytes={eng.chains.stats()['max_bytes']}"),
        "vs_baseline": None,
    }))

    if tiered:
        # A/B arm: same engine config + kv_spill on, host budget 10x
        # the device pool — evicted prefixes demote to the host tier
        # instead of dying, and a revisit promotes them back
        # (bit-identical pages) instead of re-prefilling cold.
        import dataclasses
        from ray_tpu.serve.metrics import metrics_summary
        budget_pages = 10 * cfg.num_pages
        teng, t_warm, t_cold, outs_t, t_wall = _run_arm(
            dataclasses.replace(cfg, kv_spill=True),
            spill_budget_pages=budget_pages)
        # promoted pages must be bit-identical to a cold prefill:
        # greedy outputs of the two arms match token-for-token
        assert outs_t == outs_u, \
            "tiered arm outputs diverged from untiered arm"
        # counter-verify the tiered arm: chain-table sums == engine
        # aggregates == live tier residence == shipped
        # rtpu_llm_prefix_spill_* store == metrics_summary() fold
        # (the untiered arm ships zero spill events, so the store's
        # spill rows are the tiered arm's alone)
        ts, ttot = teng.stats, teng.chains.totals()
        tacct = teng.prefix_accounting()
        assert ttot["spilled_pages"] == teng.spill.resident_pages()
        assert ttot["promotions"] == ts["spill_promotions"]
        assert tacct["spill_resident_pages"] == \
            teng.spill.resident_pages()
        assert tacct["spill_demotions"] == ts["spill_demotions"]
        store2 = collect_store()

        def _shipped2(name):
            rec = store2.get(name)
            return sum(rec["series"].values()) if rec else 0.0
        for name, sk in (
                ("rtpu_llm_prefix_spill_demotions_total",
                 "spill_demotions"),
                ("rtpu_llm_prefix_spill_promotions_total",
                 "spill_promotions"),
                ("rtpu_llm_prefix_spill_expired_total",
                 "spill_expired"),
                ("rtpu_llm_prefix_spill_pages_total", "spill_pages"),
                ("rtpu_llm_prefix_spill_bytes_total", "spill_bytes")):
            assert int(_shipped2(name)) == ts[sk], \
                f"spill metric drift: {name}={_shipped2(name)} " \
                f"vs {sk}={ts[sk]}"
        fold = metrics_summary()["cache"]["spill"]
        assert fold["demotions"] == ts["spill_demotions"]
        assert fold["promotions"] == ts["spill_promotions"]
        assert ts["spill_promotions"] > 0, \
            "tiered arm never promoted — scenario broken"
        t_warm_p50 = sorted(t_warm)[len(t_warm) // 2]
        t_cold_p50 = sorted(t_cold)[len(t_cold) // 2]
        hit_gain = tacct["hit_rate"] / max(acct["hit_rate"], 1e-9)
        print(json.dumps({
            "metric": "serve_longtail_tiered_hit_rate",
            "value": round(tacct["hit_rate"], 4),
            "unit": (f"hit rate with kv_spill on vs "
                     f"{acct['hit_rate']:.3f} untiered (spill budget "
                     f"{budget_pages}p = 10x pool, demotions="
                     f"{ts['spill_demotions']}, promotions="
                     f"{ts['spill_promotions']}, expired="
                     f"{ts['spill_expired']}, tokens_saved="
                     f"{tacct['tokens_saved']} vs "
                     f"{acct['tokens_saved']}, warm p50 "
                     f"{t_warm_p50:.4f}s vs {warm_p50:.4f}s, cold p50 "
                     f"{t_cold_p50:.4f}s vs {cold_p50:.4f}s, outputs "
                     f"bit-identical, wall {t_wall:.1f}s vs "
                     f"{wall:.1f}s, {jax.devices()[0].platform})"),
            "vs_baseline": round(hit_gain, 4),
        }))

    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)


def _decode_plan():
    """Static decode plan scenario: stream completions through a REAL
    serve deployment (handle -> replica -> engine) with the sealed-ring
    channel transport on vs the per-chunk stream_next poll transport,
    and report CONTROL-PLANE dispatches per streamed item — a count, not
    a time, so it is machine-independent. The plan's whole point is
    ~0 dispatches/token in steady state (one setup call per request);
    the poll transport pays roughly one actor call per chunk batch.
    Outputs are asserted identical across transports. CPU-only: device
    speed is irrelevant to dispatch economy, so no accelerator probe."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import cfg as rcfg
    from ray_tpu.llm.paged_engine import PagedEngineConfig
    from ray_tpu.llm.serving import LLMConfig, build_llm_deployment
    from ray_tpu.models import llama

    rcfg.override(worker_prestart=2)
    ray_tpu.init(num_cpus=2, object_store_memory=512 << 20)
    ecfg = PagedEngineConfig(
        model=llama.llama_tiny(vocab_size=258, max_seq_len=256),
        max_batch_size=4, page_size=8, num_pages=128,
        max_pages_per_seq=16, chunk_size=16)
    app = build_llm_deployment(
        LLMConfig(model_id="tiny", engine=ecfg, warmup=False))
    h = serve.run(app, name="decode-plan")
    hs = h.options(method_name="completions_stream", stream=True)
    prompts = ["the quick brown fox", "jumps over", "a lazy dog today",
               "serving tokens fast"]

    def run_mode(plan: bool):
        rcfg.override(serve_static_decode_plan=plan)
        outs = []
        for p in prompts:
            gen = hs.remote({"prompt": p, "max_tokens": 16,
                             "temperature": 0.0})
            outs.append("".join(c["choices"][0]["text"] for c in gen))
        return outs

    trace_t0 = time.monotonic_ns()
    outs_on = run_mode(True)
    outs_off = run_mode(False)
    assert outs_on == outs_off, \
        "static decode plan changed streamed outputs"

    from ray_tpu.serve.metrics import metrics_summary
    st = metrics_summary().get("stream", {})
    chan, poll = st.get("chan", {}), st.get("poll", {})
    chan_rate = chan.get("dispatches_per_item")
    poll_rate = poll.get("dispatches_per_item")
    print(json.dumps({
        "metric": "serve_stream_dispatches_per_token",
        "value": None if chan_rate is None else round(chan_rate, 4),
        "unit": (f"control dispatches per streamed item, static plan "
                 f"(poll transport={None if poll_rate is None else round(poll_rate, 4)}; "
                 f"chan {chan.get('dispatches', 0):.0f} disp/"
                 f"{chan.get('items', 0):.0f} items, poll "
                 f"{poll.get('dispatches', 0):.0f}/"
                 f"{poll.get('items', 0):.0f}; outputs identical)"),
        # >= 1 means the static plan beats polling; 'amortized zero'
        # shows up as a large ratio (setup-only vs per-chunk calls)
        "vs_baseline": (None if not chan_rate or poll_rate is None
                        else round(poll_rate / chan_rate, 3)),
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    serve.shutdown()
    ray_tpu.shutdown()


def _mesh_tp(tp: int):
    """Tensor-parallel serving A/B (see module docstring --mesh-tp)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={max(8, tp)}").strip()
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama

    if len(jax.devices()) < tp:
        print(json.dumps({
            "metric": "serve_mesh_tp_decode_tokens_per_s", "value": None,
            "unit": f"tok/s (need {tp} devices, have {len(jax.devices())})",
            "vs_baseline": None}))
        raise SystemExit(3)

    model = llama.llama_tiny(vocab_size=258, max_seq_len=256)
    base = dict(model=model, max_batch_size=4, page_size=8, num_pages=128,
                max_pages_per_seq=16, chunk_size=16)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 258, (n,))) for n in (16, 32, 24, 16)]
    sp = SamplingParams(max_tokens=24, temperature=0.0)

    def run_arm(mesh):
        eng = PagedInferenceEngine(
            PagedEngineConfig(mesh=mesh, **base), rng_seed=0)
        eng.warmup(families=("prefill", "decode"))
        t0 = time.perf_counter()
        outs = eng.generate(prompts, sp)
        wall = time.perf_counter() - t0
        toks = sum(len(o["token_ids"]) for o in outs)
        ttfts = sorted(o["ttft_s"] for o in outs)
        return (outs, toks / wall, ttfts[len(ttfts) // 2],
                dict(eng.stats))

    trace_t0 = time.monotonic_ns()
    outs1, tps1, ttft1, st1 = run_arm(None)
    outsN, tpsN, ttftN, stN = run_arm({"tp": tp})
    assert [o["token_ids"] for o in outs1] == \
        [o["token_ids"] for o in outsN], "mesh changed greedy outputs"
    assert stN["mesh_reshard_bytes"] == 0, \
        f"involuntary reshards: {stN['mesh_reshard_bytes']} bytes"
    assert st1["mesh_dispatches"] == 0  # off-mesh arm counts nothing
    print(json.dumps({
        "metric": "serve_mesh_tp_decode_tokens_per_s",
        "value": round(tpsN, 1),
        "unit": (f"tok/s on tp={tp} NamedSharding mesh (single-chip "
                 f"{tps1:.1f} tok/s; ttft p50 {ttftN:.4f}s vs "
                 f"{ttft1:.4f}s; outputs token-identical; "
                 f"{stN['mesh_dispatches']} dispatches moved "
                 f"{stN['mesh_input_bytes']}B in / "
                 f"{stN['mesh_output_bytes']}B out, reshard_bytes=0; "
                 f"{jax.devices()[0].platform} virtual mesh)"),
        "vs_baseline": round(tpsN / max(tps1, 1e-9), 3),
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)


def _pd_chan():
    """Sealed-channel PD handoff A/B (see module docstring --pd-chan)."""
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ray_tpu
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.llm.pd_disagg import build_pd_proxy
    from ray_tpu.models import llama

    ray_tpu.init(num_cpus=2, object_store_memory=512 << 20)
    model = llama.llama_tiny(vocab_size=258, max_seq_len=256)
    cfg = PagedEngineConfig(
        model=model, max_batch_size=4, page_size=8, num_pages=128,
        max_pages_per_seq=16, chunk_size=16)
    rng = np.random.RandomState(0)
    n_requests = 30
    prompts = [list(rng.randint(1, 258, (16 + (i % 3) * 8,)))
               for i in range(n_requests)]
    sp = SamplingParams(max_tokens=12, temperature=0.0)

    def run_arm(use_channels):
        proxy = build_pd_proxy(n_prefill=1, n_decode=1, engine_cfg=cfg,
                               use_channels=use_channels)
        t0 = time.perf_counter()
        outs = ray_tpu.get(
            [proxy.generate.remote(p, sp) for p in prompts], timeout=600)
        wall = time.perf_counter() - t0
        st = ray_tpu.get(proxy.proxy_stats.remote(), timeout=60)
        if use_channels:
            assert st["channels"], "sealed-channel wiring did not engage"
            ray_tpu.get(proxy.shutdown_channels.remote(), timeout=60)
        return outs, wall, st

    trace_t0 = time.monotonic_ns()
    outs_actor, wall_actor, _ = run_arm(False)
    outs_chan, wall_chan, _ = run_arm(True)
    assert [o["token_ids"] for o in outs_actor] == \
        [o["token_ids"] for o in outs_chan], \
        "channel handoff changed outputs"
    # handoff control dispatches per KV payload: the actor arm pays one
    # decode-side call carrying the payload ref per request; the channel
    # arm pays only the wiring (open_kv_channel + connect_kv_channel per
    # prefill->decode pair), amortized across the stream — the payloads
    # themselves cross in shm with zero dispatches.
    actor_rate = 1.0
    chan_rate = 2.0 / n_requests
    assert chan_rate <= 0.1, chan_rate
    print(json.dumps({
        "metric": "serve_pd_chan_dispatches_per_handoff",
        "value": round(chan_rate, 4),
        "unit": (f"control dispatches per KV payload, sealed-channel arm "
                 f"(actor-call arm={actor_rate}; {n_requests} reqs, "
                 f"outputs token-identical; wall {wall_chan:.1f}s vs "
                 f"{wall_actor:.1f}s actor, cpu)"),
        "vs_baseline": round(actor_rate / chan_rate, 1),
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    ray_tpu.shutdown()


def _soak():
    """Front-door soak (serve/frontdoor/): a REAL serve deployment —
    2 LLM replicas behind 2 controller-managed proxies with SLO-aware
    admission — slammed with thousands of concurrent HTTP connections.
    CPU-only by design: the gates under test (zero 500s, sheds are
    429-with-Retry-After ONLY, bounded p99 for admitted traffic,
    cross-replica prefix-directory hits bit-identical to cold prefill)
    are data-plane properties, not device speed. Prints the headline
    JSON line (vs_baseline = 1.0 iff every gate holds) plus an
    admission-counter line and a ``serve_soak_slo_verdict`` line — the
    shipped serve SLOs evaluated against the soak's own TSDB capture
    (the burn engine must flag the deliberate shed storm and clear the
    zero-500s error ratio); ``bench_trend --history`` folds all three.

    Flags: ``--connections N`` (default 2500), ``--quick`` (400)."""
    import asyncio
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import cfg as rcfg
    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (PagedEngineConfig,
                                          PagedInferenceEngine)
    from ray_tpu.llm.serving import LLMConfig, build_llm_deployment
    from ray_tpu.models import llama

    conns = 400 if "--quick" in sys.argv else 2500
    if "--connections" in sys.argv:
        conns = int(sys.argv[sys.argv.index("--connections") + 1])

    # fast TSDB tick so the soak's own capture carries enough points
    # for the SLO burn windows (fast-short = 20 ticks = 10 s here)
    rcfg.override(worker_prestart=2, tsdb_scrape_s=0.5)
    ray_tpu.init(num_cpus=2, object_store_memory=512 << 20)
    ecfg = PagedEngineConfig(
        model=llama.llama_tiny(vocab_size=258, max_seq_len=256),
        max_batch_size=8, page_size=8, num_pages=256,
        max_pages_per_seq=24, chunk_size=16)
    app = build_llm_deployment(
        LLMConfig(model_id="tiny", engine=ecfg, num_replicas=2,
                  max_ongoing_requests=16, warmup=False))
    serve.run(app, name="default", http_port=18511, num_proxies=2)

    ports = sorted(p["port"] for p in serve.status()["proxies"])
    assert len(ports) >= 2, "soak requires >= 2 proxies"

    system = ("You are a helpful, precise assistant. Use short answers "
              "and cite nothing. ") * 2
    rng = np.random.RandomState(0)
    fixed_prompt = system + "What is 2+2?"

    # prime: a small warm wave serves the shared system prefix on one
    # replica and lets it publish to the prefix directory (production
    # steady state) — the storm's spillover traffic on the OTHER
    # replica then admission-matches via cross-replica import
    import json as _json
    import urllib.request
    for _ in range(2):
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[0]}/default", method="POST",
            data=_json.dumps({"prompt": fixed_prompt, "max_tokens": 4,
                              "temperature": 0.0}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=120).read()
    time.sleep(1.0)     # > cfg.serve_prefix_publish_s

    trace_t0 = time.monotonic_ns()

    async def run_load():
        import aiohttp
        out = []
        sem = asyncio.Semaphore(conns)          # all in flight at once

        async def one(session, i):
            port = ports[i % len(ports)]
            prompt = (fixed_prompt if i % 7 == 0 else
                      system + f"Question {rng.randint(1e6)}?")
            t0 = time.perf_counter()
            try:
                async with sem, session.post(
                        f"http://127.0.0.1:{port}/default",
                        json={"prompt": prompt, "max_tokens": 4,
                              "temperature": 0.0},
                        timeout=aiohttp.ClientTimeout(total=120)) as r:
                    body = await r.json()
                    out.append((r.status, time.perf_counter() - t0,
                                r.headers.get("Retry-After"),
                                body if i % 7 == 0 else None))
            except Exception as e:  # noqa: BLE001 — a gate failure
                out.append(("exc:" + type(e).__name__,
                            time.perf_counter() - t0, None, None))

        connector = aiohttp.TCPConnector(limit=0)
        async with aiohttp.ClientSession(connector=connector) as s:
            await asyncio.gather(*(one(s, i) for i in range(conns)))
        return out

    t0 = time.perf_counter()
    results = asyncio.new_event_loop().run_until_complete(run_load())
    wall = time.perf_counter() - t0

    statuses = [r[0] for r in results]
    n200 = statuses.count(200)
    n429 = statuses.count(429)
    n_other = len(statuses) - n200 - n429
    bare_500s = sum(1 for s in statuses if s == 500)
    shed_clean = all(ra is not None for s, _t, ra, _b in results
                     if s == 429)
    admitted_lat = sorted(t for s, t, _ra, _b in results if s == 200)
    p99 = admitted_lat[int(len(admitted_lat) * 0.99)] if admitted_lat \
        else None
    p50 = admitted_lat[len(admitted_lat) // 2] if admitted_lat else None

    # cross-replica prefix directory: counter-verified hits, and the
    # served text for the fixed prompt is BIT-IDENTICAL to a cold
    # local prefill (same config, same seed, greedy)
    time.sleep(3.0)     # worker metric flush cadence
    ms = serve.metrics_summary()
    pd = ms.get("prefix_directory") or {}
    dir_hits = pd.get("hits", 0)
    served_texts = {b["choices"][0]["text"] for s, _t, _ra, b in results
                    if s == 200 and b}
    cold = PagedInferenceEngine(ecfg, rng_seed=0)
    cold_out = cold.generate([cold.tokenizer.encode(fixed_prompt)],
                             SamplingParams(max_tokens=4))[0]
    bit_identical = served_texts == {cold_out["text"]} if served_texts \
        else False

    gates = {
        "zero_500s": bare_500s == 0 and n_other == 0,
        "sheds_are_429_with_retry_after": shed_clean,
        "admitted_p99_bounded": p99 is not None and p99 < 60.0,
        "prefix_directory_hits": dir_hits > 0,
        "bit_identical_to_cold_prefill": bit_identical,
    }
    print(json.dumps({
        "metric": "serve_soak_admitted_p99",
        "value": None if p99 is None else round(p99, 4),
        "unit": (f"s e2e over {conns} concurrent conns x 2 proxies "
                 f"(p50={None if p50 is None else round(p50, 4)}s, "
                 f"{n200} ok / {n429} shed / {n_other} other in "
                 f"{wall:.1f}s, dir_hits={dir_hits:.0f}, "
                 f"imported_pages="
                 f"{pd.get('imported_pages', 0):.0f}, "
                 f"gates={gates})"),
        "vs_baseline": 1.0 if all(gates.values()) else 0.0,
    }))
    print(json.dumps({"metric": "serve_soak_admission",
                      "value": ms.get("admission"),
                      "unit": "admitted/shed counters + queue waits"},
                     default=str))

    # SLO verdict against the soak's OWN TSDB capture: the burn engine
    # must DETECT the deliberate shed storm (shed_ratio burning) while
    # correctly reporting the zero-500s run healthy (error_ratio ok) —
    # a counter-verified exercise of the whole obs pipeline under real
    # overload. Folded round-over-round by bench_trend --history.
    from ray_tpu import state as state_mod
    from ray_tpu.core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    if rt is not None and getattr(rt, "obs", None) is not None:
        rt.obs.scrape_once()    # fold the final post-load counters
    slo = state_mod.slo_report()
    rows = {r["slo"]: r for r in slo.get("slos", [])}
    shed_row = rows.get("shed_ratio", {})
    err_row = rows.get("error_ratio", {})
    slo_gates = {
        "all_shipped_slos_evaluated": len(rows) >= 4,
        "shed_storm_detected": (shed_row.get("state") != "ok"
                                or (shed_row.get("burn_fast")
                                    or [0.0])[0] > 1.0),
        "error_ratio_ok": err_row.get("state", "ok") == "ok",
    }
    print(json.dumps({
        "metric": "serve_soak_slo_verdict",
        "value": round((shed_row.get("burn_fast") or [0.0])[0], 3),
        "unit": (f"shed_ratio fast-short burn rate (states="
                 f"{slo.get('states')}, "
                 f"tsdb {slo.get('tsdb', {}).get('series', 0)} series/"
                 f"{slo.get('tsdb', {}).get('ticks', 0)} ticks, "
                 f"slo_gates={slo_gates})"),
        "vs_baseline": 1.0 if all(slo_gates.values()) else 0.0,
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    serve.shutdown()
    ray_tpu.shutdown()
    raise SystemExit(0 if all(gates.values()) else 1)


def _multi_tenant():
    """Multi-tenant LoRA scenario (llm/multilora + tenant front door),
    CPU-only by design — every gate is a COUNT or a status-code
    property, not a device speed:

    1. **dispatch economy**: the same burst over ONE shared paged base
       model costs the same device dispatches per token whether its
       rows are 1 tenant or N tenants (counter-verified via the
       engine's rtpu_llm_*-backed stats, like --decode-plan) — the
       slot table multiplexes adapters into shared programs, never
       extra dispatches. Each tenant's greedy output is asserted
       bit-identical to its merged-engine reference while we're at it.
    2. **fairness under overload**: a REAL serve deployment behind an
       admission-gated proxy; a heavy tenant floods it while a light
       tenant trickles. Gates: the heavy tenant sheds tenant_quota
       429s (all with Retry-After), the light tenant's requests ALL
       admit with bounded latency, zero bare 500s, and the per-tenant
       split is counter-verified in metrics_summary()["tenants"].

    Prints ONE JSON line; vs_baseline = 1.0 iff every gate holds.
    """
    import asyncio
    import os
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from ray_tpu.llm import SamplingParams, lora
    from ray_tpu.llm.paged_engine import (PagedEngineConfig,
                                          PagedInferenceEngine)
    from ray_tpu.models import llama

    model = llama.llama_tiny(vocab_size=258, max_seq_len=256)
    ecfg = dict(max_batch_size=8, page_size=8, num_pages=256,
                max_pages_per_seq=24, chunk_size=16)
    n_tenants = 4
    base = None   # engine seeds its own params from rng_seed

    # -- part 1: dispatches/token flat in tenant count -------------------
    rng = np.random.RandomState(0)
    adapters = [lora.random_adapter(
        jax.random.PRNGKey(10 + i), model, rank=4, alpha=32.0,
        targets=("wq", "wv", "lm_head")) for i in range(n_tenants)]
    prompts = [list(rng.randint(1, 250, (24 if i % 2 else 40,)))
               for i in range(12)]
    sp = SamplingParams(max_tokens=16)

    def run_tenants(k: int):
        eng = PagedInferenceEngine(PagedEngineConfig(
            model=model, max_adapters=n_tenants + 1, lora_rank=4,
            **ecfg), params=base, rng_seed=0)
        # pin every request to exactly max_tokens (instance-level EOS
        # shadow): the dispatch comparison needs IDENTICAL output
        # shapes across the two runs — with live EOS, different
        # tenants stop at different steps and the tail's thinner
        # decode windows shift dispatches/token for reasons that have
        # nothing to do with multiplexing
        eng.tokenizer.eos_id = None
        for i in range(n_tenants):
            eng.load_adapter_slot(i + 1, adapters[i])
        reqs = []
        for i, p in enumerate(prompts):
            s = (i % k) + 1
            reqs.append(eng.submit(p, sp, adapter_slot=s,
                                   prefix_salt=bytes([s])))
        while not all(r.done for r in reqs):
            eng.step()
        st = eng.stats
        disp = (st["prefill_dispatches"] + st["decode_dispatches"]
                + st["spec_dispatches"])
        return disp / max(st["tokens_out"], 1), reqs, eng

    dpt_1, _, _ = run_tenants(1)
    dpt_n, reqs_n, eng_n = run_tenants(n_tenants)
    ratio = dpt_n / max(dpt_1, 1e-9)

    # per-tenant greedy parity against the merged oracle
    merged_ok = True
    for t in range(n_tenants):
        ref_eng = PagedInferenceEngine(
            PagedEngineConfig(model=model, **ecfg),
            params=lora.merge(PagedInferenceEngine(
                PagedEngineConfig(model=model, **ecfg),
                rng_seed=0).params, adapters[t]), rng_seed=0)
        ref_eng.tokenizer.eos_id = None   # same full-length contract
        idx = t   # first request of tenant t+1 in the round-robin
        ref = ref_eng.submit(prompts[idx], sp)
        while not ref.done:
            ref_eng.step()
        merged_ok &= (reqs_n[idx].out_ids == ref.out_ids)

    # -- part 2: fairness split under overload ---------------------------
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.core.config import cfg as rcfg
    from ray_tpu.llm.serving import LLMConfig, build_llm_deployment

    rcfg.override(worker_prestart=2)
    ray_tpu.init(num_cpus=2, object_store_memory=512 << 20)
    app = build_llm_deployment(LLMConfig(
        model_id="tiny",
        engine=PagedEngineConfig(model=model, **ecfg),
        num_replicas=1, max_ongoing_requests=8, warmup=False))
    serve.run(app, name="default", http_port=18521, num_proxies=1)
    port = serve.status()["proxies"][0]["port"]

    trace_t0 = time.monotonic_ns()
    heavy_n, light_n = 60, 8
    results = {"heavy": [], "light": []}

    async def run_load():
        import aiohttp

        async def one(session, tenant, i):
            t0 = time.perf_counter()
            try:
                async with session.post(
                        f"http://127.0.0.1:{port}/default",
                        json={"prompt": f"q {tenant} {i}",
                              "max_tokens": 4, "tenant": tenant},
                        timeout=aiohttp.ClientTimeout(total=120)) as r:
                    await r.read()
                    results[tenant].append(
                        (r.status, time.perf_counter() - t0,
                         r.headers.get("Retry-After")))
            except Exception as e:  # noqa: BLE001 — a gate failure
                results[tenant].append(
                    ("exc:" + type(e).__name__,
                     time.perf_counter() - t0, None))

        async def light_trickle(session):
            for i in range(light_n):
                await one(session, "light", i)
                await asyncio.sleep(0.05)

        connector = aiohttp.TCPConnector(limit=0)
        async with aiohttp.ClientSession(connector=connector) as s:
            await asyncio.gather(
                light_trickle(s),
                *(one(s, "heavy", i) for i in range(heavy_n)))

    t0 = time.perf_counter()
    asyncio.new_event_loop().run_until_complete(run_load())
    wall = time.perf_counter() - t0

    time.sleep(3.0)     # worker metric flush cadence
    ms = serve.metrics_summary()
    tstats = ms.get("tenants", {})
    h_status = [r[0] for r in results["heavy"]]
    l_status = [r[0] for r in results["light"]]
    l_lat = sorted(t for s, t, _ra in results["light"] if s == 200)
    l_p99 = l_lat[int(len(l_lat) * 0.99)] if l_lat else None
    shed_clean = all(ra is not None for s, _t, ra in results["heavy"]
                     if s == 429)
    bare_500s = h_status.count(500) + l_status.count(500)
    gates = {
        "dispatches_flat_in_tenants": abs(ratio - 1.0) < 0.05,
        "tenant_outputs_match_merged": merged_ok,
        "heavy_tenant_shed_429": h_status.count(429) > 0 and shed_clean,
        "light_tenant_all_admitted": (l_status.count(200) == light_n
                                      and l_status.count(429) == 0),
        "light_p99_bounded": l_p99 is not None and l_p99 < 30.0,
        "zero_500s": bare_500s == 0,
        "tenant_split_counter_verified": (
            tstats.get("heavy", {}).get("shed", 0) > 0
            and tstats.get("light", {}).get("shed", 1) == 0
            and tstats.get("light", {}).get("admitted", 0) >= light_n),
    }
    print(json.dumps({
        "metric": "serve_multi_tenant_light_p99",
        "value": None if l_p99 is None else round(l_p99, 4),
        "unit": (f"s light-tenant e2e under a {heavy_n}-conn heavy "
                 f"flood ({n_tenants} tenants x 1 base model; "
                 f"dispatches/token {dpt_n:.4f} vs {dpt_1:.4f} "
                 f"single-tenant = {ratio:.3f}x; heavy "
                 f"{h_status.count(200)} ok / {h_status.count(429)} "
                 f"shed, light {l_status.count(200)}/{light_n} ok in "
                 f"{wall:.1f}s; tenants={tstats}; gates={gates})"),
        "vs_baseline": 1.0 if all(gates.values()) else 0.0,
    }))
    from bench import flight_report, trace_arg
    flight_report(trace_arg(sys.argv), trace_t0)
    serve.shutdown()
    ray_tpu.shutdown()
    raise SystemExit(0 if all(gates.values()) else 1)


def _pd_interference(model, cfg, rng, max_tokens, prompt_lens, on_tpu):
    """Decode-stall comparison: max inter-token gap of an ACTIVE decode
    while a long prompt prefills — colocated single engine vs
    disaggregated decode replica (llm/pd_disagg.py; reference:
    prefill_decode_disagg.py:64). Disaggregation exists precisely to keep
    long prefills from stalling running decodes. NOTE: with one chip both
    PD replicas still share the device, so the PD number here bounds
    interference from above; separate-chip deployments only improve it."""
    import time

    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import PagedInferenceEngine

    long_len = prompt_lens[-1] * 2
    short = list(rng.randint(1, model.vocab_size, (prompt_lens[0],)))
    long_p = list(rng.randint(1, model.vocab_size, (long_len,)))
    sp = SamplingParams(max_tokens=max_tokens, temperature=0.0)

    def max_gap(engine, req, inject):
        """Step until req done; inject() once after 2 tokens; return the
        max wall gap between consecutive generated tokens."""
        gaps, last, seen, injected = [], None, 0, False
        while not req.done:
            engine.step()
            now = time.perf_counter()
            if len(req.out_ids) > seen:
                if last is not None:
                    gaps.append(now - last)
                last, seen = now, len(req.out_ids)
                if seen >= 2 and not injected:
                    inject()
                    injected = True
        return max(gaps) if gaps else 0.0

    # colocated: one engine does both phases
    colo = PagedInferenceEngine(cfg, rng_seed=0)
    colo.generate([short], SamplingParams(max_tokens=2))  # warm compiles
    req = colo.submit(short, sp)
    colo_gap = max_gap(colo, req, lambda: colo.submit(long_p, sp))

    # disaggregated: decode replica never sees prefill work
    pre = PagedInferenceEngine(cfg, rng_seed=0)
    dec = PagedInferenceEngine(cfg, rng_seed=0)
    pre.generate([short], SamplingParams(max_tokens=2))
    payload = pre.prefill_export(short, sp)
    dreq = dec.import_prefill(payload, sp)
    import threading
    background = threading.Thread(
        target=lambda: pre.prefill_export(long_p, sp), daemon=True)
    pd_gap = max_gap(dec, dreq, background.start)
    background.join(timeout=120)

    print(json.dumps({
        "metric": "serve_pd_decode_stall",
        "value": round(pd_gap, 4),
        "unit": (f"s max inter-token gap under long-prefill injection "
                 f"(colocated={colo_gap:.4f}s, "
                 f"{jax.devices()[0].platform})"),
        # the PD decode replica should stall less than the colocated engine
        "vs_baseline": round(colo_gap / max(pd_gap, 1e-9), 4),
    }))


if __name__ == "__main__":
    main()
