"""Pinned CPU-mesh training-step trend benchmark.

The MFU north star needs the TPU tunnel, which is frequently down
(BENCH_r03/r04 rc=1). This benchmark is the hedge: a FIXED model config
+ FIXED 8-device virtual CPU mesh + FIXED batch, measured every round,
so step-time regressions in the sharded training path are visible
round-over-round even when the TPU is not reachable. The absolute
number is meaningless (CPU emulation); the TREND is the signal.

Prints one JSON line: {"metric": "cpu_mesh_tokens_per_sec", ...} with
vs_baseline against the round-5 pin.

Run directly (it re-execs itself with the CPU-mesh env):
    python bench_trend.py

`python bench_trend.py --history [--dir D] [--out trend.json]` folds the
accumulated per-round bench artifacts (BENCH_r0N.json, BENCHCORE_r0N.json,
BENCH_TPU_*.json, MULTICHIP_r0N.json, ...) into ONE round-over-round
trend table — markdown to stdout, the structured JSON to --out — so a
regression is visible at a glance instead of requiring hand-diffing N
files of three different shapes (single-object, single-object-with-
parsed, and JSON-lines metric records).
"""
import json
import os
import subprocess
import sys
import time

# Pinned at round 5 on the 1-core build box (measured 2026-07-30:
# 773.7 tokens/s). Do not retune without recording a new pin; the point
# is cross-round comparability — vs_baseline ~1.0 means no regression.
BASELINE_TOKENS_PER_SEC = 773.7
_PIN_FILE_DEFAULT = 773.7
# round-5 pin for the serving dispatch-economy scenario (dispatches per
# generated token on the pinned burst; windowed decode + batched prefill)
BASELINE_SERVE_DISPATCH_PER_TOKEN = 0.1172


def _child():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import llama
    from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
    from ray_tpu.parallel.sharding import batch_spec, logical_sharding
    from jax.sharding import NamedSharding

    cfg = llama.LlamaConfig(
        vocab_size=2048, dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
        mlp_dim=512, max_seq_len=512, dtype=jnp.float32, remat=False,
        use_flash=False)
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    batch, seq = 8, 257

    with use_mesh(mesh):
        params = llama.init(jax.random.PRNGKey(0), cfg)
        param_sh = logical_sharding(llama.logical_axes(cfg), mesh)
        params = jax.device_put(params, param_sh)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        batch_sh = NamedSharding(mesh, batch_spec(mesh))
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(0).randint(
                0, cfg.vocab_size, (batch, seq)), jnp.int32), batch_sh)

        def train_step(params, opt_state, tokens):
            def loss_fn(p):
                logits = llama.apply(p, tokens[:, :-1], cfg)
                return llama.cross_entropy_loss(logits, tokens[:, 1:])
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state2 = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss

        # opt_state shardings must be PINNED on both sides: its mu/nu
        # leaves inherit the param shardings from opt.init, but with
        # `None` the output placement is left to XLA, which may pick a
        # different sharding than the donated input — the aliased
        # buffers then differ in per-device size and the step fails at
        # dispatch ("Expected aliased input ... to have the same
        # size"). Scalar leaves (adam's count) come back single-device;
        # replicate them onto the mesh so one sharding tree covers the
        # whole state.
        replicated = NamedSharding(mesh, jax.sharding.PartitionSpec())
        opt_sh = jax.tree.map(
            lambda a: a.sharding if isinstance(a.sharding, NamedSharding)
            else replicated, opt_state)
        opt_state = jax.device_put(opt_state, opt_sh)
        step = jax.jit(train_step,
                       in_shardings=(param_sh, opt_sh, batch_sh),
                       out_shardings=(param_sh, opt_sh, None),
                       donate_argnums=(0, 1))
        # compile + warm
        params, opt_state, loss = step(params, opt_state, tokens)
        loss.block_until_ready()
        n_steps = 5
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
    tps = n_steps * batch * (seq - 1) / dt
    print(json.dumps({"_trend_tokens_per_sec": tps}))


def _child_serve():
    """Pinned serving dispatch-economy scenario: device DISPATCHES per
    generated token over a fixed burst (count, not time — identical on
    any machine, so the trend is noise-free). The dispatch-minimal
    engine work (windowed decode, batched prefill, fused sampling)
    shows up here; a regression that reintroduces per-token dispatches
    moves this number ~10x."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ray_tpu.llm import SamplingParams
    from ray_tpu.llm.paged_engine import (
        PagedEngineConfig, PagedInferenceEngine,
    )
    from ray_tpu.models import llama

    cfg = PagedEngineConfig(
        model=llama.llama_tiny(vocab_size=258, max_seq_len=256),
        max_batch_size=8, page_size=8, num_pages=256,
        max_pages_per_seq=24, chunk_size=16, prefill_rows=4,
        decode_window=8)
    eng = PagedInferenceEngine(cfg, rng_seed=0)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 250, (24 if i % 2 else 48,)))
               for i in range(12)]
    eng.generate(prompts, SamplingParams(max_tokens=32))
    st = eng.stats
    disp = (st["prefill_dispatches"] + st["decode_dispatches"]
            + st["spec_dispatches"])
    print(json.dumps({"_serve_dispatch_per_token":
                      disp / max(st["tokens_out"], 1)}))


def _run_child(kind: str, result_key: str, extra_env=None) -> float:
    """Re-exec this file as a pinned child and parse one result key."""
    env = dict(os.environ)
    env["_BENCH_TREND_CHILD"] = kind
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench_trend child {kind!r} failed rc={proc.returncode}:\n"
            f"{proc.stdout}\n{proc.stderr}")
    for line in reversed(proc.stdout.splitlines()):
        try:
            rec = json.loads(line)
            if result_key in rec:
                return float(rec[result_key])
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"no {result_key} line in child output: "
                       f"{proc.stdout}")


def measure_serve_dispatch() -> float:
    """Dispatches per generated token on the pinned burst (child proc)."""
    return _run_child("serve", "_serve_dispatch_per_token")


def measure() -> float:
    """Run the pinned step in a clean CPU-mesh subprocess; returns
    tokens/s."""
    flags = os.environ.get("XLA_FLAGS", "")
    extra = {}
    if "xla_force_host_platform_device_count" not in flags:
        extra["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    return _run_child("1", "_trend_tokens_per_sec", extra)


# --------------------------------------------------------------------- #
# --history: fold per-round bench artifacts into one trend table
# --------------------------------------------------------------------- #

import glob as _glob
import re as _re

_ROUND_RE = _re.compile(r"_r(\d+)")


def _metric_records(path: str):
    """Yield {"metric", "value", "vs_baseline"} records from one bench
    artifact, tolerating all four accumulated shapes: a JSON-lines file
    of metric records (BENCHCORE r05 / BENCH_TPU), a wrapper object with
    a "metrics" list (BENCHCORE r04), a single object carrying a
    "parsed" metric record (BENCH_r0N driver wrapper), and a single
    status object with no metric at all (MULTICHIP dryruns — reported as
    an ok/rc pseudo-metric so tunnel regressions still show)."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict):
        if isinstance(obj.get("metrics"), list):
            for rec in obj["metrics"]:
                if isinstance(rec, dict) and "metric" in rec:
                    yield rec
        elif isinstance(obj.get("parsed"), dict) \
                and "metric" in obj["parsed"]:
            yield obj["parsed"]
        elif "metric" in obj:
            yield obj
        elif "rc" in obj:
            name = os.path.basename(path).split("_r")[0].lower()
            yield {"metric": f"{name}_ok",
                   "value": 1.0 if obj.get("rc") == 0 else 0.0,
                   "vs_baseline": None}
        return
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "metric" in rec:
            yield rec


def build_history(directory: str) -> dict:
    """Scan `directory` for BENCH*_r*.json / MULTICHIP*_r*.json round
    artifacts and fold them into {"rounds": [..], "metrics": {name:
    {round: {"value", "vs_baseline"}}}}. Later files for the same
    (metric, round) win (e.g. an *_interim refresh)."""
    paths = sorted(_glob.glob(os.path.join(directory, "BENCH*_r*.json"))
                   + _glob.glob(os.path.join(directory,
                                             "MULTICHIP*_r*.json")))
    metrics: dict = {}
    rounds: set = set()
    for path in paths:
        m = _ROUND_RE.search(os.path.basename(path))
        if m is None:
            continue
        rnd = int(m.group(1))
        for rec in _metric_records(path):
            rounds.add(rnd)
            metrics.setdefault(rec["metric"], {})[rnd] = {
                "value": rec.get("value"),
                "vs_baseline": rec.get("vs_baseline"),
            }
    return {"rounds": sorted(rounds), "metrics": metrics,
            "files": len(paths)}


def _fmt_cell(cell) -> str:
    if cell is None:
        return ""
    v, vb = cell.get("value"), cell.get("vs_baseline")
    if v is None:
        return "err"
    s = f"{v:.4g}" if isinstance(v, (int, float)) else str(v)
    if isinstance(vb, (int, float)):
        s += f" ({vb:.2f}x)"
    return s


def history_markdown(hist: dict) -> str:
    """Render build_history() output as one markdown table: metrics x
    rounds, cells `value (vs_baseline x)`."""
    rounds = hist["rounds"]
    lines = ["| metric | " + " | ".join(f"r{r:02d}" for r in rounds)
             + " |",
             "|---" * (len(rounds) + 1) + "|"]
    for name in sorted(hist["metrics"]):
        cells = [_fmt_cell(hist["metrics"][name].get(r)) for r in rounds]
        lines.append(f"| {name} | " + " | ".join(cells) + " |")
    return "\n".join(lines)


def history_main(argv) -> int:
    import argparse
    p = argparse.ArgumentParser(prog="bench_trend.py --history")
    p.add_argument("--history", action="store_true")
    p.add_argument("--dir", default=os.path.dirname(
        os.path.abspath(__file__)))
    p.add_argument("--out", default=None,
                   help="also write the structured JSON here")
    args = p.parse_args(argv)
    hist = build_history(args.dir)
    if not hist["metrics"]:
        print(f"no BENCH*_r*.json artifacts under {args.dir}")
        return 1
    print(history_markdown(hist))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(hist, f, indent=1)
        print(f"\nwrote {args.out} ({hist['files']} files, "
              f"{len(hist['metrics'])} metrics, "
              f"rounds {hist['rounds']})")
    return 0


def main():
    tps = measure()
    base = BASELINE_TOKENS_PER_SEC or _PIN_FILE_DEFAULT
    print(json.dumps({
        "metric": "cpu_mesh_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s (8-dev virtual CPU mesh, pinned config)",
        "vs_baseline": round(tps / base, 3),
    }))


if __name__ == "__main__":
    kind = os.environ.get("_BENCH_TREND_CHILD")
    if kind == "serve":
        _child_serve()
    elif kind:
        _child()
    elif "--history" in sys.argv[1:]:
        sys.exit(history_main(sys.argv[1:]))
    else:
        main()
