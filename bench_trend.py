"""Pinned CPU-mesh training-step trend benchmark.

The MFU north star needs the TPU tunnel, which is frequently down
(BENCH_r03/r04 rc=1). This benchmark is the hedge: a FIXED model config
+ FIXED 8-device virtual CPU mesh + FIXED batch, measured every round,
so step-time regressions in the sharded training path are visible
round-over-round even when the TPU is not reachable. The absolute
number is meaningless (CPU emulation); the TREND is the signal.

Prints one JSON line: {"metric": "cpu_mesh_tokens_per_sec", ...} with
vs_baseline against the round-5 pin.

Run directly (it re-execs itself with the CPU-mesh env):
    python bench_trend.py
"""
import json
import os
import subprocess
import sys
import time

# Pinned at round 5 on the 1-core build box (measured 2026-07-30:
# 773.7 tokens/s). Do not retune without recording a new pin; the point
# is cross-round comparability — vs_baseline ~1.0 means no regression.
BASELINE_TOKENS_PER_SEC = 773.7
_PIN_FILE_DEFAULT = 773.7


def _child():
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import llama
    from ray_tpu.parallel import MeshSpec, build_mesh, use_mesh
    from ray_tpu.parallel.sharding import batch_spec, logical_sharding
    from jax.sharding import NamedSharding

    cfg = llama.LlamaConfig(
        vocab_size=2048, dim=256, n_layers=4, n_heads=8, n_kv_heads=4,
        mlp_dim=512, max_seq_len=512, dtype=jnp.float32, remat=False,
        use_flash=False)
    mesh = build_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    batch, seq = 8, 257

    with use_mesh(mesh):
        params = llama.init(jax.random.PRNGKey(0), cfg)
        param_sh = logical_sharding(llama.logical_axes(cfg), mesh)
        params = jax.device_put(params, param_sh)
        opt = optax.adamw(1e-3)
        opt_state = opt.init(params)
        batch_sh = NamedSharding(mesh, batch_spec(mesh))
        tokens = jax.device_put(
            jnp.asarray(np.random.RandomState(0).randint(
                0, cfg.vocab_size, (batch, seq)), jnp.int32), batch_sh)

        def train_step(params, opt_state, tokens):
            def loss_fn(p):
                logits = llama.apply(p, tokens[:, :-1], cfg)
                return llama.cross_entropy_loss(logits, tokens[:, 1:])
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state2 = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state2, loss

        step = jax.jit(train_step,
                       in_shardings=(param_sh, None, batch_sh),
                       out_shardings=(param_sh, None, None),
                       donate_argnums=(0, 1))
        # compile + warm
        params, opt_state, loss = step(params, opt_state, tokens)
        loss.block_until_ready()
        n_steps = 5
        t0 = time.perf_counter()
        for _ in range(n_steps):
            params, opt_state, loss = step(params, opt_state, tokens)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
    tps = n_steps * batch * (seq - 1) / dt
    print(json.dumps({"_trend_tokens_per_sec": tps}))


def measure() -> float:
    """Run the pinned step in a clean CPU-mesh subprocess; returns
    tokens/s."""
    env = dict(os.environ)
    env["_BENCH_TREND_CHILD"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench_trend child failed rc={proc.returncode}:\n"
            f"{proc.stdout}\n{proc.stderr}")
    for line in reversed(proc.stdout.splitlines()):
        try:
            rec = json.loads(line)
            if "_trend_tokens_per_sec" in rec:
                return float(rec["_trend_tokens_per_sec"])
        except json.JSONDecodeError:
            continue
    raise RuntimeError(f"no trend line in child output: {proc.stdout}")


def main():
    tps = measure()
    base = BASELINE_TOKENS_PER_SEC or _PIN_FILE_DEFAULT
    print(json.dumps({
        "metric": "cpu_mesh_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s (8-dev virtual CPU mesh, pinned config)",
        "vs_baseline": round(tps / base, 3),
    }))


if __name__ == "__main__":
    if os.environ.get("_BENCH_TREND_CHILD"):
        _child()
    else:
        main()
