"""ray_tpu — a TPU-native distributed AI framework.

Brand-new implementation with the capabilities of the Ray reference
(task/actor/object runtime, placement groups, Train/Tune/Data/Serve/RLlib/LLM
libraries), re-designed TPU-first: intra-slice parallelism is expressed via
JAX/XLA (pjit + shard_map over a device mesh, Pallas kernels for hot ops) and
the actor runtime coordinates hosts and slices.

Public core API mirrors the reference's `ray` module surface
(python/ray/_private/worker.py): init/shutdown/remote/get/put/wait/kill/
cancel/get_actor/nodes/cluster_resources/...
"""
from ._version import __version__
from . import exceptions
from .core.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    head_address,
    init,
    is_initialized,
    kill,
    kv_del,
    kv_get,
    kv_keys,
    kv_put,
    nodes,
    put,
    remote,
    shutdown,
    timeline,
    wait,
)
from .core.ref import ObjectRef
from .core.actor import ActorHandle

__all__ = [
    "__version__", "exceptions", "init", "shutdown", "is_initialized",
    "remote", "get", "put", "wait", "kill", "cancel", "get_actor",
    "get_runtime_context", "head_address", "nodes", "cluster_resources",
    "available_resources", "timeline", "ObjectRef", "ActorHandle", "util",
    "state", "kv_put", "kv_get", "kv_del", "kv_keys",
]

from . import util  # noqa: E402  (needs the names above)
from . import state  # noqa: E402  (state API + Prometheus metrics)
