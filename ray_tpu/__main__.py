"""`python -m ray_tpu` — the cluster/job CLI (scripts/scripts.py analog)."""
import sys

from .cli import main

sys.exit(main())
