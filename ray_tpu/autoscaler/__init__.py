"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference parity: autoscaler v2 (autoscaler/v2/autoscaler.py:42,
instance_manager/instance_manager.py:29, scheduler.py:632
ResourceDemandScheduler) and the fake multi-node provider
(autoscaler/_private/fake_multi_node/node_provider.py:236).
"""
from .autoscaler import Autoscaler, NodeTypeConfig, active_autoscalers
from .config import autoscaler_from_config
from .gce_tpu import GceTpuVmProvider
from .node_provider import FakeNodeProvider, NodeProvider
from .v2 import AutoscalerV2, Instance, InstanceManager

from .sdk import request_resources

__all__ = ["Autoscaler", "AutoscalerV2", "NodeTypeConfig", "NodeProvider",
           "request_resources",
           "FakeNodeProvider", "GceTpuVmProvider", "Instance",
           "InstanceManager", "active_autoscalers",
           "autoscaler_from_config"]
