"""The autoscaler reconcile loop + demand bin-packing.

Reference parity: autoscaler/v2/autoscaler.py:42 (update_autoscaling_state
reading cluster resource state), scheduler.py:632 ResourceDemandScheduler
(bin-packs pending demands onto node types), instance lifecycle
(instance_manager.py:29). TPU inversion: demand is read straight off the
head runtime's queues (pending tasks, unplaced actors, pending PG
bundles) — there is no GCS/autoscaler RPC hop because the head IS the
control plane.

Scale-up: first-fit-decreasing bin-pack of unmet demands onto the
configured node types (respecting per-type max_workers).
Scale-down: a provider node with no busy/actor workers and no reserved PG
bundle for `idle_timeout_s` is terminated (min_workers respected).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from .node_provider import NodeProvider

# scalers started in this process, for the state API / dashboard
# (reference: the autoscaler reports through GcsAutoscalerStateManager;
# here the head process IS the control plane so a registry suffices)
_ACTIVE: list = []


def active_autoscalers() -> list:
    return list(_ACTIVE)


@dataclasses.dataclass
class NodeTypeConfig:
    """(reference: available_node_types in the cluster YAML; TPU slice
    types additionally carry a host count, like the reference's
    tpu-pod worker groups)"""
    name: str
    resources: dict          # PER-HOST resources
    min_workers: int = 0
    max_workers: int = 4     # counted in INSTANCES (slices), not hosts
    hosts: int = 1           # hosts per instance (>1 = TPU slice type)
    labels: Optional[dict] = None  # labels stamped on every host


def _fits(demand: dict, capacity: dict) -> bool:
    return all(capacity.get(k, 0.0) >= v - 1e-9 for k, v in demand.items())


def _sub(capacity: dict, demand: dict) -> None:
    for k, v in demand.items():
        capacity[k] = capacity.get(k, 0.0) - v


def _gang_fits(gang: list[dict], hosts: int, per_host: dict,
               strategy: str = "PACK") -> bool:
    """Can `gang`'s bundles bin-pack onto `hosts` hosts of `per_host`
    resources? PACK and soft-SPREAD gangs may put several bundles on one
    host (the runtime's placer doubles up soft SPREAD when short on
    nodes); STRICT_SPREAD is exactly one bundle per host, so a slice
    with fewer hosts than bundles can never satisfy it."""
    if strategy == "STRICT_SPREAD":
        return hosts >= len(gang) and all(_fits(b, per_host) for b in gang)
    bins = [dict(per_host) for _ in range(hosts)]
    for b in sorted(gang, key=lambda d: -sum(d.values())):
        for cap in bins:
            if _fits(b, cap):
                _sub(cap, b)
                break
        else:
            return False
    return True


def plan_scaling(node_types: dict, demands: list[dict],
                 gangs: list[tuple[list[dict], str]], frees: list[dict],
                 booting_types: list[str],
                 live_by_type: dict[str, int]) -> dict[str, int]:
    """The pure scale-up decision shared by v1 and v2 (reference:
    scheduler.py:632 ResourceDemandScheduler): bin-pack unmet demands and
    pending slice gangs onto new instances of the configured types.

    `frees` is per-alive-host free resources; `booting_types` lists the
    type of every instance already launching (their capacity is counted so
    a burst of demand doesn't launch a node per tick); `live_by_type`
    counts ALL non-terminal instances for max_workers ceilings. Mutates
    nothing; returns {type name: count to launch}.
    """
    frees = [dict(f) for f in frees]
    live_by_type = dict(live_by_type)
    for tname in booting_types:
        t = node_types[tname]
        for _ in range(t.hosts):
            frees.append(dict(t.resources))

    unmet: list[dict] = []
    for d in sorted(demands, key=lambda d: -sum(d.values())):
        for cap in frees:
            if _fits(d, cap):
                _sub(cap, d)
                break
        else:
            unmet.append(d)

    # bin-pack unmet onto new nodes, first-fit-decreasing by type order
    to_launch: dict[str, int] = {}
    new_caps: list[dict] = []
    for d in unmet:
        placed = False
        for cap in new_caps:
            if _fits(d, cap):
                _sub(cap, d)
                placed = True
                break
        if placed:
            continue
        for t in node_types.values():
            count = live_by_type.get(t.name, 0) + to_launch.get(t.name, 0)
            if count >= t.max_workers:
                continue
            if _fits(d, dict(t.resources)):
                cap = dict(t.resources)
                _sub(cap, d)
                new_caps.append(cap)
                to_launch[t.name] = to_launch.get(t.name, 0) + 1
                placed = True
                break
        # unplaceable on ANY type: leave it pending (the task's own
        # infeasibility timeout reports the error)

    # slice gangs: each pending same-label PG needs ONE instance with
    # enough hosts, every bundle fitting the type's per-host resources
    # (one bundle per host, the slice_placement_group shape). A booting
    # slice-capable instance covers a gang so bursts don't launch one
    # slice per tick.
    in_flight = list(booting_types)
    for gang, strategy in gangs:
        def covers(t: NodeTypeConfig) -> bool:
            return _gang_fits(gang, t.hosts, t.resources, strategy)
        hit = next((tn for tn in in_flight
                    if covers(node_types[tn])), None)
        if hit is not None:
            in_flight.remove(hit)
            continue
        for t in node_types.values():
            count = live_by_type.get(t.name, 0) + to_launch.get(t.name, 0)
            if count >= t.max_workers or not covers(t):
                continue
            to_launch[t.name] = to_launch.get(t.name, 0) + 1
            break

    # min_workers floor
    for t in node_types.values():
        have = live_by_type.get(t.name, 0) + to_launch.get(t.name, 0)
        if have < t.min_workers:
            to_launch[t.name] = to_launch.get(t.name, 0) + (
                t.min_workers - have)
    return to_launch


def busy_node_hexes(rt) -> set:
    """NodeID hexes with busy/actor/starting workers or reserved PG
    bundles — nodes the autoscaler must not reclaim."""
    with rt.lock:
        busy_nodes = set()
        for w in rt.workers.values():
            if w.state in ("busy", "actor", "starting") or w.blocked:
                busy_nodes.add(w.node_id)
        for pg in rt.pgs.values():
            if pg.state == "created":
                for b in pg.bundles:
                    if b.node_id is not None:
                        busy_nodes.add(b.node_id)
        return {n.hex() for n in busy_nodes}


class Autoscaler:
    def __init__(self, node_types: list[NodeTypeConfig],
                 provider: Optional[NodeProvider] = None,
                 idle_timeout_s: float = 30.0,
                 period_s: float = 1.0,
                 runtime=None):
        from ..core import runtime as rt_mod
        self.rt = runtime or rt_mod.get_runtime_if_exists()
        if self.rt is None:
            raise RuntimeError("ray_tpu.init() first")
        if provider is None:
            from .node_provider import FakeNodeProvider
            provider = FakeNodeProvider(self.rt)
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.idle_timeout_s = idle_timeout_s
        self.period_s = period_s
        # instance bookkeeping: iid -> type name; iid -> launch ts
        self.instances: dict[str, str] = {}
        self._launched_at: dict[str, float] = {}
        self._idle_since: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.events: list[dict] = []  # scale decisions, for observability

    # -- demand collection -------------------------------------------- #

    def pending_demands(self) -> list[dict]:
        """Resource asks the cluster cannot currently place."""
        rt = self.rt
        demands: list[dict] = []
        with rt.lock:
            for spec in rt.pending:
                demands.append(dict(spec.resources))
            for a in rt.actors.values():
                if a.state in ("pending", "restarting") and a.wid is None \
                        and a.spec.pg_id is None:
                    demands.append(dict(a.spec.resources))
            for pg in rt.pgs.values():
                if pg.state == "pending" and not pg.same_label:
                    demands.extend(dict(b.resources) for b in pg.bundles)
            # programmatic floor (reference: autoscaler/sdk
            # request_resources): bundles the operator asked to keep
            # launchable regardless of queued work — planned like
            # pending tasks every tick until replaced/cleared
            demands.extend(dict(b)
                           for b in getattr(rt, "resource_requests", ()))
        return [d for d in demands if d]

    def pending_gangs(self) -> list[tuple[list[dict], str]]:
        """(bundles, strategy) of pending same-label (slice-constrained)
        PGs. These can only be satisfied by launching a whole slice
        instance, so they are planned as units, never as loose bundles.
        The strategy matters: SPREAD gangs need hosts >= bundles."""
        with self.rt.lock:
            return [([dict(b.resources) for b in pg.bundles], pg.strategy)
                    for pg in self.rt.pgs.values()
                    if pg.state == "pending" and pg.same_label]

    def _free_capacity(self) -> list[dict]:
        """Per-alive-node free resources (head + agents)."""
        return [dict(row["Available"]) for row in self.rt.node_table()
                if row["Alive"]]

    # -- the decision step --------------------------------------------- #

    def plan(self) -> tuple[dict[str, int], list[str]]:
        """One reconcile decision: ({type: count to launch},
        [instance ids to terminate])."""
        demands = self.pending_demands()
        gangs = self.pending_gangs()
        booting_types = [tname for iid, tname in self.instances.items()
                         if self.provider.node_id_of(iid) is None]
        live_by_type: dict[str, int] = {}
        for iid, tname in self.instances.items():
            live_by_type[tname] = live_by_type.get(tname, 0) + 1
        to_launch = plan_scaling(self.node_types, demands, gangs,
                                 self._free_capacity(), booting_types,
                                 live_by_type)
        to_terminate = self._find_idle() if not (demands or gangs) else []
        return to_launch, to_terminate

    def _find_idle(self) -> list[str]:
        rt = self.rt
        now = time.monotonic()
        out = []
        busy_hex = busy_node_hexes(rt)
        live_by_type: dict[str, int] = {}
        for iid, tname in self.instances.items():
            live_by_type[tname] = live_by_type.get(tname, 0) + 1
        for iid, tname in list(self.instances.items()):
            nid = self.provider.node_id_of(iid)
            if nid is None:  # still booting
                self._idle_since.pop(iid, None)
                continue
            # a slice instance is idle only when EVERY host is idle
            if any(h in busy_hex for h in self.provider.nodes_of(iid)):
                self._idle_since.pop(iid, None)
                continue
            first = self._idle_since.setdefault(iid, now)
            t = self.node_types[tname]
            if now - first >= self.idle_timeout_s and \
                    live_by_type.get(tname, 0) > t.min_workers:
                out.append(iid)
                live_by_type[tname] -= 1
        return out

    # -- actuation ------------------------------------------------------ #

    def reconcile_once(self) -> None:
        to_launch, to_terminate = self.plan()
        for tname, n in to_launch.items():
            t = self.node_types[tname]
            for _ in range(n):
                iid = self.provider.create_slice(
                    tname, dict(t.resources), t.hosts,
                    dict(t.labels) if t.labels else None)
                self.instances[iid] = tname
                self._launched_at[iid] = time.monotonic()
                self.events.append({"event": "launch", "type": tname,
                                    "hosts": t.hosts,
                                    "instance": iid, "ts": time.time()})
        for iid in to_terminate:
            nid = self.provider.node_id_of(iid)
            self.provider.terminate_node(iid)
            self.instances.pop(iid, None)
            self._idle_since.pop(iid, None)
            self.events.append({"event": "terminate", "instance": iid,
                                "node_id": nid, "ts": time.time()})
        # drop instances whose process died outside our control
        alive = set(self.provider.non_terminated_nodes())
        for iid in [i for i in self.instances if i not in alive]:
            self.instances.pop(iid, None)
            self._idle_since.pop(iid, None)

    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="rtpu-autoscaler")
            self._thread.start()
            _ACTIVE.append(self)
        return self

    def report(self) -> dict:
        """Instance table + recent events for the state API/dashboard."""
        rows = []
        for iid, tname in list(self.instances.items()):
            nid = self.provider.node_id_of(iid)
            rows.append({"instance": iid, "type": tname,
                         "state": "RUNNING" if nid else "BOOTING",
                         "node_id": nid})
        return {"version": 1, "instances": rows,
                "events": list(self.events[-100:])}

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.reconcile_once()
            except Exception:
                import traceback
                traceback.print_exc()

    def stop(self, terminate_nodes: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if terminate_nodes:
            self.provider.shutdown()
