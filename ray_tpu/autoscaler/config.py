"""Cluster scaling config -> a ready Autoscaler (the `ray up
cluster.yaml` role, reference: autoscaler/_private/commands.py +
the cluster YAML's available_node_types section, reduced to JSON and
TPU-first provider choices).

Schema (JSON, see tests/test_autoscaler_v2.py for an example):

    {
      "v2": true,                    # instance-manager reconciler (default)
      "idle_timeout_s": 60,
      "provider": {"type": "fake"},  # or {"type": "gce_tpu", ...ctor kw}
      "node_types": [
        {"name": "cpu4", "resources": {"CPU": 4},
         "min_workers": 0, "max_workers": 4},
        {"name": "v5e-16", "resources": {"CPU": 8, "TPU": 4},
         "hosts": 4, "max_workers": 2,
         "labels": {"pool": "train"}}
      ]
    }

The gce_tpu provider's head_address/authkey_hex are filled from the
running head when omitted, so one config file works for `ray_tpu.cli
start --head --autoscale-config cfg.json`.
"""
from __future__ import annotations

import json
from typing import Optional

from .autoscaler import Autoscaler, NodeTypeConfig


def autoscaler_from_config(config, runtime=None):
    """Build (NOT start) an Autoscaler/AutoscalerV2 from a config dict or
    a path to a JSON file."""
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict) or "node_types" not in config:
        raise ValueError("autoscale config needs a node_types list")
    types = [NodeTypeConfig(
        name=t["name"], resources=dict(t["resources"]),
        min_workers=int(t.get("min_workers", 0)),
        max_workers=int(t.get("max_workers", 4)),
        hosts=int(t.get("hosts", 1)),
        labels=t.get("labels")) for t in config["node_types"]]
    provider = _provider_from_config(config.get("provider"), runtime)
    kwargs = {k: config[k] for k in
              ("idle_timeout_s", "period_s") if k in config}
    if config.get("v2", True):
        from .v2 import AutoscalerV2
        for k in ("allocation_timeout_s", "max_allocation_retries",
                  "retry_backoff_s"):
            if k in config:
                kwargs[k] = config[k]
        return AutoscalerV2(types, provider=provider, runtime=runtime,
                            **kwargs)
    return Autoscaler(types, provider=provider, runtime=runtime, **kwargs)


def _provider_from_config(pcfg: Optional[dict], runtime):
    if pcfg is None:
        pcfg = {"type": "fake"}
    pcfg = dict(pcfg)
    kind = pcfg.pop("type", "fake")
    if kind == "fake":
        from .node_provider import FakeNodeProvider
        return FakeNodeProvider(runtime)
    if kind == "gce_tpu":
        from ..core import runtime as rt_mod

        from .gce_tpu import GceTpuVmProvider
        rt = runtime or rt_mod.get_runtime_if_exists()
        if rt is not None:
            # the address TPU-VM agents dial back to (host_ip-based, NOT
            # gethostbyname(hostname) which commonly resolves to
            # loopback); override in the config when behind NAT
            pcfg.setdefault("head_address", rt.head_address)
            pcfg.setdefault("authkey_hex", rt._authkey.hex())
        return GceTpuVmProvider(**pcfg)
    raise ValueError(f"unknown provider type {kind!r}")
