"""GCE TPU-VM node provider: real slice provisioning over `gcloud`.

Reference parity: autoscaler/_private/gcp/node_provider.py (GCPNodeProvider
create/terminate over the compute API, with TPU pods routed to the TPU API)
+ tpu_command_runner.py (TPUCommandRunner fans setup commands to every
worker of a pod with `--worker=all`). TPU inversion: one provider instance
call = one whole slice (the TPU API has no single-host create for pods),
and bootstrap is a single agent start command per worker rather than the
reference's multi-stage rsync/setup pipeline — TPU VM images already carry
the runtime, so bootstrap only needs the cluster address + labels.

All gcloud interaction goes through an injectable `runner` (signature of
``subprocess.run``) so the control flow is unit-testable with no cloud
access; the default runner shells out to the real CLI.
"""
from __future__ import annotations

import json
import shlex
import subprocess
import threading
from typing import Callable, Optional

from .node_provider import NodeProvider


class GceTpuVmProvider(NodeProvider):
    """Provisions TPU-VM slices with `gcloud compute tpus tpu-vm`."""

    def __init__(self,
                 project: str,
                 zone: str,
                 head_address: str,
                 authkey_hex: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 hosts_per_slice: Optional[int] = None,
                 chips_per_host: int = 4,
                 bootstrap_command: str = "",
                 runtime=None,
                 runner: Optional[Callable] = None):
        from ..core import runtime as rt_mod
        self._rt = runtime or rt_mod.get_runtime_if_exists()
        self.project = project
        self.zone = zone
        self.head_address = head_address
        self.authkey_hex = authkey_hex
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        # hosts per slice: derived generation-aware (v4/v5p type suffixes
        # count TensorCores, v5e/v6e count chips — util/tpu.slice_hosts)
        from ..util.tpu import slice_hosts
        self.chips_per_host = chips_per_host
        if hosts_per_slice is None:
            hosts_per_slice = slice_hosts(accelerator_type, chips_per_host)
        self.hosts_per_slice = hosts_per_slice
        self.bootstrap_command = bootstrap_command
        self._run = runner or self._default_runner
        self._lock = threading.Lock()
        self._instances: dict[str, int] = {}   # name -> hosts
        self._seq = 0

    @staticmethod
    def _default_runner(cmd: list[str], **kw):
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=kw.pop("timeout", 900), **kw)

    def _gcloud(self, *args: str) -> list[str]:
        return ["gcloud", "compute", "tpus", "tpu-vm", *args,
                "--project", self.project, "--zone", self.zone]

    def _check(self, cmd: list[str]):
        res = self._run(cmd)
        rc = getattr(res, "returncode", 0)
        if rc != 0:
            raise RuntimeError(
                f"gcloud failed rc={rc}: {' '.join(cmd)}\n"
                f"{getattr(res, 'stderr', '')}")
        return res

    # -- NodeProvider surface ------------------------------------------- #

    def create_node(self, node_type: str, resources: dict,
                    labels: Optional[dict] = None) -> str:
        return self.create_slice(node_type, resources, 1, labels)

    def create_slice(self, node_type: str, resources: dict, hosts: int,
                     labels: Optional[dict] = None) -> str:
        from ..util.tpu import SLICE_LABEL, WORKER_ID_LABEL
        if hosts > self.hosts_per_slice:
            raise ValueError(
                f"type {node_type} asks {hosts} hosts but "
                f"{self.accelerator_type} slices have {self.hosts_per_slice}")
        with self._lock:
            self._seq += 1
            name = f"rtpu-{node_type}-{self._seq}"
        self._check(self._gcloud(
            "create", name,
            "--accelerator-type", self.accelerator_type,
            "--version", self.runtime_version))
        # the slice exists from here on — record it BEFORE the ssh
        # bootstrap so a failed bootstrap still leaves it visible to
        # terminate_node/shutdown (no billing leak)
        with self._lock:
            self._instances[name] = self.hosts_per_slice
        # One agent per worker. $(TPU_WORKER_ID) is NOT available in the
        # ssh env, so each worker's id label comes from the TPU runtime env
        # the agent discovers itself (util/tpu.discover_tpu_labels); only
        # the slice identity is pinned here.
        node_labels = {**(labels or {}), SLICE_LABEL: name}
        res = dict(resources)
        res.setdefault("TPU", float(self.chips_per_host))
        agent_cmd = (
            f"{self.bootstrap_command} python -m ray_tpu.core.node_agent"
            f" --head {shlex.quote(self.head_address)}"
            f" --authkey {self.authkey_hex}"
            f" --num-cpus {res.get('CPU', 1)}"
            f" --resources {shlex.quote(json.dumps({k: v for k, v in res.items() if k != 'CPU'}))}"
            f" --labels {shlex.quote(json.dumps(node_labels))}"
            f" --name {name}-w$(grep -oP '(?<=worker-id: )\\d+' /etc/tpu-env 2>/dev/null || echo 0)"
            f" --own-store"
        ).strip()
        self._check(self._gcloud(
            "ssh", name, "--worker=all",
            "--command", f"nohup {agent_cmd} >/tmp/rtpu_agent.log 2>&1 &"))
        return name

    def terminate_node(self, instance_id: str) -> None:
        # delete FIRST: only forget the instance once gcloud confirmed, so
        # a transient failure leaves it tracked for a retried terminate
        self._check(self._gcloud("delete", instance_id, "--quiet"))
        with self._lock:
            self._instances.pop(instance_id, None)

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            return list(self._instances)

    def _registered(self, instance_id: str) -> list[str]:
        if self._rt is None:
            return []
        return [row["NodeID"] for row in self._rt.node_table()
                if row["Alive"]
                and row["NodeName"].startswith(instance_id + "-w")]

    def node_id_of(self, instance_id: str) -> Optional[str]:
        with self._lock:
            hosts = self._instances.get(instance_id, 0)
        nids = self._registered(instance_id)
        if hosts and len(nids) >= hosts:
            return sorted(nids)[0]
        return None

    def nodes_of(self, instance_id: str) -> list[str]:
        return self._registered(instance_id)
