"""Node providers: how the autoscaler actually obtains hosts.

Reference parity: autoscaler NodeProvider ABC
(autoscaler/node_provider.py) + the fake multi-node provider used by the
reference's own tests (fake_multi_node/node_provider.py:236 — real raylet
processes on one machine posing as separate nodes).

Here a "node" is a node-agent process joined to the head over TCP
(core/node_agent.py), so the fake provider launches REAL agents — the
whole control path (register → schedule → spawn workers → heartbeat →
remove on death) is exercised, not mocked. A cloud provider would replace
``_launch`` with its instance API and run the agent via startup script.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Optional


class NodeProvider:
    """Minimal provider surface the autoscaler drives."""

    def create_node(self, node_type: str, resources: dict) -> str:
        """Launch a node of `node_type`; returns a provider instance id."""
        raise NotImplementedError

    def terminate_node(self, instance_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_id_of(self, instance_id: str) -> Optional[str]:
        """Cluster NodeID hex once the instance registered, else None."""
        raise NotImplementedError

    def shutdown(self) -> None:
        for iid in list(self.non_terminated_nodes()):
            self.terminate_node(iid)


class FakeNodeProvider(NodeProvider):
    """Spawns real node agents as local subprocesses."""

    def __init__(self, runtime=None):
        from ..core import runtime as rt_mod
        self._rt = runtime or rt_mod.get_runtime_if_exists()
        if self._rt is None:
            raise RuntimeError("ray_tpu.init() first")
        self._lock = threading.Lock()
        self._procs: dict[str, subprocess.Popen] = {}
        self._node_ids: dict[str, str] = {}
        self._seq = 0

    def create_node(self, node_type: str, resources: dict) -> str:
        with self._lock:
            self._seq += 1
            iid = f"fake-{node_type}-{self._seq}"
        rt = self._rt
        env = dict(os.environ)
        env["RTPU_AUTHKEY"] = rt._authkey.hex()
        extra = {k: v for k, v in resources.items() if k != "CPU"}
        args = [sys.executable, "-m", "ray_tpu.core.node_agent",
                "--head", f"127.0.0.1:{rt.tcp_port}",
                "--num-cpus", str(resources.get("CPU", 1)),
                "--resources", json.dumps(extra),
                "--name", iid]
        log = open(os.path.join(rt.session_dir, f"agent-{iid}.log"), "wb")
        proc = subprocess.Popen(args, env=env, stdout=log,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        log.close()
        with self._lock:
            self._procs[iid] = proc
        return iid

    def node_id_of(self, instance_id: str) -> Optional[str]:
        with self._lock:
            nid = self._node_ids.get(instance_id)
            if nid is not None:
                return nid
        # resolve by the node name the agent registered with
        for row in self._rt.node_table():
            if row["NodeName"] == instance_id and row["Alive"]:
                with self._lock:
                    self._node_ids[instance_id] = row["NodeID"]
                return row["NodeID"]
        return None

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            proc = self._procs.pop(instance_id, None)
            self._node_ids.pop(instance_id, None)
        if proc is not None:
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            dead = [iid for iid, p in self._procs.items()
                    if p.poll() is not None]
            for iid in dead:
                self._procs.pop(iid)
                self._node_ids.pop(iid, None)
            return list(self._procs)
