"""Node providers: how the autoscaler actually obtains hosts.

Reference parity: autoscaler NodeProvider ABC
(autoscaler/node_provider.py) + the fake multi-node provider used by the
reference's own tests (fake_multi_node/node_provider.py:236 — real raylet
processes on one machine posing as separate nodes).

Here a "node" is a node-agent process joined to the head over TCP
(core/node_agent.py), so the fake provider launches REAL agents — the
whole control path (register → schedule → spawn workers → heartbeat →
remove on death) is exercised, not mocked. A cloud provider would replace
``_launch`` with its instance API and run the agent via startup script.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Optional


class NodeProvider:
    """Minimal provider surface the autoscaler drives."""

    def create_node(self, node_type: str, resources: dict,
                    labels: Optional[dict] = None) -> str:
        """Launch a node of `node_type`; returns a provider instance id."""
        raise NotImplementedError

    def create_slice(self, node_type: str, resources: dict, hosts: int,
                     labels: Optional[dict] = None) -> str:
        """Launch one multi-host accelerator slice (all hosts share the
        instance id and any slice-identity labels). A cloud TPU provider
        creates the whole slice in one API call; the default is only valid
        for single-host types."""
        if hosts != 1:
            raise NotImplementedError(
                f"{type(self).__name__} cannot launch {hosts}-host slices")
        return self.create_node(node_type, resources, labels)

    def terminate_node(self, instance_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_id_of(self, instance_id: str) -> Optional[str]:
        """Cluster NodeID hex once the instance registered, else None.
        Multi-host instances report None until EVERY host registered."""
        raise NotImplementedError

    def nodes_of(self, instance_id: str) -> list[str]:
        """All cluster NodeID hexes belonging to the instance (one per
        host). Default: the single node_id_of."""
        nid = self.node_id_of(instance_id)
        return [nid] if nid is not None else []

    def shutdown(self) -> None:
        for iid in list(self.non_terminated_nodes()):
            self.terminate_node(iid)


class FakeNodeProvider(NodeProvider):
    """Spawns real node agents as local subprocesses. Multi-host slices
    launch `hosts` agents sharing one instance id and one slice label —
    the fake analog of a TPU pod slice (reference:
    fake_multi_node/node_provider.py:236)."""

    def __init__(self, runtime=None):
        from ..core import runtime as rt_mod
        self._rt = runtime or rt_mod.get_runtime_if_exists()
        if self._rt is None:
            raise RuntimeError("ray_tpu.init() first")
        self._lock = threading.Lock()
        # iid -> {host name -> Popen}
        self._procs: dict[str, dict[str, subprocess.Popen]] = {}
        self._node_ids: dict[str, dict[str, str]] = {}
        self._seq = 0

    def create_node(self, node_type: str, resources: dict,
                    labels: Optional[dict] = None) -> str:
        return self.create_slice(node_type, resources, 1, labels)

    def create_slice(self, node_type: str, resources: dict, hosts: int,
                     labels: Optional[dict] = None) -> str:
        from ..util.tpu import SLICE_LABEL, WORKER_ID_LABEL
        with self._lock:
            self._seq += 1
            iid = f"fake-{node_type}-{self._seq}"
        rt = self._rt
        env = dict(os.environ)
        env["RTPU_AUTHKEY"] = rt._authkey.hex()
        extra = {k: v for k, v in resources.items() if k != "CPU"}
        base_labels = dict(labels or {})
        if hosts > 1:
            base_labels.setdefault(SLICE_LABEL, iid)
        procs: dict[str, subprocess.Popen] = {}
        for h in range(hosts):
            name = iid if hosts == 1 else f"{iid}-h{h}"
            node_labels = dict(base_labels)
            if hosts > 1:
                node_labels[WORKER_ID_LABEL] = str(h)
            args = [sys.executable, "-m", "ray_tpu.core.node_agent",
                    "--head", f"127.0.0.1:{rt.tcp_port}",
                    "--num-cpus", str(resources.get("CPU", 1)),
                    "--resources", json.dumps(extra),
                    "--labels", json.dumps(node_labels),
                    "--name", name]
            log = open(os.path.join(rt.session_dir, f"agent-{name}.log"),
                       "wb")
            procs[name] = subprocess.Popen(args, env=env, stdout=log,
                                           stderr=subprocess.STDOUT,
                                           start_new_session=True)
            log.close()
        with self._lock:
            self._procs[iid] = procs
        return iid

    def _resolve_locked(self, instance_id: str) -> dict[str, str]:
        """host name -> NodeID hex for every registered host so far."""
        known = self._node_ids.setdefault(instance_id, {})
        names = set(self._procs.get(instance_id, ())) - set(known)
        if names:
            for row in self._rt.node_table():
                if row["NodeName"] in names and row["Alive"]:
                    known[row["NodeName"]] = row["NodeID"]
        return known

    def node_id_of(self, instance_id: str) -> Optional[str]:
        with self._lock:
            procs = self._procs.get(instance_id)
            if not procs:
                return None
            known = self._resolve_locked(instance_id)
            if len(known) < len(procs):
                return None  # still booting (multi-host: ALL must join)
            first = sorted(procs)[0]
            return known.get(first)

    def nodes_of(self, instance_id: str) -> list[str]:
        with self._lock:
            return list(self._resolve_locked(instance_id).values())

    def terminate_node(self, instance_id: str) -> None:
        with self._lock:
            procs = self._procs.pop(instance_id, None) or {}
            self._node_ids.pop(instance_id, None)
        for proc in procs.values():
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                proc.kill()
        for proc in procs.values():
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            dead = [iid for iid, procs in self._procs.items()
                    if procs and all(p.poll() is not None
                                     for p in procs.values())]
            for iid in dead:
                self._procs.pop(iid)
                self._node_ids.pop(iid, None)
            return list(self._procs)
