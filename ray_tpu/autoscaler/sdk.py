"""Programmatic autoscaler requests.

Reference parity: ray.autoscaler.sdk.request_resources
(python/ray/autoscaler/sdk/sdk.py) — ask the cluster to scale to
accommodate a resource shape immediately, without queueing workloads
first (pre-warming before a burst, holding capacity between jobs). The
last call REPLACES the standing request; calling with no arguments
clears it. Bundles already covered by free capacity launch nothing (the
planner subtracts live free capacity), and a standing request also
holds off idle scale-down — it is a floor, not a one-shot.
"""
from __future__ import annotations

from typing import Optional


def request_resources(num_cpus: Optional[int] = None,
                      bundles: Optional[list[dict]] = None) -> None:
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    if rt is None:
        raise RuntimeError("ray_tpu.init() first")
    req: list[dict] = []
    if num_cpus:
        # reference semantics: 'scale until N CPUs exist' — N unit
        # bundles, so any mix of node sizes can satisfy it (one {CPU: N}
        # bundle would demand a single N-CPU host)
        req.extend({"CPU": 1.0} for _ in range(int(num_cpus)))
    for b in bundles or ():
        if b:
            req.append({k: float(v) for k, v in b.items()})
    if isinstance(rt, rt_mod.Runtime):
        with rt.lock:
            rt.resource_requests = req
        return
    rt._rpc("request_resources_rpc", req)  # worker/driver: one head RPC
