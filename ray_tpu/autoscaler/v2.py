"""Autoscaler v2: the instance-manager rewrite.

Reference parity: autoscaler/v2/autoscaler.py:42 (update_autoscaling_state
— one reconcile over declared cluster state), instance_manager/
instance_manager.py:29 (a VERSIONED instance table mutated only through
update events, so concurrent reconcilers can't clobber each other) and
scheduler.py:632 (ResourceDemandScheduler — here the shared
`plan_scaling` bin-packer). TPU inversion: demand comes straight off the
head runtime's queues (no GCS/autoscaler RPC hop), and the instance
lifecycle is driven by a single reconciler thread per head, with the
versioned table there to make every transition observable, event-sourced
and crash-recoverable — not to coordinate multiple writers.

What v2 adds over v1's flat `instances` dict:
  - an explicit per-instance state machine
        QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                -> TERMINATING -> TERMINATED
    with ALLOCATION_FAILED + bounded backoff retry on the request edge
    (v1 called the provider inline and a raising provider lost the
    launch: the demand re-planned from scratch next tick, with no retry
    budget or failure record);
  - event-sourced transitions: every instance carries its full
    (ts, from, to, reason) history, mirrored into a global event log;
  - crash-safe persistence: the table journals to a JSON file in the
    session dir and a restarted head resumes instance bookkeeping
    (provider drift is then reconciled against reality);
  - drift detection: instances the provider no longer reports move to
    TERMINATED with reason "provider-lost"; min_workers then relaunches
    through the normal QUEUED path.

Known limitation: provider objects keep their fleet membership in
process memory, so after a head restart pre-restart nodes are no longer
under instance management — they re-join the cluster (head-restart
survivability) and their capacity is planned against, but idle
scale-down can't reclaim them and a min_workers floor counts only
managed instances (it may launch fresh ones alongside orphans).
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from .autoscaler import (
    Autoscaler, NodeTypeConfig, busy_node_hexes, plan_scaling,
)
from .node_provider import NodeProvider

# instance lifecycle states (reference: instance_manager/common.py's
# Instance proto states, collapsed to the ones a TPU head drives)
QUEUED = "QUEUED"                      # decided, not yet asked of provider
REQUESTED = "REQUESTED"                # provider call issued
ALLOCATED = "ALLOCATED"                # provider reports hosts exist
RAY_RUNNING = "RAY_RUNNING"            # every host registered with head
TERMINATING = "TERMINATING"            # terminate issued
TERMINATED = "TERMINATED"              # gone (terminal)
ALLOCATION_FAILED = "ALLOCATION_FAILED"  # create failed; retry w/ backoff

_TERMINAL = {TERMINATED}
# ALLOCATION_FAILED counts for planning: it holds a retry slot, so the
# min_workers floor must not launch a duplicate while it waits to retry
# (nor may retries push the total past max_workers)
_LIVE_FOR_PLANNING = (QUEUED, REQUESTED, ALLOCATED, RAY_RUNNING,
                      ALLOCATION_FAILED)

_VALID = {
    QUEUED: {REQUESTED, ALLOCATION_FAILED, TERMINATED},
    REQUESTED: {ALLOCATED, RAY_RUNNING, ALLOCATION_FAILED, TERMINATING,
                TERMINATED},
    ALLOCATED: {RAY_RUNNING, ALLOCATION_FAILED, TERMINATING, TERMINATED},
    RAY_RUNNING: {TERMINATING, TERMINATED},
    TERMINATING: {TERMINATED},
    ALLOCATION_FAILED: {QUEUED, TERMINATED},
    TERMINATED: set(),
}


@dataclasses.dataclass
class Instance:
    instance_id: str                   # manager-scoped logical id
    node_type: str
    state: str = QUEUED
    provider_id: Optional[str] = None  # set once the provider call returns
    version: int = 1                   # bumped on every applied update
    retries: int = 0                   # failed allocation attempts so far
    retry_after: float = 0.0           # monotonic ts gate for the retry
    queued_at: float = dataclasses.field(default_factory=time.monotonic)
    requested_at: float = 0.0
    events: list = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        # monotonic stamps don't survive a process restart; persist zeros
        # so a resumed manager re-times from its own clock
        d["queued_at"] = d["requested_at"] = d["retry_after"] = 0.0
        return d

    @classmethod
    def from_json(cls, d: dict) -> "Instance":
        return cls(**d)


class InstanceManager:
    """The versioned instance table. All mutation goes through
    `update()`, which enforces the state machine, optimistic versioning
    and the event journal, then persists (reference:
    instance_manager.py:29; its InstanceUpdateEvent becomes the update()
    call, its versioned InstanceStorage the journal file)."""

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._instances: dict[str, Instance] = {}
        self._seq = 0
        self._path = path
        self.events: list[dict] = []   # global mirror, for observability
        if path and os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
            self._seq = data["seq"]
            now = time.monotonic()
            for d in data["instances"]:
                inst = Instance.from_json(d)
                if inst.state in (REQUESTED, ALLOCATED):
                    # monotonic stamps were zeroed on persist; re-time the
                    # allocation-timeout clock from this process's clock
                    # (ALLOCATED included: a partially registered slice
                    # must still time out after a head restart)
                    inst.requested_at = now
                self._instances[inst.instance_id] = inst

    def _log_event(self, ev: dict) -> None:
        """Append to the bounded global event mirror."""
        self.events.append(ev)
        if len(self.events) > 4096:
            del self.events[:2048]

    # -- reads ---------------------------------------------------------- #

    def get(self, instance_id: str) -> Optional[Instance]:
        with self._lock:
            return self._instances.get(instance_id)

    def instances(self, *states: str) -> list[Instance]:
        with self._lock:
            out = list(self._instances.values())
        if states:
            out = [i for i in out if i.state in states]
        return out

    def live_by_type(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for i in self.instances(*_LIVE_FOR_PLANNING):
            out[i.node_type] = out.get(i.node_type, 0) + 1
        return out

    # -- writes --------------------------------------------------------- #

    def create(self, node_type: str) -> Instance:
        with self._lock:
            self._seq += 1
            inst = Instance(instance_id=f"im-{self._seq}",
                            node_type=node_type)
            ev = {"ts": time.time(), "from": None, "to": QUEUED,
                  "reason": "scale-up", "instance": inst.instance_id}
            inst.events.append(ev)
            self._log_event(ev)
            self._instances[inst.instance_id] = inst
            self._persist_locked()
            return inst

    def update(self, instance_id: str, new_state: str, *,
               expected_version: Optional[int] = None,
               reason: str = "", **fields) -> bool:
        """Apply one transition. Returns False (no mutation) when the
        transition is invalid for the current state or the caller's
        expected_version is stale — the optimistic-concurrency contract:
        read the instance, decide, update with its version."""
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst is None:
                return False
            if expected_version is not None and \
                    inst.version != expected_version:
                self._log_event({
                    "ts": time.time(), "instance": instance_id,
                    "rejected": True, "to": new_state, "reason":
                    f"stale version {expected_version} != {inst.version}"})
                return False
            if new_state != inst.state and \
                    new_state not in _VALID[inst.state]:
                self._log_event({
                    "ts": time.time(), "instance": instance_id,
                    "rejected": True, "to": new_state, "reason":
                    f"invalid transition {inst.state} -> {new_state}"})
                return False
            ev = {"ts": time.time(), "from": inst.state, "to": new_state,
                  "reason": reason, "instance": instance_id}
            inst.events.append(ev)
            self._log_event(ev)
            inst.state = new_state
            inst.version += 1
            for k, v in fields.items():
                setattr(inst, k, v)
            self._persist_locked()
            return True

    def prune_terminated(self, keep: int = 64) -> None:
        """Bound the table: keep only the newest `keep` TERMINATED rows
        (their event history stays in self.events)."""
        with self._lock:
            dead = sorted((i for i in self._instances.values()
                           if i.state in _TERMINAL),
                          key=lambda i: i.queued_at)
            for i in dead[:max(len(dead) - keep, 0)]:
                self._instances.pop(i.instance_id, None)
            self._persist_locked()

    def _persist_locked(self) -> None:
        if not self._path:
            return
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"seq": self._seq,
                       "instances": [i.to_json()
                                     for i in self._instances.values()]},
                      f)
        os.replace(tmp, self._path)


class AutoscalerV2:
    """Reconciler: demand -> desired instances -> lifecycle -> provider.

    Reads demand exactly like v1 (the head runtime's pending queues),
    plans with the shared bin-packer, but actuates through the
    InstanceManager's state machine instead of calling the provider
    inline, which is what buys retries, drift handling and a restartable
    table (reference: autoscaler/v2/autoscaler.py:42's
    update_autoscaling_state -> Reconciler.reconcile flow).
    """

    def __init__(self, node_types: list[NodeTypeConfig],
                 provider: Optional[NodeProvider] = None,
                 idle_timeout_s: float = 30.0,
                 period_s: float = 1.0,
                 allocation_timeout_s: float = 120.0,
                 max_allocation_retries: int = 3,
                 retry_backoff_s: float = 2.0,
                 runtime=None,
                 state_path: Optional[str] = None):
        from ..core import runtime as rt_mod
        self.rt = runtime or rt_mod.get_runtime_if_exists()
        if self.rt is None:
            raise RuntimeError("ray_tpu.init() first")
        if provider is None:
            from .node_provider import FakeNodeProvider
            provider = FakeNodeProvider(self.rt)
        self.provider = provider
        self.node_types = {t.name: t for t in node_types}
        self.idle_timeout_s = idle_timeout_s
        self.period_s = period_s
        self.allocation_timeout_s = allocation_timeout_s
        self.max_allocation_retries = max_allocation_retries
        self.retry_backoff_s = retry_backoff_s
        if state_path is None and getattr(self.rt, "session_dir", None):
            state_path = os.path.join(self.rt.session_dir,
                                      "autoscaler_v2_instances.json")
        self.im = InstanceManager(state_path)
        self._idle_since: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # demand collection is identical to v1's — reuse its methods
    pending_demands = Autoscaler.pending_demands
    pending_gangs = Autoscaler.pending_gangs
    _free_capacity = Autoscaler._free_capacity

    @property
    def events(self) -> list[dict]:
        return self.im.events

    # -- one reconcile pass --------------------------------------------- #

    def reconcile_once(self) -> None:
        self._sync_provider()
        self._plan_and_enqueue()
        self._drive_lifecycle()
        # bound table/journal growth under long-running churn
        self._ticks = getattr(self, "_ticks", 0) + 1
        if self._ticks % 60 == 0:
            self.im.prune_terminated()

    def _sync_provider(self) -> None:
        """Converge table state with provider + head reality: advance
        REQUESTED/ALLOCATED instances whose hosts showed up, and mark
        provider-lost instances TERMINATED (drift — e.g. a preempted TPU
        slice) so min_workers/demand relaunches them."""
        alive = set(self.provider.non_terminated_nodes())
        for inst in self.im.instances(REQUESTED, ALLOCATED, RAY_RUNNING,
                                      TERMINATING):
            if inst.provider_id is None:
                continue
            if inst.provider_id not in alive:
                self.im.update(inst.instance_id, TERMINATED,
                               reason="provider-lost"
                               if inst.state != TERMINATING else "terminated")
                self._idle_since.pop(inst.instance_id, None)
                continue
            if inst.state in (REQUESTED, ALLOCATED):
                if self.provider.node_id_of(inst.provider_id) is not None:
                    self.im.update(inst.instance_id, RAY_RUNNING,
                                   reason="all hosts registered")
                elif inst.requested_at and \
                        time.monotonic() - inst.requested_at > \
                        self.allocation_timeout_s:
                    # hung allocation — including a PARTIALLY registered
                    # slice stuck in ALLOCATED (one host never joins):
                    # reclaim and retry under the SAME bounded-backoff
                    # budget as a failed create. If the reclaim itself
                    # fails, stay put and retry it next tick — clearing
                    # provider_id after a failed terminate would leak a
                    # live, billing node with no row pointing at it.
                    try:
                        self.provider.terminate_node(inst.provider_id)
                    except Exception:
                        continue  # provider hiccup; next reconcile retries
                    self.im.update(
                        inst.instance_id, ALLOCATION_FAILED,
                        reason="allocation timeout", provider_id=None,
                        retries=inst.retries + 1,
                        retry_after=time.monotonic() +
                        self.retry_backoff_s * (2 ** inst.retries))
                elif inst.state == REQUESTED and \
                        self.provider.nodes_of(inst.provider_id):
                    self.im.update(inst.instance_id, ALLOCATED,
                                   reason="hosts allocating")

    def _plan_and_enqueue(self) -> None:
        demands = self.pending_demands()
        gangs = self.pending_gangs()
        booting = [i.node_type for i in self.im.instances(
            QUEUED, REQUESTED, ALLOCATED)]
        # ALLOCATION_FAILED instances about to retry also count as
        # booting capacity (they hold a retry slot), preventing a
        # launch-per-tick burst while one retries
        booting += [i.node_type for i in
                    self.im.instances(ALLOCATION_FAILED)]
        to_launch = plan_scaling(
            self.node_types, demands, gangs, self._free_capacity(),
            booting, self.im.live_by_type())
        for tname, n in to_launch.items():
            for _ in range(n):
                self.im.create(tname)
        if not demands and not gangs:
            for inst in self._find_idle():
                self.im.update(inst.instance_id, TERMINATING,
                               reason="idle timeout")

    def _drive_lifecycle(self) -> None:
        now = time.monotonic()
        for inst in self.im.instances(ALLOCATION_FAILED):
            if inst.retries >= self.max_allocation_retries:
                self.im.update(inst.instance_id, TERMINATED,
                               reason="allocation retries exhausted")
            elif now >= inst.retry_after:
                self.im.update(inst.instance_id, QUEUED,
                               reason=f"retry {inst.retries}")
        for inst in self.im.instances(QUEUED):
            t = self.node_types[inst.node_type]
            v = inst.version
            try:
                pid = self.provider.create_slice(
                    t.name, dict(t.resources), t.hosts,
                    dict(t.labels) if t.labels else None)
            except Exception as e:
                self.im.update(
                    inst.instance_id, ALLOCATION_FAILED,
                    expected_version=v, reason=f"create failed: {e}",
                    retries=inst.retries + 1,
                    retry_after=now + self.retry_backoff_s *
                    (2 ** inst.retries))
            else:
                self.im.update(inst.instance_id, REQUESTED,
                               expected_version=v, provider_id=pid,
                               requested_at=time.monotonic())
        for inst in self.im.instances(TERMINATING):
            if inst.provider_id is not None:
                try:
                    self.provider.terminate_node(inst.provider_id)
                except Exception:
                    # leave it TERMINATING: retried next tick (moving on
                    # would leak a live, billing provider node forever)
                    continue
            self.im.update(inst.instance_id, TERMINATED,
                           reason="terminated")
            self._idle_since.pop(inst.instance_id, None)

    def _find_idle(self) -> list[Instance]:
        busy_hex = busy_node_hexes(self.rt)
        now = time.monotonic()
        live = self.im.live_by_type()
        out = []
        for inst in self.im.instances(RAY_RUNNING):
            if any(h in busy_hex
                   for h in self.provider.nodes_of(inst.provider_id)):
                self._idle_since.pop(inst.instance_id, None)
                continue
            first = self._idle_since.setdefault(inst.instance_id, now)
            t = self.node_types[inst.node_type]
            if now - first >= self.idle_timeout_s and \
                    live.get(inst.node_type, 0) > t.min_workers:
                out.append(inst)
                live[inst.node_type] -= 1
        return out

    # -- loop ----------------------------------------------------------- #

    def start(self) -> "AutoscalerV2":
        from .autoscaler import _ACTIVE
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="rtpu-autoscaler-v2")
            self._thread.start()
            _ACTIVE.append(self)
        return self

    def report(self) -> dict:
        """Instance table + recent events for the state API/dashboard."""
        rows = [{"instance": i.instance_id, "type": i.node_type,
                 "state": i.state, "provider_id": i.provider_id,
                 "retries": i.retries, "version": i.version}
                for i in self.im.instances()]
        return {"version": 2, "instances": rows,
                "events": list(self.im.events[-100:])}

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.reconcile_once()
            except Exception:
                import traceback
                traceback.print_exc()

    def stop(self, terminate_nodes: bool = True):
        from .autoscaler import _ACTIVE
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self in _ACTIVE:
            _ACTIVE.remove(self)
        if terminate_nodes:
            self.provider.shutdown()
