"""Cluster + job CLI: ``python -m ray_tpu <command>``.

Reference parity: the `ray` CLI (python/ray/scripts/scripts.py — start/stop/
status) and the job CLI (dashboard/modules/job/cli.py — submit/list/status/
logs/stop).  The head here is one daemon process owning the whole control
plane (SURVEY.md §7 inversion: no per-node raylet zoo to supervise), so
`start --head` forks exactly one process and `start --address` runs a node
agent in the foreground.

Commands:
    start --head [--num-cpus N] [--num-tpus N] [--name NAME] [--block]
    start --address CLUSTER_FILE [--num-cpus N] ...   (join as a node agent)
    stop [--name NAME]
    status [--address ...]
    job submit [--working-dir DIR] [--env K=V ...] [--follow] -- CMD...
    job list | job status ID | job logs ID [--follow] | job stop ID
    state tasks|actors|nodes|objects|jobs  (state API, ray list analog)
    stack [--all]   (live thread stacks cluster-wide, ray stack analog)
    doctor          (summary + stuck tasks + deadlocks + stacks + memory)
    top [--window S] [--once]  (live serving table from the metrics TSDB)
    slo             (SLO burn-rate report; exit 1 when paging)
    cache [--top K] (prefix-cache heat map: hot chains, reclaimable
                     pages, per-tenant warmth — the cache heat plane)
    timeline --out FILE
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time


def _client(address):
    import ray_tpu
    info = ray_tpu.init(address=address or "auto")
    from .core import runtime as rt_mod
    return ray_tpu, rt_mod.get_runtime_if_exists(), info


# --------------------------------------------------------------------- #
# start / stop / status
# --------------------------------------------------------------------- #

def _cluster_pointer(name: str) -> str:
    return f"/tmp/ray_tpu/named_{name}.json"


def cmd_start(args) -> int:
    if args.head:
        if args.block:
            return _run_head(args)
        # fork a detached head daemon, wait for its cluster file
        cmd = [sys.executable, "-m", "ray_tpu.cli", "start", "--head",
               "--block", "--name", args.name]
        if args.num_cpus is not None:
            cmd += ["--num-cpus", str(args.num_cpus)]
        if args.num_tpus is not None:
            cmd += ["--num-tpus", str(args.num_tpus)]
        if args.enable_remote_nodes:
            cmd += ["--enable-remote-nodes"]
        if args.autoscale_config:
            cmd += ["--autoscale-config",
                    os.path.abspath(args.autoscale_config)]
        pointer = _cluster_pointer(args.name)
        if os.path.exists(pointer):
            with open(pointer) as f:
                old = json.load(f)
            if _alive(old.get("head_pid", -1)):
                print(f"cluster {args.name!r} already running "
                      f"(pid {old['head_pid']}); `stop` it first",
                      file=sys.stderr)
                return 1
            os.unlink(pointer)
        proc = subprocess.Popen(cmd, start_new_session=True,
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        deadline = time.time() + 30
        while time.time() < deadline:
            if os.path.exists(pointer):
                with open(pointer) as f:
                    info = json.load(f)
                print(f"head started (pid {proc.pid})")
                print(f"cluster file: {info['cluster_file']}")
                print("connect with: ray_tpu.init(address='auto')")
                return 0
            if proc.poll() is not None:
                print("head failed to start", file=sys.stderr)
                return 1
            time.sleep(0.1)
        print("timed out waiting for head", file=sys.stderr)
        return 1
    if args.address:
        # join as a node agent (foreground; daemonize with nohup/systemd)
        with open(args.address) as f:
            cf = json.load(f)
        from .core.node_agent import main as agent_main
        host = cf["tcp_host"]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        agent_args = ["--head", f"{host}:{cf['tcp_port']}",
                      "--authkey", cf["authkey"],
                      "--num-cpus", str(args.num_cpus or os.cpu_count())]
        if args.num_tpus:
            agent_args += ["--resources", json.dumps({"TPU": args.num_tpus})]
        return agent_main(agent_args) or 0
    print("start needs --head or --address", file=sys.stderr)
    return 2


def _run_head(args) -> int:
    import ray_tpu
    from .core import runtime as rt_mod
    ray_tpu.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                 **({"enable_remote_nodes": True}
                    if args.enable_remote_nodes else {}))
    rt = rt_mod.get_runtime_if_exists()
    asc = None
    if args.autoscale_config:
        from .autoscaler.config import autoscaler_from_config
        asc = autoscaler_from_config(args.autoscale_config).start()
    pointer = _cluster_pointer(args.name)
    os.makedirs(os.path.dirname(pointer), exist_ok=True)
    with open(pointer, "w") as f:
        json.dump({"cluster_file": rt.cluster_file,
                   "head_pid": os.getpid(), "name": args.name}, f)
    stop = {"flag": False}

    def _term(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        while not stop["flag"]:
            time.sleep(0.2)
    finally:
        try:
            os.unlink(pointer)
        except OSError:
            pass
        if asc is not None:
            asc.stop()
        ray_tpu.shutdown()
    return 0


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except (OSError, TypeError):
        return False


def cmd_stop(args) -> int:
    pointer = _cluster_pointer(args.name)
    if not os.path.exists(pointer):
        print(f"no cluster {args.name!r}", file=sys.stderr)
        return 1
    with open(pointer) as f:
        info = json.load(f)
    pid = info["head_pid"]
    if not _alive(pid):
        os.unlink(pointer)
        print("head already gone; cleaned up pointer")
        return 0
    os.kill(pid, signal.SIGTERM)
    deadline = time.time() + 15
    while time.time() < deadline:
        if not _alive(pid):
            print("stopped")
            return 0
        time.sleep(0.1)
    os.kill(pid, signal.SIGKILL)
    print("killed (did not stop in 15s)")
    return 0


def cmd_status(args) -> int:
    ray, rt, info = _client(args.address)
    res = rt.cluster_resources()
    avail = rt.available_resources()
    print(f"cluster: {info['address']}")
    for node in rt.node_table():
        state = "ALIVE" if node["Alive"] else "DEAD"
        print(f"  node {node['NodeName']:<12} {state:<6} "
              f"{node['Resources']}")
    print(f"resources: {res}")
    print(f"available: {avail}")
    jobs = _job_rpc(rt, "job_list")
    if jobs:
        print("jobs:")
        for j in jobs:
            print(f"  {j['job_id']:<10} {j['status']:<10} {j['entrypoint']}")
    ray.shutdown()
    return 0


# --------------------------------------------------------------------- #
# jobs
# --------------------------------------------------------------------- #

def _job_rpc(rt, method, *rpc_args):
    if hasattr(rt, "_rpc"):
        return rt._rpc(method, *rpc_args)
    return getattr(rt, method)(*rpc_args)


def cmd_job(args) -> int:
    ray, rt, _ = _client(args.address)
    try:
        if args.job_cmd == "submit":
            wd = None
            if args.working_dir:
                from .core.job_manager import pack_working_dir
                wd = pack_working_dir(args.working_dir)
            env = {}
            for kv in args.env or []:
                k, _, v = kv.partition("=")
                env[k] = v
            import shlex
            entrypoint = shlex.join(args.entrypoint)
            job_id = _job_rpc(rt, "job_submit", entrypoint, env, wd,
                              {"submitted_via": "cli"}, args.job_id)
            print(f"submitted {job_id}")
            if args.follow:
                return _follow(rt, job_id)
            return 0
        if args.job_cmd == "list":
            for j in _job_rpc(rt, "job_list"):
                print(f"{j['job_id']:<10} {j['status']:<10} "
                      f"{j['entrypoint']}")
            return 0
        if args.job_cmd == "status":
            print(json.dumps(_job_rpc(rt, "job_status", args.id), indent=2))
            return 0
        if args.job_cmd == "logs":
            if args.follow:
                return _follow(rt, args.id)
            sys.stdout.write(_job_rpc(rt, "job_logs", args.id))
            return 0
        if args.job_cmd == "stop":
            stopped = _job_rpc(rt, "job_stop", args.id)
            print("stopped" if stopped else "already finished")
            return 0
        print(f"unknown job command {args.job_cmd!r}", file=sys.stderr)
        return 2
    finally:
        ray.shutdown()


def _follow(rt, job_id: str) -> int:
    offset = 0  # byte cursor into the driver log (not capped by the
    while True:  # default tail window, so >1MB logs keep streaming)
        chunk = _job_rpc(rt, "job_logs", job_id, 1 << 20, offset)
        if chunk:
            sys.stdout.write(chunk)
            sys.stdout.flush()
            offset += len(chunk.encode(errors="replace"))
        st = _job_rpc(rt, "job_status", job_id)
        if st["status"] not in ("PENDING", "RUNNING"):
            chunk = _job_rpc(rt, "job_logs", job_id, 1 << 20, offset)
            if chunk:
                sys.stdout.write(chunk)
            print(f"\n--- job {job_id} {st['status']} ---")
            return 0 if st["status"] == "SUCCEEDED" else 1
        time.sleep(0.5)


# --------------------------------------------------------------------- #
# state / timeline
# --------------------------------------------------------------------- #

def cmd_serve(args) -> int:
    """`serve run module:app` (reference: the serve CLI)."""
    import importlib

    ray, rt, _ = _client(args.address)
    from . import serve as serve_api
    mod_name, _, attr = args.target.partition(":")
    if not attr:
        print("target must be module.path:app_variable", file=sys.stderr)
        return 2
    sys.path.insert(0, os.getcwd())
    mod = importlib.import_module(mod_name)
    # the app's module only exists on THIS machine: ship its code by
    # value so replicas never try to import it (the jobs path solves the
    # same problem with working_dir)
    import cloudpickle
    cloudpickle.register_pickle_by_value(mod)
    app = getattr(mod, attr)
    serve_api.run(app, name=args.name, route_prefix=args.route_prefix,
                  http_port=args.http_port, num_proxies=args.proxies)
    from .core.config import cfg as _cfg
    # same default resolution run() applies, so the banner matches the
    # ports actually listening
    n = max(1, args.proxies if args.proxies is not None
            else _cfg.serve_num_proxies)
    ports = f"{args.http_port}" if n == 1 else \
        f"{args.http_port}..{args.http_port + n - 1}"
    print(f"serving {args.target!r} as app {args.name!r} on "
          f"http://127.0.0.1:{{{ports}}} ({n} proxies, Ctrl-C to stop)")
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        serve_api.shutdown()
        ray.shutdown()
        return 0


def cmd_state(args) -> int:
    ray, rt, _ = _client(args.address)
    try:
        if args.kind == "jobs":
            rows = _job_rpc(rt, "job_list")
        else:
            from . import state as state_api
            rows = getattr(state_api, f"list_{args.kind}")()
        print(json.dumps(rows, indent=2, default=str))
        return 0
    finally:
        ray.shutdown()


def cmd_memory(args) -> int:
    """`ray memory` analog: per-object reference breakdown + store
    totals (reference: scripts.py memory command)."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_api
        m = state_api.memory_summary(limit=args.limit)
        st = m["object_store"]
        print(f"object store: {st['bytes_in_use']:,} / "
              f"{st['capacity']:,} bytes in {st['num_objects']} objects "
              f"({st['evictions']} evictions); "
              f"{m['num_objects_tracked']} tracked, "
              f"{m['num_transfer_pins']} transfer pins, "
              f"{m['num_task_arg_refs']} task-arg refs")
        for r in m["objects"]:
            flags = "".join((
                "P" if r["pinned"] else "-",
                "S" if r["in_store"] else "-",
                "D" if r["spilled"] else "-",
                "L" if r["reconstructable"] else "-"))
            holders = ",".join(r["ref_holders"][:4])
            if r["num_refs"] > 4:
                holders += f",+{r['num_refs'] - 4}"
            print(f"{r['object_id'][:16]}  {r['state']:<8} {flags}  "
                  f"refs={r['num_refs']:<3} pins={r['transfer_pins']:<2} "
                  f"contains={r['contains']:<3} {holders}")
        return 0
    finally:
        ray.shutdown()


def cmd_stack(args) -> int:
    """`ray stack` analog: live thread stacks of every process in the
    cluster (head, workers, drivers), annotated with the task each
    thread runs and the object/channel a parked thread waits on. The
    default view hides idle bookkeeping threads; --all shows every
    thread."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_mod
        from .core import stacks as stacks_mod
        report = state_mod.stack_report()
        print(stacks_mod.format_report(report, show_all=args.all))
        return 0
    finally:
        ray.shutdown()


def cmd_doctor(args) -> int:
    """One-shot stall diagnosis: cluster summary + hang report (stuck
    tasks with attached stacks, wait-graph deadlocks, watchdog health)
    + live stacks + memory pressure, in that order — the first page of
    every "why is my job hung" investigation."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_mod
        from .core import stacks as stacks_mod
        s = state_mod.summary()
        print("== cluster ==")
        print(f"nodes {s['nodes_alive']} | workers {s['workers']} | "
              f"actors {s['actors']} | pending tasks {s['pending_tasks']}")
        print(f"tasks by state: {s['tasks_by_state']}")
        st = s["object_store"]
        print(f"object store: {st['bytes_in_use']:,}/{st['capacity']:,} "
              f"bytes in {st['num_objects']} objects "
              f"({st['evictions']} evictions)")
        print("\n== hangs ==")
        hangs = state_mod.hang_report()
        print(stacks_mod.format_hangs(hangs))
        print("\n== stacks ==")
        # reuse the snapshots the hang diagnosis already collected: one
        # cluster-wide pull serves both sections
        print(stacks_mod.format_report(hangs, show_all=False))
        print("== memory ==")
        m = state_mod.memory_summary(limit=10)
        print(f"{m['num_objects_tracked']} objects tracked, "
              f"{m['num_transfer_pins']} transfer pins, "
              f"{m['num_task_arg_refs']} task-arg refs")
        # non-zero exit when something is wrong, so scripts can gate on it
        return 1 if (hangs["stuck_tasks"] or hangs["deadlocks"]) else 0
    finally:
        ray.shutdown()


def _top_frame(state_mod, window_s: float) -> str:
    """One rendered `top` frame: per-deployment live table out of the
    head TSDB (rates and windowed quantiles, not spot reads)."""
    def last_by_dep(hist):
        """(app, deployment) -> max of each matching series' newest
        sample (gauges are one series per deployment; max covers
        stragglers from a replaced series)."""
        out = {}
        for s in hist["series"]:
            if not s["points"]:
                continue
            key = dict(s["key"])
            k = (key.get("app", ""), key.get("deployment", ""))
            out[k] = max(out.get(k, 0.0), s["points"][-1][1])
        return out

    def by_group(hist, field):
        """(app, deployment) -> server-computed per-group aggregate."""
        out = {}
        for row in hist.get("groups", []):
            k = (row["key"].get("app", ""),
                 row["key"].get("deployment", ""))
            out[k] = row.get(field)
        return out

    GB = ("app", "deployment")
    # windowed queries even for last-value reads: without a window the
    # head materializes + pickles every retained point (up to
    # retention_points per series) just for points[-1]
    replicas = last_by_dep(state_mod.metrics_history(
        "rtpu_serve_replicas", None, window_s))
    ongoing = last_by_dep(state_mod.metrics_history(
        "rtpu_serve_queue_depth", None, window_s))
    deps = sorted(set(replicas) | set(ongoing))
    lines = []
    ttft = state_mod.metrics_history(
        "rtpu_llm_ttft_seconds", None, window_s,
        quantiles=(0.5, 0.95))["quantiles"]
    slo = state_mod.slo_report()
    states = slo.get("states", {})
    badge = " ".join(f"{n}:{s}" for n, s in sorted(states.items())) \
        or "(no slos evaluated yet)"
    t95 = ttft.get("0.95")
    lines.append(
        f"cluster ttft p50/p95 = "
        f"{_ms(ttft.get('0.5'))}/{_ms(t95)}  |  slo: {badge}")
    lines.append(f"{'deployment':<28}{'repl':>5}{'ongoing':>8}"
                 f"{'rps':>8}{'p95 ms':>8}{'shed/s':>8}{'queued':>8}")
    # one RPC per COLUMN (server-side group_by), not one per deployment:
    # a 50-deployment cluster renders a frame in the same ~7 round-trips
    # as a 1-deployment one
    rps_by = by_group(state_mod.metrics_history(
        "rtpu_serve_replica_requests_total", None, window_s,
        group_by=GB), "rate_per_s")
    shed_by = by_group(state_mod.metrics_history(
        "rtpu_serve_admission_shed_total", None, window_s,
        group_by=GB), "rate_per_s")
    p95_by = by_group(state_mod.metrics_history(
        "rtpu_serve_replica_latency_seconds", None, window_s,
        quantiles=(0.95,), group_by=GB), "quantiles")
    queued_by: dict = {}
    for s in state_mod.metrics_history(
            "rtpu_serve_tenant_queued", None, window_s)["series"]:
        if s["points"]:
            key = dict(s["key"])
            k = (key.get("app", ""), key.get("deployment", ""))
            queued_by[k] = queued_by.get(k, 0.0) + s["points"][-1][1]
    for app, dep in deps:
        k = (app, dep)
        p95 = (p95_by.get(k) or {}).get("0.95")
        lines.append(f"{app + '/' + dep:<28}"
                     f"{replicas.get(k, 0):>5.0f}"
                     f"{ongoing.get(k, 0):>8.0f}"
                     f"{rps_by.get(k) or 0.0:>8.2f}{_ms(p95):>8}"
                     f"{shed_by.get(k) or 0.0:>8.2f}"
                     f"{queued_by.get(k, 0.0):>8.0f}")
    if not deps:
        lines.append("(no serve deployments reporting)")
    return "\n".join(lines)


def _ms(v) -> str:
    return "-" if v is None else f"{v * 1e3:.0f}"


def cmd_top(args) -> int:
    """Live refreshing cluster serving table (`top` for deployments):
    replicas, ongoing, RPS, windowed p95, shed rate and admission queue
    depth per deployment — every number a TSDB rate/quantile over
    --window seconds, so it reads as a trendline, not a spot sample."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_mod
        while True:
            try:
                frame = _top_frame(state_mod, args.window)
            except RuntimeError as e:
                # clusters started with tsdb_enable=0 have no history
                print(f"cli top needs the metrics TSDB: {e}",
                      file=sys.stderr)
                return 1
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        ray.shutdown()


def cmd_slo(args) -> int:
    """SLO burn-rate report: per objective the alert state, fast/slow
    window burn rates and error budget. Exit 1 when anything is paging
    so scripts can gate on it (the `cli doctor` convention)."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_mod
        try:
            rep = state_mod.slo_report()
        except RuntimeError as e:
            # clusters started with tsdb_enable=0 have no SLO engine
            print(f"cli slo needs the metrics TSDB: {e}",
                  file=sys.stderr)
            return 1
        rows = rep.get("slos", [])
        if not rows:
            print("(slo engine has not evaluated yet — is "
                  "cfg.tsdb_enable on?)")
            return 0
        print(f"{'slo':<14}{'state':<7}{'objective':<18}"
              f"{'burn fast':>16}{'burn slow':>16}  windows")
        for r in rows:
            bf = "/".join(f"{b:.2f}" for b in r["burn_fast"])
            bs = "/".join(f"{b:.2f}" for b in r["burn_slow"])
            w = r["windows_s"]["fast"]
            print(f"{r['slo']:<14}{r['state']:<7}"
                  f"{r['objective']:<18}{bf:>16}{bs:>16}  "
                  f"{w[0]:.0f}s/{w[1]:.0f}s")
        ts = rep.get("tsdb", {})
        print(f"tsdb: {ts.get('series', 0)} series, "
              f"{ts.get('samples_recorded', 0)} samples, "
              f"{ts.get('ticks', 0)} scrapes @ "
              f"{ts.get('period_s', 0)}s")
        return 1 if "page" in rep.get("states", {}).values() else 0
    finally:
        ray.shutdown()


def _bytes_h(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:.0f}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _cache_frame(rep: dict) -> str:
    """Render one `cli cache` frame from a state.cache_report() dict.
    Pure function of the report (tested without a terminal)."""
    lines = []
    t = rep.get("totals", {})
    tr = rep.get("trend")
    head = (f"prefix cache: hit rate {t.get('hit_rate', 0.0):.2%} "
            f"cumulative ({int(t.get('hits', 0))} hits / "
            f"{int(t.get('misses', 0))} misses, "
            f"{int(t.get('evictions', 0))} evictions, "
            f"{int(t.get('tokens_saved', 0))} tokens saved)")
    if tr and tr.get("hit_rate") is not None:
        head += (f"  |  last {tr['window_s']:.0f}s: "
                 f"{tr['hit_rate']:.2%} @ "
                 f"{tr['hits_per_s'] + tr['misses_per_s']:.1f} pages/s")
    lines.append(head)
    pg = rep.get("pages", {})
    if pg.get("total"):
        active = pg["total"] - pg["free"] - pg["cached"]
        lines.append(
            f"pages: {active} active, {pg['cached']} cached "
            f"(reclaimable {_bytes_h(pg['reclaimable_bytes'])}), "
            f"{pg['free']} free / {pg['total']} total "
            f"across {len(rep.get('replicas', []))} replica(s)")
    chains = rep.get("chains", [])
    if chains:
        lines.append(f"{'chain':<14}{'hits':>10}{'tok saved':>12}"
                     f"{'resident':>10}{'repl':>6}{'last hit':>10}")
        for c in chains:
            age = c.get("last_hit_age_s")
            lines.append(
                f"{c['chain']:<14}{int(c.get('hits', 0)):>10}"
                f"{int(c.get('tokens_saved', 0)):>12}"
                f"{int(c.get('resident_pages', 0)):>10}"
                f"{c.get('replicas', 0):>6}"
                f"{'-' if age is None else f'{age:.0f}s ago':>10}")
    else:
        lines.append("(no per-chain series yet — is an engine with "
                     "chain_stats_slots > 0 taking traffic?)")
    tenants = rep.get("tenants", {})
    if tenants:
        lines.append("tenant warmth (from replica heat summaries):")
        for name, row in sorted(tenants.items(),
                                key=lambda kv: -kv[1]["hits"]):
            lines.append(
                f"  {name or '(unlabeled)':<12} {row['hits']} hits, "
                f"{row['tokens_saved']} tokens saved, "
                f"{_bytes_h(row['resident_bytes'])} resident")
    return "\n".join(lines)


def cmd_cache(args) -> int:
    """Cluster prefix-cache heat map (cache heat plane): fleet hit/miss
    totals with recent trend, the hottest prompt chains folded across
    replicas, active vs reclaimable pages, and per-tenant warmth. Works
    without the TSDB (trend line simply absent)."""
    ray, rt, _ = _client(args.address)
    try:
        from . import state as state_mod
        print(_cache_frame(state_mod.cache_report(top_k=args.top)))
        return 0
    finally:
        ray.shutdown()


def cmd_timeline(args) -> int:
    ray, rt, _ = _client(args.address)
    try:
        if args.flight:
            # cluster-stitched flight-recorder trace: every process's
            # event ring on one clock, channel seal->wake flow arrows
            # included. state.timeline owns the remote-vs-local
            # dispatch — one path to keep in sync with the RPC.
            from . import state as state_mod
            events = state_mod.timeline(flight=True)
            n = len(events.get("traceEvents", []))
        else:
            events = rt.timeline()
            n = len(events)
        with open(args.out, "w") as f:
            json.dump(events, f)
        print(f"wrote {n} events to {args.out} "
              f"(open in chrome://tracing or https://ui.perfetto.dev)")
        return 0
    finally:
        ray.shutdown()


# --------------------------------------------------------------------- #

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="ray_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start", help="start a head or join a cluster")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="cluster file to join as a node agent")
    sp.add_argument("--num-cpus", type=float, default=None)
    sp.add_argument("--num-tpus", type=float, default=None)
    sp.add_argument("--name", default="default")
    sp.add_argument("--block", action="store_true",
                    help="run the head in the foreground")
    sp.add_argument("--enable-remote-nodes", action="store_true")
    sp.add_argument("--autoscale-config", default=None,
                    help="JSON scaling config (autoscaler/config.py schema)"
                    )
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("stop", help="stop a named head")
    sp.add_argument("--name", default="default")
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster resources + jobs")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser("job")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("--address", default=None)
    js.add_argument("--working-dir", default=None)
    js.add_argument("--env", action="append")
    js.add_argument("--job-id", default=None)
    js.add_argument("--follow", action="store_true")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for name in ("list",):
        j = jsub.add_parser(name)
        j.add_argument("--address", default=None)
    for name in ("status", "stop"):
        j = jsub.add_parser(name)
        j.add_argument("id")
        j.add_argument("--address", default=None)
    j = jsub.add_parser("logs")
    j.add_argument("id")
    j.add_argument("--follow", action="store_true")
    j.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_job)

    sp = sub.add_parser("serve", help="deploy a serve application")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    sr = ssub.add_parser("run", help="import module:app and serve it")
    sr.add_argument("target", help="module.path:app_variable")
    sr.add_argument("--name", default="default")
    sr.add_argument("--route-prefix", default="/")
    sr.add_argument("--http-port", type=int, default=8000)
    sr.add_argument("--proxies", type=int, default=None,
                    help="HTTP proxy actors to run (ports http-port.."
                         "http-port+N-1; default cfg.serve_num_proxies)")
    sr.add_argument("--address", default=None)
    sr.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("state", help="list cluster state")
    sp.add_argument("kind", choices=["tasks", "actors", "nodes", "objects",
                                     "jobs"])
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_state)

    sp = sub.add_parser("memory", help="object refs + store usage "
                                       "(`ray memory` analog)")
    sp.add_argument("--limit", type=int, default=200)
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser("stack", help="live thread stacks of every "
                                      "process (`ray stack` analog)")
    sp.add_argument("--all", action="store_true",
                    help="include idle bookkeeping threads")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_stack)

    sp = sub.add_parser("doctor", help="one-shot stall diagnosis: "
                                       "summary + hangs + stacks + memory")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser("top", help="live serving table from the "
                                    "metrics TSDB (rates + windowed "
                                    "quantiles per deployment)")
    sp.add_argument("--window", type=float, default=60.0,
                    help="rate/quantile window in seconds")
    sp.add_argument("--interval", type=float, default=2.0,
                    help="refresh period")
    sp.add_argument("--once", action="store_true",
                    help="print one frame and exit (scripts/tests)")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser("slo", help="SLO burn-rate report (exit 1 "
                                    "when any objective is paging)")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_slo)

    sp = sub.add_parser("cache", help="prefix-cache heat map: hot "
                                      "chains, reclaimable pages, "
                                      "tenant warmth")
    sp.add_argument("--top", type=int, default=10,
                    help="hot chains to show")
    sp.add_argument("--address", default=None)
    sp.set_defaults(fn=cmd_cache)

    sp = sub.add_parser("timeline", help="dump chrome trace")
    sp.add_argument("--out", default="timeline.json")
    sp.add_argument("--address", default=None)
    sp.add_argument("--flight", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="include the cluster-stitched flight-recorder "
                         "rings (--no-flight = span events only)")
    sp.set_defaults(fn=cmd_timeline)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # strip a leading "--" from REMAINDER entrypoints
    if getattr(args, "entrypoint", None) and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
