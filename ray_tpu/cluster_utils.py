"""Multi-node cluster simulation for tests.

Reference parity: python/ray/cluster_utils.py:135 (Cluster — starts multiple
raylets in one OS host; add_node :202, remove_node :286). Our nodes are
logical resource domains inside the head runtime; workers spawned for a node
are tagged with it, and remove_node kills them, exercising the same failure
paths real node loss would (task retry, actor restart, PG re-reservation).
"""
from __future__ import annotations

from typing import Optional

from .core import runtime as rt_mod
from .core.ids import NodeID
from .core.runtime import Runtime


class NodeHandle:
    def __init__(self, node_id: NodeID):
        self.node_id = node_id

    @property
    def node_id_hex(self) -> str:
        return self.node_id.hex()


class Cluster:
    """In-process multi-node cluster for tests.

    ``Cluster(initialize_head=True, head_node_args={"num_cpus": 2})`` starts
    the head; ``add_node(num_cpus=2)`` adds simulated nodes;
    ``remove_node(n)`` kills the node's workers and marks it dead.
    """

    def __init__(self, initialize_head: bool = False,
                 head_node_args: Optional[dict] = None):
        self.head_handle: Optional[NodeHandle] = None
        self._nodes: list[NodeHandle] = []
        if initialize_head:
            from .core.api import init
            args = dict(head_node_args or {})
            args.setdefault("num_cpus", 1)
            init(**args)
            rt = rt_mod.get_runtime_if_exists()
            self.head_handle = NodeHandle(rt.head_node.node_id)
            self._nodes.append(self.head_handle)

    @property
    def _rt(self) -> Runtime:
        rt = rt_mod.get_runtime_if_exists()
        if rt is None:
            raise RuntimeError("cluster not initialized")
        return rt

    def connect(self):
        return self

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 name: str = "") -> NodeHandle:
        res = {"CPU": float(num_cpus), **(resources or {})}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        nid = self._rt.add_node(res, labels, name)
        h = NodeHandle(nid)
        self._nodes.append(h)
        return h

    def remove_node(self, node: NodeHandle, allow_graceful: bool = True):
        self._rt.remove_node(node.node_id)
        if node in self._nodes:
            self._nodes.remove(node)

    def list_all_nodes(self) -> list[NodeHandle]:
        return list(self._nodes)

    def shutdown(self):
        rt = rt_mod.get_runtime_if_exists()
        if rt is not None:
            rt.shutdown()
