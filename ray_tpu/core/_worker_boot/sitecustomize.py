"""Shadowing sitecustomize for spawned CPU workers.

The image's real sitecustomize imports jax + the axon TPU PJRT plugin at
interpreter start (~1.8s on one core). Worker processes that will never touch
the TPU skip it by having this empty module earlier on PYTHONPATH; TPU-flagged
workers (Runtime._spawn_worker_locked tpu=True) keep the real one.
"""
