"""Actor API: @ray_tpu.remote classes, handles, and method submission.

Reference parity: python/ray/actor.py (ActorClass ~:1100, method submission
:1729) with the GCS-side lifecycle living in core/runtime.py. Handles are
picklable and can be passed to tasks/other actors; calls route through the
head for ordering (reference analog: ActorTaskSubmitter sequence numbers,
transport/actor_task_submitter.h:49).
"""
from __future__ import annotations

import hashlib
from typing import Any

import cloudpickle

from .ids import ActorID, ObjectID, TaskID
from .ref import ObjectRef
from .remote_function import (_trace_ctx, prepare_args,
                              prepare_runtime_env, resolve_strategy)
from .task_spec import ActorSpec, TaskSpec, validate_resources

_DEFAULT_ACTOR_OPTS = dict(
    num_cpus=0.0, num_tpus=0.0, resources=None, name=None, namespace=None,
    max_restarts=0, max_task_retries=0, max_concurrency=1,
    max_pending_calls=-1,
    lifetime=None, scheduling_strategy="DEFAULT", placement_group=None,
    placement_group_bundle_index=-1, _node_id=None, _node_soft=False,
    runtime_env=None, concurrency_groups=None, label_selector=None,
)


def split_actor_name(qualified):
    """Inverse of qualify_actor_name for display surfaces: ``"ns/name"``
    -> (ns, name); system (``rtpu:``) and unqualified names -> ("", name)
    (reference: `ray list actors` shows name and ray_namespace as
    separate columns)."""
    if not qualified:
        return "", ""
    if qualified.startswith("rtpu:") or "/" not in qualified:
        return "", qualified
    ns, _, short = qualified.partition("/")
    return ns, short


def qualify_actor_name(name, namespace, rt):
    """Scope a user-visible actor name to a namespace (reference:
    ray.init(namespace=)/get_actor(namespace=) isolation of named actors).
    Delta from the reference, by design: the cluster-wide default
    namespace is the shared ``"default"`` (not a per-driver anonymous
    UUID) — a single-job TPU cluster wants its drivers to see each
    other's named actors unless told otherwise. ``rtpu:``-prefixed system
    actors (serve controller, proxies) stay cluster-global, the analog of
    the reference's reserved SERVE_NAMESPACE."""
    if name is None:
        return None
    if name.startswith("rtpu:"):
        return name
    ns = namespace or getattr(rt, "namespace", None) or "default"
    return f"{ns}/{name}"


def _runtime():
    from . import runtime as rt
    r = rt.get_runtime_if_exists()
    if r is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return r


class ActorClass:
    def __init__(self, cls, opts: dict):
        self._cls = cls
        self._opts = {**_DEFAULT_ACTOR_OPTS, **opts}
        self._blob: bytes | None = None
        self._cid: str | None = None
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **kwargs) -> "ActorClass":
        bad = set(kwargs) - set(_DEFAULT_ACTOR_OPTS)
        if bad:
            raise ValueError(f"unknown actor options: {sorted(bad)}")
        ac = ActorClass(self._cls, {**self._opts, **kwargs})
        ac._blob, ac._cid = self._blob, self._cid
        return ac

    def remote(self, *args, **kwargs) -> "ActorHandle":
        rt = _runtime()
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._cls)
            self._cid = "cls_" + hashlib.sha1(self._blob).hexdigest()[:16]
        rt.register_function(self._cid, self._blob)
        o = self._opts
        blob, deps = prepare_args(rt, args, kwargs)
        res = validate_resources({
            "CPU": o["num_cpus"], "TPU": o["num_tpus"],
            **(o["resources"] or {})})
        strat = resolve_strategy(o)
        aid = ActorID.from_random()
        ready_oid = ObjectID.from_random()
        spec = ActorSpec(
            actor_id=aid,
            class_id=self._cid,
            name=o["name"] or self.__name__,
            args_blob=blob,
            dep_oids=deps,
            resources=res,
            max_restarts=o["max_restarts"],
            max_task_retries=o["max_task_retries"],
            max_concurrency=o["max_concurrency"],
            pg_id=strat["pg_id"],
            pg_bundle_index=strat["pg_bundle_index"],
            node_affinity=strat["node_affinity"],
            node_affinity_soft=strat["node_affinity_soft"],
            label_selector=(dict(o["label_selector"])
                            if o["label_selector"] else None),
            named=qualify_actor_name(o["name"], o["namespace"], rt),
            namespace=(o["namespace"]
                       or getattr(rt, "namespace", None)),
            ready_oid=ready_oid,
            runtime_env=prepare_runtime_env(rt, o["runtime_env"]),
            concurrency_groups=o["concurrency_groups"],
        )
        rt.create_actor(spec)
        methods = sorted(
            m for m in dir(self._cls)
            if callable(getattr(self._cls, m, None)) and not m.startswith("__"))
        return ActorHandle(aid, self.__name__, methods,
                           o["max_task_retries"], ready_oid,
                           o["max_pending_calls"])

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()")


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group=None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns: int = 1, concurrency_group=None,
                **_ignored) -> "ActorMethod":
        if num_returns == "dynamic":
            raise ValueError(
                "num_returns='dynamic' is supported for TASKS only; have "
                "the actor method return a list and iterate it, or spawn "
                "a task for generator semantics")
        return ActorMethod(self._handle, self._name, num_returns,
                           concurrency_group)

    def remote(self, *args, **kwargs):
        rt = _runtime()
        h = self._handle
        h._admit_pending(rt)  # max_pending_calls backpressure
        blob, deps = prepare_args(rt, args, kwargs)
        nret = self._num_returns
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            func_id="",
            name=f"{h._class_name}.{self._name}",
            args_blob=blob,
            dep_oids=deps,
            return_ids=[ObjectID.from_random() for _ in range(nret)],
            resources={},
            retries_left=max(0, h._max_task_retries),
            actor_id=h._actor_id,
            method_name=self._name,
            concurrency_group=self._concurrency_group,
            trace_ctx=_trace_ctx(),
            namespace=getattr(rt, "namespace", None),
        )
        refs = rt.submit_actor_task_spec(spec)
        h._track_pending(refs)
        if nret == 0:
            return None
        return refs[0] if nret == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 methods: list[str], max_task_retries: int,
                 ready_oid: ObjectID | None = None,
                 max_pending_calls: int = -1):
        self._actor_id = actor_id
        self._class_name = class_name
        self._methods = methods
        self._max_task_retries = max_task_retries
        self._ready_oid = ready_oid
        self._max_pending_calls = max_pending_calls
        self._pending: list[ObjectRef] = []  # see _admit_pending
        # created eagerly: unpickling rebuilds the handle via __reduce__ →
        # __init__, and lazy creation would race two first .remote()s
        import threading as _t
        self._pending_lock = _t.Lock()

    def _admit_pending(self, rt):
        """Client-side backpressure (reference: ActorTaskSubmitter's
        max_pending_calls check raising PendingCallsLimitExceeded).
        A call counts as pending until its first return lands in the
        store; pruning happens on the submit path, so an idle handle
        holds only ids, no threads."""
        mp = self._max_pending_calls
        if mp is None or mp <= 0:
            return
        store = getattr(rt, "store", None)
        with self._pending_lock:
            # _pending holds STRONG ObjectRefs: the held interest keeps a
            # completed-and-consumed result from being freed before this
            # prune can observe it (a freed oid is indistinguishable from
            # a still-running call). Lifetime cost: at most mp results
            # outlive their consumers until the next submit prunes them.
            if store is not None:
                self._pending = [r for r in self._pending
                                 if not store.contains(r.id())]
            else:  # local mode executes inline; nothing can be pending
                self._pending = []
            if len(self._pending) >= mp:
                # the local store can miss settled results (remote-node
                # stores, FAILED-without-result crashes); before
                # refusing, ask the head which pending results settled —
                # ONE batched round-trip (or direct call on the head
                # driver), on the saturated path only
                try:
                    obs = [r.id().binary() for r in self._pending]
                    if hasattr(rt, "locate_many"):   # in-process head
                        done = rt.locate_many(obs)
                    elif hasattr(rt, "_rpc"):
                        done = rt._rpc("locate_many", obs, timeout=10.0)
                    else:
                        done = [False] * len(obs)
                    self._pending = [r for r, d in
                                     zip(self._pending, done) if not d]
                except Exception:
                    pass  # head unreachable: keep the conservative view
            if len(self._pending) >= mp:
                from .. import exceptions as exc
                raise exc.PendingCallsLimitExceeded(
                    f"{self._class_name} handle has {len(self._pending)} "
                    f"calls in flight (max_pending_calls={mp})")

    def _track_pending(self, refs):
        if (self._max_pending_calls or 0) > 0 and refs:
            with self._pending_lock:
                self._pending.append(refs[0])

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._methods and name not in self._methods:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}")
        return ActorMethod(self, name)

    def __ray_ready__(self) -> ObjectRef:
        """Ref that resolves when the actor's __init__ finished."""
        return ObjectRef(self._ready_oid)

    def _exec(self, fn, *args) -> ObjectRef:
        """Run ``fn(actor_instance, *args)`` inside the actor's process
        (internal; reference analog: __ray_call__). Used by compiled DAGs
        to install their execution loops."""
        import cloudpickle as _cp
        method = ActorMethod(self, "__rtpu_exec__")
        return method.remote(_cp.dumps(fn), *args)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        # pending-call tracking is per-handle-copy, like the reference's
        # per-caller submit queues
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._methods, self._max_task_retries,
                              self._ready_oid, self._max_pending_calls))
