"""Actor API: @ray_tpu.remote classes, handles, and method submission.

Reference parity: python/ray/actor.py (ActorClass ~:1100, method submission
:1729) with the GCS-side lifecycle living in core/runtime.py. Handles are
picklable and can be passed to tasks/other actors; calls route through the
head for ordering (reference analog: ActorTaskSubmitter sequence numbers,
transport/actor_task_submitter.h:49).
"""
from __future__ import annotations

import hashlib
from typing import Any

import cloudpickle

from .ids import ActorID, ObjectID, TaskID
from .ref import ObjectRef
from .remote_function import (_trace_ctx, prepare_args,
                              prepare_runtime_env, resolve_strategy)
from .task_spec import ActorSpec, TaskSpec, validate_resources

_DEFAULT_ACTOR_OPTS = dict(
    num_cpus=0.0, num_tpus=0.0, resources=None, name=None,
    max_restarts=0, max_task_retries=0, max_concurrency=1,
    lifetime=None, scheduling_strategy="DEFAULT", placement_group=None,
    placement_group_bundle_index=-1, _node_id=None, _node_soft=False,
    runtime_env=None, concurrency_groups=None, label_selector=None,
)


def _runtime():
    from . import runtime as rt
    r = rt.get_runtime_if_exists()
    if r is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    return r


class ActorClass:
    def __init__(self, cls, opts: dict):
        self._cls = cls
        self._opts = {**_DEFAULT_ACTOR_OPTS, **opts}
        self._blob: bytes | None = None
        self._cid: str | None = None
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **kwargs) -> "ActorClass":
        bad = set(kwargs) - set(_DEFAULT_ACTOR_OPTS)
        if bad:
            raise ValueError(f"unknown actor options: {sorted(bad)}")
        ac = ActorClass(self._cls, {**self._opts, **kwargs})
        ac._blob, ac._cid = self._blob, self._cid
        return ac

    def remote(self, *args, **kwargs) -> "ActorHandle":
        rt = _runtime()
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._cls)
            self._cid = "cls_" + hashlib.sha1(self._blob).hexdigest()[:16]
        rt.register_function(self._cid, self._blob)
        o = self._opts
        blob, deps = prepare_args(rt, args, kwargs)
        res = validate_resources({
            "CPU": o["num_cpus"], "TPU": o["num_tpus"],
            **(o["resources"] or {})})
        strat = resolve_strategy(o)
        aid = ActorID.from_random()
        ready_oid = ObjectID.from_random()
        spec = ActorSpec(
            actor_id=aid,
            class_id=self._cid,
            name=o["name"] or self.__name__,
            args_blob=blob,
            dep_oids=deps,
            resources=res,
            max_restarts=o["max_restarts"],
            max_task_retries=o["max_task_retries"],
            max_concurrency=o["max_concurrency"],
            pg_id=strat["pg_id"],
            pg_bundle_index=strat["pg_bundle_index"],
            node_affinity=strat["node_affinity"],
            node_affinity_soft=strat["node_affinity_soft"],
            label_selector=(dict(o["label_selector"])
                            if o["label_selector"] else None),
            named=o["name"],
            ready_oid=ready_oid,
            runtime_env=prepare_runtime_env(rt, o["runtime_env"]),
            concurrency_groups=o["concurrency_groups"],
        )
        rt.create_actor(spec)
        methods = sorted(
            m for m in dir(self._cls)
            if callable(getattr(self._cls, m, None)) and not m.startswith("__"))
        return ActorHandle(aid, self.__name__, methods,
                           o["max_task_retries"], ready_oid)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()")


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1,
                 concurrency_group=None):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, num_returns: int = 1, concurrency_group=None,
                **_ignored) -> "ActorMethod":
        if num_returns == "dynamic":
            raise ValueError(
                "num_returns='dynamic' is supported for TASKS only; have "
                "the actor method return a list and iterate it, or spawn "
                "a task for generator semantics")
        return ActorMethod(self._handle, self._name, num_returns,
                           concurrency_group)

    def remote(self, *args, **kwargs):
        rt = _runtime()
        blob, deps = prepare_args(rt, args, kwargs)
        h = self._handle
        nret = self._num_returns
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            func_id="",
            name=f"{h._class_name}.{self._name}",
            args_blob=blob,
            dep_oids=deps,
            return_ids=[ObjectID.from_random() for _ in range(nret)],
            resources={},
            retries_left=max(0, h._max_task_retries),
            actor_id=h._actor_id,
            method_name=self._name,
            concurrency_group=self._concurrency_group,
            trace_ctx=_trace_ctx(),
        )
        refs = rt.submit_actor_task_spec(spec)
        if nret == 0:
            return None
        return refs[0] if nret == 1 else refs


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 methods: list[str], max_task_retries: int,
                 ready_oid: ObjectID | None = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._methods = methods
        self._max_task_retries = max_task_retries
        self._ready_oid = ready_oid

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._methods and name not in self._methods:
            raise AttributeError(
                f"actor {self._class_name} has no method {name!r}")
        return ActorMethod(self, name)

    def __ray_ready__(self) -> ObjectRef:
        """Ref that resolves when the actor's __init__ finished."""
        return ObjectRef(self._ready_oid)

    def _exec(self, fn, *args) -> ObjectRef:
        """Run ``fn(actor_instance, *args)`` inside the actor's process
        (internal; reference analog: __ray_call__). Used by compiled DAGs
        to install their execution loops."""
        import cloudpickle as _cp
        method = ActorMethod(self, "__rtpu_exec__")
        return method.remote(_cp.dumps(fn), *args)

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._methods, self._max_task_retries,
                              self._ready_oid))
