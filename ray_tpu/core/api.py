"""Public core API: init/shutdown/remote/get/put/wait/kill/cancel/...

Reference parity: python/ray/_private/worker.py (init :1336, get :2749,
put :2885, wait :2950) and the @ray.remote decorator.
"""
from __future__ import annotations

import inspect
import os
from typing import Any, Iterable, Optional

from .. import exceptions as exc
from .actor import ActorClass, ActorHandle
from .ids import ActorID, NodeID
from .ref import ObjectRef
from .remote_function import RemoteFunction
from . import runtime as rt_mod
from .runtime import LocalModeRuntime, Runtime


def init(address: Optional[str] = None,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         resources: Optional[dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         local_mode: bool = False,
         labels: Optional[dict[str, str]] = None,
         ignore_reinit_error: bool = False,
         log_to_driver: bool = True,
         namespace: Optional[str] = None,
         resume_from: Optional[str] = None,
         **_compat) -> dict:
    """Start the head runtime in this process, or — with ``address`` — attach
    to a running cluster as a driver client.

    Reference: ray.init (python/ray/_private/worker.py:1336). TPU-specific:
    `num_tpus` declares how many TPU chips this host exposes as schedulable
    "TPU" resources; auto-detected from the JAX runtime when None and
    detection is cheap (env var, never imports jax here).

    ``address``: "auto" resolves the newest local cluster (or
    ``$RTPU_ADDRESS``, which job drivers inherit); otherwise a path to a
    session's ``cluster.json``. None starts a new in-process head —
    unless ``RTPU_ADDRESS`` is set (so a submitted job's plain
    ``init()`` joins its cluster), matching the reference's env-driven
    auto-connect.
    """
    if rt_mod.get_runtime_if_exists() is not None:
        if ignore_reinit_error:
            return {"already_initialized": True}
        raise RuntimeError("ray_tpu.init() called twice "
                           "(pass ignore_reinit_error=True to allow)")
    if address is None and os.environ.get("RTPU_ADDRESS") and not local_mode:
        address = "auto"
    if address is not None and address != "local":
        from .client import connect
        return connect(address, namespace=namespace)
    if local_mode:
        rt = LocalModeRuntime()
        rt_mod.set_runtime(rt)
        return {"local_mode": True}
    if num_cpus is None:
        num_cpus = float(os.cpu_count() or 1)
    if num_tpus is None:
        num_tpus = float(os.environ.get("RTPU_NUM_TPUS", 0))
    res = {"CPU": float(num_cpus), **(resources or {})}
    if num_tpus:
        res["TPU"] = float(num_tpus)
    # named-actor scoping (core/actor.py qualify_actor_name); set BEFORE
    # Runtime() so prestarted workers inherit it and in-task get_actor
    # resolves in the job's namespace
    os.environ["RTPU_NAMESPACE"] = namespace or "default"
    rt = Runtime(res,
                 object_store_memory=object_store_memory or None,
                 head_labels=labels,
                 log_to_driver=log_to_driver)
    rt.namespace = namespace or "default"
    rt_mod.set_runtime(rt)
    out = {"node_id": rt.head_node.node_id.hex(),
           "session_dir": rt.session_dir}
    if resume_from:
        # GCS-fault-tolerance analog: resurrect durable state (named
        # actors, placement groups, job table) from a previous session's
        # snapshot (core/gcs_store.py restore)
        from .gcs_store import restore
        rt.resumed_from = os.path.abspath(resume_from)
        out["restored"] = restore(rt, resume_from)
    return out


def is_initialized() -> bool:
    return rt_mod.get_runtime_if_exists() is not None


def shutdown() -> None:
    rt = rt_mod.get_runtime_if_exists()
    if rt is not None:
        rt.shutdown()


def _runtime():
    rt = rt_mod.get_runtime_if_exists()
    if rt is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return rt


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes."""
    if len(args) == 1 and not kwargs and (
            inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        target = args[0]
        return (ActorClass(target, {}) if inspect.isclass(target)
                else RemoteFunction(target, {}))
    if args:
        raise TypeError("@remote only takes keyword options")

    def deco(target):
        return (ActorClass(target, kwargs) if inspect.isclass(target)
                else RemoteFunction(target, kwargs))
    return deco


def get(refs, *, timeout: Optional[float] = None):
    if isinstance(refs, ObjectRef):
        return _runtime().get(refs, timeout=timeout)
    try:
        refs = list(refs)
    except TypeError:
        raise TypeError(
            f"ray_tpu.get takes an ObjectRef or a list of ObjectRefs, "
            f"got {type(refs).__name__}") from None
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get takes ObjectRefs, got {type(r)}")
    return _runtime().get(refs, timeout=timeout)


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("calling put on an ObjectRef is not allowed")
    return _runtime().put(value)


def wait(refs: Iterable[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    refs = list(refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.wait takes ObjectRefs, got {type(r)}")
    return _runtime().wait(refs, num_returns=num_returns, timeout=timeout,
                           fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill takes an actor handle")
    _runtime().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    _runtime().cancel(ref, force=force, recursive=recursive)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    """Look up a named actor, scoped to `namespace` (default: the
    calling driver/job's namespace — reference: ray.get_actor)."""
    from .actor import qualify_actor_name
    rt = _runtime()
    spec = rt.get_actor_by_name(qualify_actor_name(name, namespace, rt))
    return ActorHandle(spec.actor_id, spec.name, [], spec.max_task_retries,
                       spec.ready_oid)


def nodes() -> list[dict]:
    return _runtime().node_table()


def cluster_resources() -> dict[str, float]:
    return _runtime().cluster_resources()


def available_resources() -> dict[str, float]:
    return _runtime().available_resources()


def timeline(filename: Optional[str] = None):
    """Chrome-trace task timeline (reference: ray.timeline,
    _private/state.py:439)."""
    events = _runtime().timeline()
    if filename:
        import json
        with open(filename, "w") as f:
            json.dump(events, f)
        return None
    return events


# --------------------------------------------------------------------- #
# internal KV (reference: ray.experimental.internal_kv over
# gcs_kv_manager.h) — durable, cluster-visible small metadata
# --------------------------------------------------------------------- #

def _kv_call(method: str, *args):
    from .runtime import Runtime
    rt = _runtime()
    if isinstance(rt, Runtime):
        return getattr(rt, method)(*args)
    if hasattr(rt, "_rpc"):
        return rt._rpc(method, *args)
    raise RuntimeError("internal KV is not available in local_mode")


def kv_put(key: str, value: bytes) -> None:
    if isinstance(value, str):
        value = value.encode()
    _kv_call("kv_put", key, bytes(value))


def kv_get(key: str) -> Optional[bytes]:
    return _kv_call("kv_get", key)


def kv_del(key: str) -> bool:
    return _kv_call("kv_del", key)


def kv_keys() -> list[str]:
    return _kv_call("kv_keys")


def head_address() -> dict:
    """Connection info for joining this cluster from another host:
    `python -m ray_tpu.core.node_agent --head <address> --authkey <authkey>`
    (reference analog: the bootstrap address `ray start --address=` dials)."""
    rt = _runtime()
    if not isinstance(rt, Runtime):
        raise RuntimeError("head_address() only works on the head runtime")
    return {"address": rt.head_address, "authkey": rt._authkey.hex()}


class RuntimeContext:
    """Reference: python/ray/runtime_context.py."""

    def __init__(self, rt):
        self._rt = rt

    def get_job_id(self) -> str:
        return self._rt.job_id.hex() if hasattr(self._rt, "job_id") else ""

    def get_worker_id(self) -> str:
        return getattr(self._rt, "wid", "driver")

    def get_node_id(self) -> str:
        if isinstance(self._rt, Runtime):
            return self._rt.head_node.node_id.hex()
        return os.environ.get("RTPU_NODE_ID", "local")

    def get_task_name(self) -> str:
        return getattr(self._rt, "current_task_name", "")

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_runtime())
