"""Driver client: attach this process to a running cluster as a driver.

Reference parity: the driver path of ``ray.init(address=...)`` — a driver
core worker dialing a live GCS (python/ray/_private/worker.py:1336 connect
branch) — and the role (not the transport) of Ray Client (util/client/):
an interactive process driving a remote cluster.

The client registers over the head's control plane as a ``driver-*``
pseudo-worker: it speaks the complete worker protocol (submit/put/get/
actors/PGs/RPCs via :class:`WorkerRuntime`) but lives outside every node's
worker pool, so the scheduler can never dispatch work to it.  Data moves
through the same shared-memory store as everyone else — zero extra copies
vs the reference's dedicated client gRPC proxy.
"""
from __future__ import annotations

import json
import os
import threading
from multiprocessing.connection import Client

from .object_store import SharedObjectStore, SpillStore
from .worker import WorkerRuntime
from . import runtime as rt_mod


def resolve_cluster_file(address: str | None) -> str:
    """Find the cluster file for ``address``:

    - explicit path to a ``cluster.json``;
    - ``"auto"``/None: ``$RTPU_ADDRESS`` if set (exported to job drivers),
      else the most recently started session under ``/tmp/ray_tpu``.
    """
    if address and address not in ("auto", "local"):
        if os.path.isfile(address):
            return address
        raise ConnectionError(f"no cluster file at {address!r}")
    env = os.environ.get("RTPU_ADDRESS")
    if env:
        if not os.path.isfile(env):
            raise ConnectionError(f"RTPU_ADDRESS={env!r} does not exist")
        return env
    base = "/tmp/ray_tpu"
    candidates = []
    if os.path.isdir(base):
        for d in os.listdir(base):
            cf = os.path.join(base, d, "cluster.json")
            if os.path.isfile(cf) and _head_alive(cf):
                candidates.append((os.path.getmtime(cf), cf))
    if not candidates:
        raise ConnectionError(
            "address='auto' but no running cluster found under /tmp/ray_tpu "
            "(start one with `python -m ray_tpu start --head`)")
    return max(candidates)[1]


def _head_alive(cluster_file: str) -> bool:
    """Is the head process that wrote this cluster file still running?
    (Guards 'auto' against stale files from crashed heads — clean
    shutdowns delete theirs.)"""
    try:
        with open(cluster_file) as f:
            pid = json.load(f).get("pid", -1)
        os.kill(pid, 0)
        return True
    except (OSError, ValueError, TypeError):
        return False


class DriverRuntime(WorkerRuntime):
    """WorkerRuntime wired as an external driver. Adds: connection liveness
    tracking, head-pushed exit handling, and a real shutdown."""

    def __init__(self, store, conn, wid, spill=None):
        super().__init__(store, conn, wid, spill)
        self.disconnected = threading.Event()
        threading.Thread(target=self._conn_loop, daemon=True,
                         name="rtpu-driver-recv").start()

    def _conn_loop(self):
        # Workers drain dispatches here; a driver only ever receives "exit"
        # (head shutting down) or EOF (head died).
        try:
            while True:
                msg = self.conn.recv()
                if isinstance(msg, dict) and msg.get("t") == "exit":
                    break
        except (EOFError, OSError):
            pass
        self.disconnected.set()

    def timeline(self):
        return self._rpc("timeline")

    def shutdown(self):
        self.disconnected.set()
        try:
            self.conn.close()
        except Exception:
            pass
        try:
            self.store.close(unlink=False)
        except Exception:
            pass
        if rt_mod.get_runtime_if_exists() is self:
            rt_mod.set_runtime(None)


def connect(address: str | None = None) -> dict:
    """Connect as a driver; sets the process runtime. Returns init info."""
    cf_path = resolve_cluster_file(address)
    with open(cf_path) as f:
        cf = json.load(f)
    authkey = bytes.fromhex(cf["authkey"])
    unix_addr = cf.get("unix_addr")
    if unix_addr and os.path.exists(unix_addr):
        conn = Client(unix_addr, "AF_UNIX", authkey=authkey)
    else:
        host = cf["tcp_host"]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        conn = Client((host, cf["tcp_port"]), "AF_INET", authkey=authkey)
    conn.send({"t": "register_driver", "pid": os.getpid()})
    reply = conn.recv()
    if reply.get("t") != "registered_driver":
        raise ConnectionError(f"head rejected driver registration: {reply}")
    store = SharedObjectStore(reply["store_path"], create=False)
    spill = SpillStore(reply["spill_dir"]) if reply.get("spill_dir") else None
    rt = DriverRuntime(store, conn, reply["wid"], spill)
    rt_mod.set_runtime(rt)
    return {"address": cf_path, "wid": reply["wid"],
            "job_id": reply["job_id"], "session_dir": cf["session_dir"]}
