"""Driver client: attach this process to a running cluster as a driver.

Reference parity: the driver path of ``ray.init(address=...)`` — a driver
core worker dialing a live GCS (python/ray/_private/worker.py:1336 connect
branch) — and the role (not the transport) of Ray Client (util/client/):
an interactive process driving a remote cluster.

The client registers over the head's control plane as a ``driver-*``
pseudo-worker: it speaks the complete worker protocol (submit/put/get/
actors/PGs/RPCs via :class:`WorkerRuntime`) but lives outside every node's
worker pool, so the scheduler can never dispatch work to it.  Data moves
through the same shared-memory store as everyone else — zero extra copies
vs the reference's dedicated client gRPC proxy.
"""
from __future__ import annotations

import json
import multiprocessing as mp
import os
import threading
from multiprocessing.connection import Client

from .object_store import SharedObjectStore, SpillStore
from .protocol import PROTOCOL_VERSION, ProtocolMismatchError
from .worker import WorkerRuntime
from . import flight
from . import stacks
from . import runtime as rt_mod


def resolve_cluster_file(address: str | None) -> str:
    """Find the cluster file for ``address``:

    - explicit path to a ``cluster.json``;
    - ``"auto"``/None: ``$RTPU_ADDRESS`` if set (exported to job drivers),
      else the most recently started session under ``/tmp/ray_tpu``.
    """
    if address and address not in ("auto", "local"):
        if os.path.isfile(address):
            return address
        raise ConnectionError(f"no cluster file at {address!r}")
    env = os.environ.get("RTPU_ADDRESS")
    if env:
        if not os.path.isfile(env):
            raise ConnectionError(f"RTPU_ADDRESS={env!r} does not exist")
        return env
    base = "/tmp/ray_tpu"
    candidates = []
    if os.path.isdir(base):
        for d in os.listdir(base):
            cf = os.path.join(base, d, "cluster.json")
            if os.path.isfile(cf) and _head_alive(cf):
                candidates.append((os.path.getmtime(cf), cf))
    if not candidates:
        raise ConnectionError(
            "address='auto' but no running cluster found under /tmp/ray_tpu "
            "(start one with `python -m ray_tpu start --head`)")
    return max(candidates)[1]


def _head_alive(cluster_file: str) -> bool:
    """Is the head process that wrote this cluster file still running?
    (Guards 'auto' against stale files from crashed heads — clean
    shutdowns delete theirs.)"""
    try:
        with open(cluster_file) as f:
            pid = json.load(f).get("pid", -1)
        os.kill(pid, 0)
        return True
    except (OSError, ValueError, TypeError):
        return False


class DriverRuntime(WorkerRuntime):
    """WorkerRuntime wired as an external driver. Adds: connection liveness
    tracking, head-pushed exit handling, a real shutdown, and — the GCS
    fault-tolerance client half (reference: the retryable GCS RPC wrappers
    under src/ray/rpc/ + redis_store_client.h:111 restore) — reconnection
    with backoff to a RESTARTED head: the driver re-registers, re-ships
    its function definitions and ref interest, resubmits its unresolved
    plain tasks, and swaps onto the new session's object store so
    in-flight ``get``s resume against the re-executed results."""

    # class-level defaults so the send pump (started by the base __init__,
    # before our fields exist) can never crash on a missing attribute
    _closing = False
    _conn_gen = 0

    def __init__(self, store, conn, wid, spill=None, address_arg=None):
        super().__init__(store, conn, wid, spill)
        flight.set_proc_name("driver:" + wid)
        self.disconnected = threading.Event()
        self._address_arg = address_arg
        self._closing = False
        self._conn_gen = 0
        # fid -> pickled function blob, for re-shipping after reconnect
        self._fid_blobs: dict = {}
        # return-oid (binary) -> plain TaskSpec not yet observed resolved;
        # resubmitted on reconnect (their results died with the old store)
        self._unresolved: dict = {}
        self._track_lock = threading.Lock()
        threading.Thread(target=self._conn_loop, daemon=True,
                         name="rtpu-driver-recv").start()

    # -- call tracking for resubmission ---------------------------------- #

    def register_function(self, fid, blob):
        self._fid_blobs[fid] = blob
        super().register_function(fid, blob)

    def submit_task(self, spec):
        refs = super().submit_task(spec)
        if not spec.is_actor_task:
            with self._track_lock:
                for o in spec.return_ids:
                    self._unresolved[o.binary()] = spec
        return refs

    def _get_one(self, oid, deadline, on_wait):
        out = super()._get_one(oid, deadline, on_wait)
        with self._track_lock:
            self._unresolved.pop(oid.binary(), None)
        return out

    def _note_outgoing(self, msg):
        # the driver released its last local ref: it can never get()
        # this result, so resubmitting its task on reconnect would be
        # pure waste — and without this hook _unresolved grows
        # unboundedly in fire-and-forget workloads
        if not isinstance(msg, dict):
            return
        t = msg.get("t")
        if t == "ref_drop":
            with self._track_lock:
                self._unresolved.pop(msg["oid"], None)
        elif t == "ref_drops":
            with self._track_lock:
                for ob in msg["oids"]:
                    self._unresolved.pop(ob, None)

    def send(self, msg):
        self._note_outgoing(msg)
        return super().send(msg)

    def send_async(self, msg):
        self._note_outgoing(msg)
        return super().send_async(msg)

    # -- liveness / reconnection ----------------------------------------- #

    def _conn_loop(self):
        # Workers drain dispatches here; a driver receives "exit" (head
        # shutting down), flight_pull (cluster flight-recorder
        # collection — the driver's ring holds the handle-side serve
        # events, and an unanswered pull would stall every collection
        # for its full timeout), stack_dump (stall-doctor live-stack
        # collection — the driver's threads hold the handle-side serve
        # waits, and its own wedged gets are half the hang picture),
        # rpc replies (handled by WorkerRuntime paths), or EOF (head
        # died -> try to reconnect).
        while True:
            try:
                while True:
                    msg = self.conn.recv()
                    if not isinstance(msg, dict):
                        continue
                    t = msg.get("t")
                    if t == "exit":
                        self.disconnected.set()
                        return
                    if t == "flight_pull":
                        self.send_async(flight.pull_reply(msg))
                    elif t == "stack_dump":
                        self.send_async(stacks.dump_reply(msg))
            except (EOFError, OSError, TypeError):
                # TypeError: the conn's fd was torn down mid-recv by
                # interpreter shutdown (read(None, ...)); same as EOF
                pass
            try:
                ok = not self._closing and self._reconnect()
            except Exception:
                ok = False  # never die silently: liveness must resolve
            if not ok:
                self.disconnected.set()
                return

    def _reconnect(self) -> bool:
        from .config import cfg
        timeout = cfg.driver_reconnect_timeout_s
        if timeout <= 0:
            return False
        import time
        deadline = time.monotonic() + timeout
        delay = 0.25
        while not self._closing and time.monotonic() < deadline:
            conn = reply = None
            # the restarted head writes a NEW session dir: try the original
            # address first (a stable path), then fall back to auto-resolve
            for addr in (self._address_arg, None):
                try:
                    cf_path = resolve_cluster_file(addr)
                    conn, reply = _dial(cf_path)
                except ProtocolMismatchError as e:
                    # deterministic refusal — retrying cannot succeed
                    print(f"driver reconnect refused: {e}", flush=True)
                    return False
                except (ConnectionError, OSError, EOFError, ValueError,
                        mp.AuthenticationError):
                    continue
                # identity check: only attach to OUR cluster — the same
                # session (transient drop) or a head that RESUMED from it
                # (restart). Auto-resolve picks the newest local cluster
                # file, which on a busy box can belong to an unrelated
                # cluster; silently hijacking onto it would cross-wire
                # two jobs (reference analog: GCS FT clients reconnect to
                # a fixed redis-backed address, never to "any GCS").
                mine = getattr(self, "_session_dir", None)
                if mine and reply.get("session_dir") != mine and \
                        reply.get("resumed_from") != mine:
                    try:
                        conn.close()
                    except Exception:
                        pass  # already closing a failed dial
                    conn = reply = None
                    continue
                break
            if conn is None:
                # reconnect backoff runs on the conn-loop thread while the
                # link is DOWN: there are no inbound frames to stall
                time.sleep(delay)  # graftlint: disable=GL013
                delay = min(delay * 2, 2.0)
                continue
            store = SharedObjectStore(reply["store_path"], create=False)
            spill = (SpillStore(reply["spill_dir"])
                     if reply.get("spill_dir") else None)
            # snapshot ref interest BEFORE send_lock: ref_created/_drop_loop
            # hold _ref_lock across send(), which parks on reconnect — taking
            # _ref_lock inside send_lock here would deadlock (lock-order
            # inversion). A ref added in the window replays itself: its own
            # parked ref_add completes after the gen bump.
            with self._ref_lock:
                live = list(self._ref_counts)
            # swap AND replay under send_lock: user threads parked in
            # send() cannot slip a submit onto the new conn before its
            # func_def replays land (ordering bug otherwise); _conn_gen
            # is bumped only after the replay succeeds, so parked senders
            # wake into a fully re-registered session
            with self.send_lock:
                self.conn = conn
                self.store = store
                self.spill = spill
                self.wid = reply["wid"]
                # restart chains: the NEW session becomes our identity,
                # so a later restart resuming from IT still matches
                self._session_dir = reply.get("session_dir") or \
                    getattr(self, "_session_dir", None)
                self._sent_fids.clear()
                self._sent_renvs.clear()
                # the new head knows nothing about us: re-ship function
                # defs, re-register ref interest, resubmit unresolved
                # plain tasks (their results died with the old store)
                try:
                    for fid, blob in list(self._fid_blobs.items()):
                        conn.send({"t": "func_def", "fid": fid,
                                   "blob": blob})
                        self._sent_fids.add(fid)
                    for oid in live:
                        conn.send({"t": "ref_add", "oid": oid.binary()})
                    with self._track_lock:
                        seen, cand = set(), []
                        for spec in self._unresolved.values():
                            if spec.task_id not in seen:
                                seen.add(spec.task_id)
                                cand.append(spec)
                    # submits still parked in the flush buffer were NEVER
                    # sent (a failed flush requeues its frame before
                    # raising, under send_lock — which we hold): they ship
                    # themselves after the swap, ORDERED AFTER the
                    # func_def replay above, so resubmitting them here
                    # would run those tasks twice. Snapshot the buffer
                    # AFTER reading _unresolved: a racing submit_task
                    # appends to _sbuf before it registers in _unresolved,
                    # so any spec the scan above saw is already visible
                    # here if it is still unsent.
                    with self._sbuf_lock:
                        buffered_tids = set()
                        for m in self._sbuf:
                            if not isinstance(m, dict) or \
                                    m.get("t") not in ("submit",
                                                       "actor_call"):
                                continue
                            # they will flush into the NEW session: re-key
                            # their owner like the replayed specs get,
                            # else device-object fetches would route to
                            # the dead session's wid
                            m["spec"].owner = self.wid
                            if m["t"] == "submit":
                                buffered_tids.add(m["spec"].task_id)
                    for spec in cand:
                        if spec.task_id in buffered_tids:
                            continue
                        spec.owner = self.wid
                        conn.send({"t": "submit", "spec": spec})
                except (OSError, ValueError, BrokenPipeError):
                    continue  # head died again mid-replay; retry dial
            self._conn_gen += 1
            # kick the flush buffer: parked rider threads retry on their
            # own, but messages whose rider already gave up (deadline)
            # would otherwise strand until the next send
            try:
                super()._try_flush()
            except Exception:
                pass  # riders retry on their own; kick is best-effort
            # the restarted head's metric store is empty: re-mark gauge
            # series dirty (last-write-wins values only live on the head)
            # and re-ship everything on the spot
            try:
                from ..util import metrics as _um
                _um.mark_gauges_dirty()
                _um.flush()
            except Exception:
                pass  # next 2s flush tick re-ships
            return True
        return False

    def _flush_now(self):
        self._ride(super()._flush_now)

    def _try_flush(self):
        self._ride(super()._try_flush)

    def _ride(self, flush_fn):
        """Flushes ride out a head restart: a failed drain has already
        requeued its messages at the front of the buffer (base class), so
        this blocks until the reconnect loop swaps in a live connection
        and then re-flushes — the replay saw the parked messages in the
        buffer and excluded them from resubmission, so a ridden-out
        restart delivers them exactly once. On give-up (ConnectionError
        after the reconnect deadline) the messages STAY queued: a later
        successful reconnect may still deliver them, so a caller that saw
        the error must treat its submits as at-most-once-PLUS-pending,
        not as never-sent, before resubmitting side-effecting work."""
        deadline = None  # computed on first failure: the happy path runs
        while True:     # flush_fn with zero per-send overhead
            gen = self._conn_gen
            try:
                return flush_fn()
            except (OSError, EOFError, ValueError, BrokenPipeError) as err:
                if self._closing:
                    raise
                if isinstance(err, ValueError) and \
                        not getattr(self.conn, "closed", True):
                    # deterministic serialization failure on a LIVE
                    # connection (the drain already isolated/dropped it):
                    # not a head restart — parking here would stall the
                    # caller for the whole reconnect deadline and then
                    # mask the real error with a bogus ConnectionError
                    raise
                import time
                from .config import cfg
                if deadline is None:
                    deadline = time.monotonic() + max(
                        cfg.driver_reconnect_timeout_s, 1.0)
                while (self._conn_gen == gen
                       and not self.disconnected.is_set()
                       and time.monotonic() < deadline):
                    time.sleep(0.05)
                if self._conn_gen == gen:
                    raise ConnectionError(
                        "head connection lost and not re-established")
                # reconnected: a flush spanning ANOTHER restart gets a
                # fresh ride budget per leg, not the first leg's remnant
                deadline = None

    def timeline(self):
        return self._rpc("timeline")

    def shutdown(self):
        # _closing FIRST: it makes _ride fail fast, so the final flush
        # ships over a live head but never stalls teardown for the
        # reconnect deadline when the head is already gone (the deltas
        # were lost with the head's store anyway)
        self._closing = True
        try:
            from ..util.metrics import shutdown_flush
            shutdown_flush()  # last counter deltas before the conn dies
        except Exception:
            pass  # deltas died with the head's store
        try:
            self.flush()  # buffered submits/drops, best effort
        except Exception:
            pass  # head may already be gone
        self.disconnected.set()
        try:
            self.conn.close()
        except Exception:
            pass  # conn already dead/closed
        try:
            self.store.close(unlink=False)
        except Exception:
            pass  # unmap is best-effort at exit
        if rt_mod.get_runtime_if_exists() is self:
            rt_mod.set_runtime(None)


def _dial(cf_path: str):
    """Open a control connection + driver registration for a cluster file.
    Returns (conn, registration reply)."""
    with open(cf_path) as f:
        cf = json.load(f)
    authkey = bytes.fromhex(cf["authkey"])
    unix_addr = cf.get("unix_addr")
    if unix_addr and os.path.exists(unix_addr):
        conn = Client(unix_addr, "AF_UNIX", authkey=authkey)
    else:
        host = cf["tcp_host"]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        conn = Client((host, cf["tcp_port"]), "AF_INET", authkey=authkey)
    conn.send({"t": "register_driver", "pid": os.getpid(),
               "pv": PROTOCOL_VERSION})
    # dial-time handshake: the conn loop only reaches _dial while the old
    # link is dead, so blocking on the registration reply is the point
    reply = conn.recv()  # graftlint: disable=GL013
    if reply.get("t") == "rejected":
        # structured refusal (e.g. wire-protocol mismatch): deterministic,
        # NOT retryable — reconnect loops must surface it, not back off
        conn.close()
        raise ProtocolMismatchError(reply.get("error", "rejected"))
    if reply.get("t") != "registered_driver":
        conn.close()
        raise ConnectionError(f"head rejected driver registration: {reply}")
    if reply.get("pv") != PROTOCOL_VERSION:
        # symmetric check: a pre-versioning head never sends pv
        conn.close()
        raise ProtocolMismatchError(
            f"head speaks wire-protocol version {reply.get('pv')!r}, "
            f"this driver speaks {PROTOCOL_VERSION}")
    return conn, reply


def connect(address: str | None = None,
            namespace: str | None = None) -> dict:
    """Connect as a driver; sets the process runtime. Returns init info."""
    cf_path = resolve_cluster_file(address)
    with open(cf_path) as f:
        cf = json.load(f)
    conn, reply = _dial(cf_path)
    store = SharedObjectStore(reply["store_path"], create=False)
    spill = SpillStore(reply["spill_dir"]) if reply.get("spill_dir") else None
    rt = DriverRuntime(store, conn, reply["wid"], spill,
                       address_arg=address)
    # named-actor scoping: this driver's default namespace (a job driver
    # inherits the submitting cluster's via RTPU_NAMESPACE)
    rt.namespace = namespace or os.environ.get("RTPU_NAMESPACE", "default")
    # cluster identity for reconnect verification (_reconnect): the
    # session we attached to; updated on each successful reconnect so
    # restart CHAINS keep matching
    rt._session_dir = reply.get("session_dir") or cf.get("session_dir")
    rt_mod.set_runtime(rt)
    return {"address": cf_path, "wid": reply["wid"],
            "job_id": reply["job_id"], "session_dir": cf["session_dir"]}
