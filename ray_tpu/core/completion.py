"""Completion multiplexer: ONE wait_sealed thread resolves every awaited
ObjectRef in the process.

The old ``await ref`` path parked one executor thread per awaited ref in
a blocking ``get`` — N in-flight awaits cost N threads, N poll loops and
N GIL contenders, so await latency grew with the in-flight count. Here
every waiter registers its oid with a single daemon thread that parks in
one ``store.wait_sealed`` call over the whole watch set (plus a doorbell
object): a seal wakes it, it deserializes the ready value once and feeds
the waiter's asyncio loop via ``call_soon_threadsafe`` (or resolves a
``concurrent.futures.Future`` for ``ref.future()``). Registration while
the thread is parked rings the doorbell — a 1-byte create+seal whose
seal-sequence bump wakes the wait instantly.

Objects that never seal locally (spilled to disk, produced on another
node, evicted and awaiting lineage re-execution) are handled between
wait slices: a spill hit resolves from disk; anything absent for more
than a beat gets the runtime's recovery machinery nudged
(``_mux_nudge``: head — ensure + schedule; worker — ensure send + pull).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from .ids import ObjectID
from . import flight

# wait-slice length: only the re-check cadence for spill hits and
# recovery nudges — a seal (or the doorbell) wakes the thread instantly
_SLICE_MS = 200
# how long an oid may sit unsealed before the runtime's recovery
# machinery is nudged, and how often the nudge repeats per oid
_NUDGE_AFTER_S = 0.5

_create_lock = threading.Lock()


def mux_for(rt) -> Optional["CompletionMux"]:
    """The process-wide mux for a runtime (created on first use), or None
    when the runtime has no shm store (local mode)."""
    m = getattr(rt, "_completion_mux", None)
    if m is not None:
        return m
    if getattr(rt, "store", None) is None:
        return None
    with _create_lock:
        m = getattr(rt, "_completion_mux", None)
        if m is None:
            m = CompletionMux(rt)
            rt._completion_mux = m
    return m


class _Watch:
    __slots__ = ("cbs", "since", "last_nudge")

    def __init__(self, cb):
        self.cbs = [cb]
        self.since = time.monotonic()
        self.last_nudge = self.since


class CompletionMux:
    def __init__(self, rt):
        self._rt = rt
        self._store = rt.store
        self._spill = getattr(rt, "spill", None)
        self._lock = threading.Lock()
        self._watch: dict[ObjectID, _Watch] = {}  # guarded by: self._lock
        self._evt = threading.Event()
        self._bell = ObjectID.from_random()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-completions")
        self._thread.start()

    # -- registration ------------------------------------------------------

    def watch(self, oid: ObjectID, on_ready: Callable[[], None]) -> None:
        """Call `on_ready()` from the mux thread once `oid` is readable
        (sealed in shm or present in spill). Fires immediately via the
        normal loop pass when the object is already there."""
        with self._lock:
            w = self._watch.get(oid)
            if w is not None:
                w.cbs.append(on_ready)
            else:
                self._watch[oid] = _Watch(on_ready)
        self._evt.set()
        self._ring()

    def unwatch(self, oid: ObjectID, on_ready) -> None:
        """Drop one registered callback (a cancelled await); the entry
        dies with its last callback."""
        with self._lock:
            w = self._watch.get(oid)
            if w is None:
                return
            try:
                w.cbs.remove(on_ready)
            except ValueError:
                return  # already fired or never registered
            if not w.cbs:
                self._watch.pop(oid, None)

    def _ring(self) -> None:
        """Wake a parked wait_sealed: create+seal the doorbell object (its
        seal-seq bump is the wakeup; the loop deletes it)."""
        try:
            buf = self._store.create_raw(self._bell, 1)
            buf[0:1] = b"\x01"
            del buf
            self._store.seal(self._bell)
        except FileExistsError:
            pass  # already rung; the loop hasn't consumed it yet
        except Exception:
            # Store closing (loop exiting, nothing to do) — or a failed
            # write/seal, which would strand the bell UNSEALED: every
            # later ring would die on FileExistsError above and the mux
            # loop would never wake again. Drop the half-created bell so
            # the next ring re-creates it.
            try:
                self._store.delete(self._bell)
            except Exception:
                pass  # store really is closing


    # -- the mux thread ----------------------------------------------------

    def _fire(self, oid: ObjectID) -> None:
        with self._lock:
            w = self._watch.pop(oid, None)
        if w is None:
            return
        for cb in w.cbs:
            try:
                cb()
            except Exception:
                import traceback
                traceback.print_exc()  # one bad waiter must not kill the mux

    def _loop(self) -> None:
        while True:
            with self._lock:
                oids = list(self._watch)
            if not oids:
                self._evt.wait()
                self._evt.clear()
                continue
            try:
                flags = self._store.wait_sealed([self._bell] + oids, 1,
                                                _SLICE_MS)
            except Exception:
                return  # store closed: process is tearing down
            if flags[0]:
                try:
                    self._store.delete(self._bell)
                except Exception:
                    return  # store closed mid-delete: tearing down
            now = time.monotonic()
            n_fired = 0
            for oid, sealed in zip(oids, flags[1:]):
                if sealed or (self._spill is not None
                              and self._spill.contains(oid)):
                    n_fired += 1
                    self._fire(oid)
                    continue
                with self._lock:
                    w = self._watch.get(oid)
                    nudge = (w is not None
                             and now - w.since > _NUDGE_AFTER_S
                             and now - w.last_nudge > _NUDGE_AFTER_S)
                    if nudge:
                        w.last_nudge = now
                if nudge:
                    try:
                        self._rt._mux_nudge(oid)
                    except Exception:
                        pass  # recovery is best-effort; the slice retries
            if n_fired:
                flight.evt(flight.MUX_WAKE, n_fired, len(oids))


# -- waiter plumbing (used by ObjectRef.__await__ / .future()) ------------


def _resolve_now(rt, ref) -> tuple[Any, Optional[BaseException]]:
    """Materialize a ready ref in the mux thread (sealed/spilled, so this
    is a non-blocking deserialize; stored task errors surface here)."""
    try:
        return rt.get(ref), None
    except BaseException as e:  # noqa: BLE001 — delivered to the waiter
        return None, e


def async_future(ref, loop):
    """An asyncio future on `loop` resolving to the ref's value via the
    mux (or the legacy one-thread-per-await executor hop when the mux is
    unavailable or cfg.dag_ref_wait_executor forces it)."""
    import asyncio

    from .config import cfg
    from . import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    mux = None
    if rt is not None and not cfg.dag_ref_wait_executor:
        mux = mux_for(rt)
    if mux is None:
        from .api import get as _get
        return loop.run_in_executor(None, lambda: _get(ref))
    fut = loop.create_future()

    def deliver(val, err):
        if fut.cancelled():
            return
        if err is not None:
            fut.set_exception(err)
        else:
            fut.set_result(val)

    def on_ready():
        val, err = _resolve_now(rt, ref)
        try:
            loop.call_soon_threadsafe(deliver, val, err)
        except RuntimeError:
            pass  # loop closed while we resolved; nobody is listening

    mux.watch(ref.id(), on_ready)
    # a cancelled await must not leave a dead callback watched forever
    fut.add_done_callback(
        lambda f: mux.unwatch(ref.id(), on_ready) if f.cancelled() else None)
    return fut


def sync_future(ref):
    """A concurrent.futures.Future for ref.future(): resolved in the mux
    thread (falls back to a dedicated thread without a store)."""
    import concurrent.futures

    from . import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    mux = mux_for(rt) if rt is not None else None
    fut: concurrent.futures.Future = concurrent.futures.Future()
    if mux is None:
        from .api import get as _get

        def _resolve():
            try:
                fut.set_result(_get(ref))
            except BaseException as e:  # noqa: BLE001 — handed to waiter
                fut.set_exception(e)

        threading.Thread(target=_resolve, daemon=True).start()
        return fut

    def on_ready():
        val, err = _resolve_now(rt, ref)
        if fut.set_running_or_notify_cancel():
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(val)

    mux.watch(ref.id(), on_ready)
    return fut
