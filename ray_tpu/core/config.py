"""Typed runtime config flags, overridable via ``RTPU_*`` env vars.

Reference parity: the ``RAY_CONFIG`` flag system
(src/ray/common/ray_config.h:60, ray_config_def.h — 226 entries).  The
reference generates a C++ class whose every field reads a ``RAY_<name>`` env
var at process start; here a flag is a typed descriptor on a singleton, read
once at first access and cacheable, with ``RTPU_<NAME>`` (upper-cased) as
the override channel.  Workers inherit the head's environment, so flags set
before ``init()`` propagate to the whole local cluster.

Usage::

    from ray_tpu.core.config import cfg
    cap = cfg.object_store_memory
    cfg.override(worker_prestart=0)      # tests / programmatic override
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable


def _parse_bool(s: str) -> bool:
    return s.strip().lower() in ("1", "true", "yes", "on")


class Flag:
    """One typed config entry (a ``RAY_CONFIG(type, name, default)`` row)."""

    __slots__ = ("name", "default", "type", "doc", "env")

    def __init__(self, name: str, default: Any, doc: str = ""):
        self.name = name
        self.default = default
        self.type = type(default)
        self.doc = doc
        self.env = f"RTPU_{name.upper()}"

    def parse(self, raw: str) -> Any:
        if self.type is bool:
            return _parse_bool(raw)
        if self.type is int:
            return int(raw, 0)  # accepts 0x..., underscores not needed
        return self.type(raw)


class Config:
    """Singleton flag table. Attribute access returns the effective value:
    programmatic override > ``RTPU_*`` env var > default."""

    def __init__(self, flags: list[Flag]):
        self._flags = {f.name: f for f in flags}
        self._overrides: dict[str, Any] = {}
        self._cache: dict[str, Any] = {}
        self._lock = threading.Lock()

    def __getattr__(self, name: str) -> Any:
        # __getattr__ only fires for names not found normally, so _flags
        # etc. resolve through __init__'s instance dict without recursion.
        flags = object.__getattribute__(self, "_flags")
        if name not in flags:
            raise AttributeError(f"unknown config flag {name!r}")
        with self._lock:
            if name in self._overrides:
                return self._overrides[name]
            if name in self._cache:
                return self._cache[name]
            f = flags[name]
            raw = os.environ.get(f.env)
            val = f.default if raw is None else f.parse(raw)
            self._cache[name] = val
            return val

    def override(self, **kv: Any) -> None:
        """Programmatically pin flags (tests, embedders). Type-checked."""
        with self._lock:
            for name, val in kv.items():
                f = self._flags.get(name)
                if f is None:
                    raise AttributeError(f"unknown config flag {name!r}")
                if not isinstance(val, f.type) and not (
                        f.type is float and isinstance(val, int)):
                    raise TypeError(
                        f"{name} expects {f.type.__name__}, got "
                        f"{type(val).__name__}")
                self._overrides[name] = f.type(val)

    def overrides_for_env(self) -> dict[str, str]:
        """Current programmatic overrides as {RTPU_* env name: str value},
        for shipping driver-side cfg.override()s to spawned workers."""
        with self._lock:
            out = {}
            for name, val in self._overrides.items():
                f = self._flags[name]
                if f.type is bool:
                    out[f.env] = "1" if val else "0"
                else:
                    out[f.env] = str(val)
            return out

    def reset(self, *names: str) -> None:
        """Drop overrides/cache (all flags when called with no names)."""
        with self._lock:
            if not names:
                self._overrides.clear()
                self._cache.clear()
            for n in names:
                self._overrides.pop(n, None)
                self._cache.pop(n, None)

    def dump(self) -> dict[str, Any]:
        """Effective value of every flag (for the state API / debugging)."""
        return {n: getattr(self, n) for n in self._flags}

    def describe(self) -> list[dict[str, Any]]:
        out = []
        for n, f in self._flags.items():
            out.append({"name": n, "env": f.env, "type": f.type.__name__,
                        "default": f.default, "value": getattr(self, n),
                        "doc": f.doc})
        return out


_FLAGS = [
    # ---- object store / memory -------------------------------------- #
    Flag("object_store_memory", 2 << 30,
         "shm object store capacity in bytes"),
    Flag("object_spilling_threshold", 0.8,
         "store fill fraction above which sealed objects spill to disk"),
    Flag("min_spilling_size", 1 << 20,
         "don't spill objects smaller than this (bytes)"),
    Flag("put_copy_threads", 0,
         "threads for the large-piece memmove on the put path (0 = auto: "
         "4 when pieces exceed the parallel threshold; 1 = always copy "
         "single-threaded). ctypes.memmove releases the GIL, so slicing "
         "one multi-hundred-MiB copy across threads tracks memory "
         "bandwidth instead of one core's share of it"),
    Flag("tracing_enabled", False,
         "propagate (trace_id, span_id) context through task submission "
         "and record per-task spans in the timeline (util/tracing.py)"),
    Flag("transfer_chunk_bytes", 8 << 20,
         "cross-node object pulls move in pieces of this size: a transport "
         "failure resumes from the last good byte instead of restarting "
         "the whole frame, and frames larger than the local store stream "
         "to the spill directory piecewise"),
    Flag("collective_inline_bytes", 64 << 10,
         "collective payloads up to this size ride inside the rendezvous "
         "actor message (one round trip); larger ones move store-to-store "
         "as ObjectRefs so bulk bytes never funnel through one process"),
    Flag("zero_copy_get", False,
         "deserialize large buffers as read-only views pinned into the shm "
         "store (released when the arrays are GC'd) instead of copying "
         "them out — plasma's semantics; arrays come back non-writable"),
    Flag("store_prefault", False,
         "fault in the whole store mapping at create (one-time cost "
         "~0.4s/GiB) so big puts run at warm-memcpy speed; production "
         "long-lived clusters want this on"),
    Flag("memory_monitor_refresh_ms", 250,
         "memory-monitor poll interval; 0 disables the monitor"),
    Flag("memory_usage_threshold", 0.95,
         "host memory fraction above which the OOM killer engages"),
    # ---- scheduler / worker pool ------------------------------------ #
    Flag("worker_prestart", 4,
         "max workers prestarted at init so first tasks skip cold-start"),
    Flag("worker_idle_timeout_s", 60.0,
         "idle workers beyond the prestart pool are reaped after this"),
    Flag("head_tcp_port", 0,
         "fixed TCP port for the head's control listener (0 = ephemeral); "
         "set it (plus RTPU_CLUSTER_AUTHKEY) so agents/drivers can re-dial "
         "a restarted head at the same address"),
    Flag("driver_reconnect_timeout_s", 30.0,
         "how long an external driver retries dialing a restarted head "
         "before its pending calls fail (0 disables reconnection)"),
    Flag("worker_pipeline_depth", 4,
         "extra same-shape tasks queued on a busy worker so the done->"
         "dispatch round-trip leaves the critical path (0 disables); "
         "idle workers steal from the longest pipeline, so skew does "
         "not strand work behind a slow task"),
    Flag("scheduler_spread_threshold", 0.5,
         "node utilization below which the hybrid policy packs"),
    Flag("task_retry_delay_ms", 0,
         "delay before re-submitting a retriable failed task"),
    Flag("actor_restart_delay_ms", 0,
         "delay before restarting a restartable dead actor"),
    Flag("pg_retry_timeout_s", 120.0,
         "how long placement groups keep retrying reservation"),
    # ---- control plane ---------------------------------------------- #
    Flag("control_batching", True,
         "coalesce control-plane messages (submit/done/ref traffic) into "
         "batch frames via the adaptive flush buffer, and coalesce burst "
         "submissions into shared scheduling passes on the head; off "
         "restores one-message-per-write for debugging (results must be "
         "identical either way)"),
    Flag("send_batch_max", 512,
         "force-flush the control-plane send buffer at this many queued "
         "messages (bounds per-frame pickle size and head-side latency)"),
    Flag("submit_burst_window_us", 100.0,
         "an in-process submit arriving within this window of the "
         "previous one is treated as part of a burst: its scheduling "
         "pass is deferred to the scheduler pump so one pass (and one "
         "batched frame per worker) serves the whole burst; 0 schedules "
         "every submit inline"),
    Flag("rpc_pool_workers", 32,
         "threads serving worker->head RPCs (pg_wait parks here)"),
    Flag("driver_submit_queue", True,
         "in-process driver submits enqueue to the scheduler pump (one "
         "lock acquisition + one scheduling pass per burst, v2-style "
         "presumed interest) instead of taking the runtime lock per "
         ".remote(); off restores per-call inline submission for "
         "debugging — results must be identical either way"),
    Flag("dag_sealed_channels", True,
         "compiled-DAG edges ride sealed ring channels (futex wait on "
         "{data, stop}, ack-object ring retirement, zero-copy reads "
         "allowed) instead of the legacy delete-and-recreate polling "
         "transport; off restores the polling transport — results must "
         "be bit-identical either way"),
    Flag("dag_ref_wait_executor", False,
         "await ObjectRef falls back to the legacy one-thread-per-await "
         "executor hop instead of the shared wait_sealed completion "
         "multiplexer (debugging)"),
    Flag("task_records_max", 10000,
         "bounded task-state records kept for the state API"),
    Flag("timeline_events_max", 20000,
         "bounded chrome-trace timeline events kept in memory"),
    Flag("health_check_period_ms", 1000,
         "node-agent heartbeat period"),
    Flag("health_check_timeout_s", 10.0,
         "node declared dead after this long without a heartbeat"),
    Flag("gcs_snapshot_period_s", 5.0,
         "head-table persistence snapshot period; 0 disables"),
    # ---- serve ------------------------------------------------------- #
    Flag("serve_replica_poll_s", 10.0,
         "handle replica-set TTL refresh — fallback only; the long-poll "
         "listener pushes changes promptly"),
    Flag("serve_autoscale_period_s", 1.0,
         "controller reconcile/autoscale loop period"),
    Flag("serve_static_decode_plan", True,
         "streaming serve responses ride a sealed ring channel (replica "
         "drains the generator into shm, the handle reads it directly: "
         "zero control-plane dispatches per item in steady state) when "
         "handle and replica share an object store; off (or no shared "
         "store) falls back to per-chunk stream_next actor calls — "
         "items must be identical either way"),
    Flag("serve_stream_ring", 64,
         "in-flight item bound of the static decode plan's ring channel "
         "(producer blocks once this far ahead of the consumer)"),
    # ---- serve front door (serve/frontdoor/) ------------------------- #
    Flag("serve_num_proxies", 1,
         "HTTP proxies the controller keeps alive per application "
         "deploy (ports http_port..http_port+n-1); each is a "
         "controller-managed actor, replaced on death like a replica"),
    Flag("serve_admission_control", True,
         "SLO-aware admission at the proxies: per-deployment budgets "
         "derived from live replica capacity (replicas x "
         "max_ongoing_requests, split across proxies); past the budget "
         "requests queue with bounded depth and deadline, then shed as "
         "HTTP 429 + Retry-After instead of timing out as 500s"),
    Flag("serve_admission_queue_depth", 64,
         "per-proxy, per-deployment bound on requests parked waiting "
         "for an admission slot; arrivals past it shed immediately"),
    Flag("serve_admission_timeout_s", 2.0,
         "admission-queue deadline (the TTFT SLO contribution the "
         "queue may add): a request predicted or measured to wait "
         "longer sheds with a Retry-After estimate instead of queueing"),
    Flag("serve_prefix_directory", True,
         "cluster-wide prefix-cache directory: paged-engine replicas "
         "publish chained page hashes to the head (core/directory.py), "
         "and admission-match prefixes warmed on ANY replica by "
         "importing the KV pages from the owner over the object store"),
    Flag("serve_prefix_publish_s", 0.25,
         "how often a replica's engine loop drains newly published / "
         "evicted page hashes to the prefix directory (one async frame "
         "per drain with anything to report)"),
    Flag("serve_prefix_import_timeout_s", 10.0,
         "deadline for fetching a warmed prefix's KV pages from the "
         "owning replica; on timeout/death the entries are dropped "
         "from the directory and the request prefills cold (stale "
         "entries are hints, never correctness)"),
    Flag("dir_max_entries", 65536,
         "per-directory entry cap of the head's shared directory "
         "service (FIFO eviction; bounds head memory no matter how "
         "many pages the fleet publishes)"),
    # ---- multi-tenant serving (llm/multilora + tenant front door) ---- #
    Flag("llm_lora_refresh_s", 0.25,
         "TTL on a serving replica's cached latest-version lookups in "
         "the adapter registry: the upper bound on how long a freshly "
         "published adapter version takes to start serving (the "
         "hot-swap observation window), and the floor on dir_query "
         "cadence per adapter on the request hot path"),
    Flag("serve_tenant_fair", True,
         "weighted-fair admission queueing across tenants at the "
         "proxies: parked requests drain round-robin per tenant "
         "(deficit-weighted), so one tenant's burst cannot starve "
         "another tenant's queue position; off restores one global "
         "FIFO"),
    Flag("serve_tenant_max_share", 0.5,
         "per-tenant quota as a fraction of a deployment's admission "
         "budget (and of its queue depth): a TENANTED request past its "
         "tenant's share sheds 429+Retry-After (reason tenant_quota) "
         "while other tenants keep admitting. Applies only to requests "
         "that resolve a tenant id (header/body/adapter); untenanted "
         "traffic keeps the plain budget. 1.0 disables the quota"),
    Flag("serve_tenant_max_tracked", 64,
         "per-gate bound on distinct tenant ids tracked for quota / "
         "fair-queueing / metrics; tenants past the cap share one "
         "__other__ bucket (tenant ids are client-controlled — "
         "unbounded ids must not grow gate state or metric "
         "cardinality)"),
    # ---- metrics plane (ray_tpu/obs/) -------------------------------- #
    Flag("tsdb_enable", True,
         "head-side metrics TSDB (obs/tsdb.py): a scraper thread folds "
         "the merged user-metric store into fixed-memory per-series "
         "rings every tsdb_scrape_s, powering metrics_history(), the "
         "SLO burn-rate engine, cli top/slo and signal-driven "
         "autoscaling; off = instantaneous snapshots only (pre-PR-13 "
         "behavior)"),
    Flag("tsdb_scrape_s", 15.0,
         "TSDB scrape tick; SLO burn windows scale with it (240 ticks "
         "= the canonical 1h fast window at the 15 s default), so "
         "tests with a 50 ms tick exercise the full page/warn ladder "
         "in seconds"),
    Flag("tsdb_retention_points", 2048,
         "per-series ring capacity in samples (preallocated: 16 bytes "
         "per point; 2048 x 15 s default = 8.5 h of history, enough "
         "for the 6 h slow-burn window)"),
    Flag("tsdb_max_series", 4096,
         "hard cardinality cap across all (name, label-set) series; "
         "past it, samples for unseen label sets fold into a per-name "
         "__overflow__ sink — client-controlled labels can never grow "
         "head memory (ceiling = (max_series + one sink per metric "
         "NAME, code-controlled) x retention x 16 bytes, ~128 MiB at "
         "the defaults)"),
    Flag("serve_slo_ttft_s", 2.0,
         "shipped TTFT SLO threshold: 95% of requests must see first "
         "token within this many seconds (obs/slo.py ttft_p95)"),
    Flag("serve_slo_e2e_s", 10.0,
         "shipped end-to-end latency SLO threshold: 99% of proxied "
         "requests complete within this many seconds (e2e_p99)"),
    Flag("serve_slo_error_ratio", 0.01,
         "shipped error-ratio SLO budget: at most this fraction of "
         "proxy requests may error (error_ratio)"),
    Flag("serve_slo_shed_ratio", 0.05,
         "shipped admission shed-ratio SLO budget: at most this "
         "fraction of arrivals may shed 429 (shed_ratio)"),
    Flag("serve_autoscale_signals", "on",
         "signal-driven autoscaling (obs/scraper.py autoscale_signals "
         "composed into the serve controller's queue-depth rule): "
         "scale OUT when the shed rate, TTFT/e2e burn rate, TTFT "
         "slope or a per-tenant admission backlog says the SLO will "
         "be violated — BEFORE the first 429; 'off' reproduces the "
         "legacy ongoing-requests-only autoscaler exactly"),
    # ---- observability ----------------------------------------------- #
    Flag("metrics_export_port", 0,
         "Prometheus /metrics port (0 = ephemeral)"),
    Flag("event_export_enabled", False,
         "write task/actor events to session_dir/events.jsonl"),
    Flag("flight_recorder", True,
         "always-on per-process flight recorder (core/flight.py): "
         "sub-microsecond struct-packed event ring instrumenting the "
         "zero-dispatch fast paths; off = evt() is a no-op (the "
         "overhead A/B knob)"),
    Flag("flight_ring_slots", 16384,
         "flight-recorder ring capacity in events (rounded up to a "
         "power of two; 44 bytes/event — the default is ~720 KiB per "
         "process, overwritten oldest-first with a drop counter)"),
    Flag("stall_watchdog", True,
         "stuck-task watchdog on the head (core/stacks.py stall "
         "doctor): per-task-name runtime EWMAs flag tasks RUNNING "
         "beyond stuck_task_multiple x typical (with an absolute "
         "floor), auto-attach the owning worker's live stack to the "
         "task record, and emit rtpu_core_stuck_tasks metrics + a "
         "task_stuck flight event"),
    Flag("stall_watchdog_period_s", 2.0,
         "watchdog scan period (one pass over the bounded RUNNING "
         "task records; a scan does no control-plane traffic unless "
         "it flags something)"),
    Flag("stuck_task_multiple", 10.0,
         "a task is suspect once its runtime exceeds this multiple of "
         "its task-name EWMA (never below stuck_task_floor_s)"),
    Flag("stuck_task_floor_s", 30.0,
         "absolute minimum runtime before the watchdog may flag a "
         "task — also the threshold for task names with no history"),
]

cfg = Config(_FLAGS)
