"""Cluster-shared directory service (protocol v7).

Head-side named maps any peer can merge into (``dir_update``, async
fire-and-forget) and read from (``dir_query``, answered inline on the
head's recv thread — a pure dict read, so lookups on request hot paths
never queue behind the rpc pool). The serve front door rides two of
these: ``serve:routes`` (the proxies' shared route table, one snapshot
entry the controller republishes on every topology change) and
``serve:prefix:<model>`` (the cluster-wide prefix-cache directory:
chained page hash -> owning replica). The prefix directories also
carry two string-keyed entry families beside the 16-byte page hashes
— ``"heat:<proc>"`` replica cache summaries and, under the tiered
KV-cache, ``"spill:<hash hex>" -> {"m": model, "oid": ref bytes}``
rows pointing at store-materialized demoted pages. String keys cannot
collide with hash keys; both families are owner-stamped like any
entry, so they sweep with their replica.

Consistency model — entries are HINTS, never correctness:

- merges are last-write-wins per key, with no cross-key atomicity;
- a reader may see an entry whose owner has since died, evicted the
  underlying state, or republished elsewhere. Readers MUST validate on
  use (call the owner; on failure drop the keys and fall back) — the
  serve prefix importer re-prefills cold when a hint goes stale, so a
  stale directory can cost latency, never wrong bytes;
- entries published by a worker are owner-stamped with its wid and
  swept when that worker disconnects, bounding how long dead hints
  survive; per-directory entry counts are capped FIFO
  (cfg.dir_max_entries), so the head's memory is bounded no matter how
  many pages the fleet publishes.

Wire shapes::

    {"t": "dir_update", "d": name, "put": {key: value}, "drop": [key]}
    {"t": "dir_query", "d": name, "keys": [key] | None,
     "reply_oid": bytes}                       # None = whole directory

The query reply rides the existing ``rpc_reply`` plumbing (worker
_rpc_frame), status-tupled like every head rpc.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Iterable, Optional


class DirectoryService:
    """The head-side store behind dir_update/dir_query frames."""

    def __init__(self, max_entries: Optional[int] = None):
        if max_entries is None:
            from .config import cfg
            max_entries = cfg.dir_max_entries
        self._max = max(int(max_entries), 1)
        self._lock = threading.Lock()
        # name -> OrderedDict{key: (value, owner)} — guarded by: self._lock
        self._dirs: dict[str, "OrderedDict[Any, tuple]"] = {}
        # name -> monotonically increasing mutation count
        self._versions: dict[str, int] = {}    # guarded by: self._lock
        self._evictions = 0                    # guarded by: self._lock

    def merge(self, name: str, put: Optional[dict] = None,
              drop: Optional[Iterable] = None,
              owner: Optional[str] = None) -> int:
        """Apply a dir_update; returns the directory's new version.
        Re-put refreshes a key's FIFO position (the eviction order is
        oldest-write-first, so live prefixes keep re-arming)."""
        with self._lock:
            d = self._dirs.get(name)
            if d is None:
                d = self._dirs[name] = OrderedDict()
            changed = False
            for k in (drop or ()):
                if d.pop(k, None) is not None:
                    changed = True
            for k, v in (put or {}).items():
                d[k] = (v, owner)
                d.move_to_end(k)
                changed = True
            while len(d) > self._max:
                d.popitem(last=False)
                self._evictions += 1
                changed = True
            if changed:
                self._versions[name] = self._versions.get(name, 0) + 1
            return self._versions.get(name, 0)

    def lookup(self, name: str, keys: Optional[Iterable] = None) -> dict:
        """-> {"v": version, "entries": {key: value}}; with keys=None the
        whole directory (route-table snapshots are single-key, so this
        stays cheap)."""
        with self._lock:
            d = self._dirs.get(name) or {}
            if keys is None:
                entries = {k: v for k, (v, _o) in d.items()}
            else:
                entries = {}
                for k in keys:
                    got = d.get(k)
                    if got is not None:
                        entries[k] = got[0]
            return {"v": self._versions.get(name, 0), "entries": entries}

    def lookup_prefix(self, name: str, prefix: str) -> dict:
        """-> {key: value} for string keys starting with `prefix`. The
        cache heat plane files one bounded per-replica summary under a
        ``"heat:<proc>"`` string key next to the (bytes-keyed) page
        entries; this reads just those summaries without copying the
        up-to-64k page hashes a full lookup() would."""
        with self._lock:
            d = self._dirs.get(name) or {}
            return {k: v for k, (v, _o) in d.items()
                    if isinstance(k, str) and k.startswith(prefix)}

    def sweep_owner(self, wid: str) -> int:
        """Drop every entry a disconnected worker published; returns the
        number of entries removed."""
        swept = 0
        with self._lock:
            for name, d in self._dirs.items():
                stale = [k for k, (_v, o) in d.items() if o == wid]
                for k in stale:
                    del d[k]
                if stale:
                    swept += len(stale)
                    self._versions[name] = self._versions.get(name, 0) + 1
        return swept

    def stats(self) -> dict:
        with self._lock:
            return {"directories": {n: len(d)
                                    for n, d in self._dirs.items()},
                    "evictions": self._evictions}


# ------------------------------------------------------------------ #
# client helpers (worker / driver / head-local)
# ------------------------------------------------------------------ #

def update(name: str, put: Optional[dict] = None,
           drop: Optional[Iterable] = None) -> bool:
    """Merge entries into a head directory. Fire-and-forget from workers
    and drivers (one async frame, owner-stamped by the head from the
    sending connection); a direct call on the head. Returns False when
    no cluster runtime exists (local mode) — callers treat the
    directory as absent, never an error."""
    from . import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    if rt is None:
        return False
    if isinstance(rt, rt_mod.Runtime):
        rt.dirs.merge(name, put, drop, owner="head")
        return True
    send = getattr(rt, "send_async", None)
    if send is None:
        return False  # local-mode runtime: no control plane
    try:
        send({"t": "dir_update", "d": name,
              "put": dict(put) if put else None,
              "drop": list(drop) if drop else None})
        return True
    except Exception:
        return False  # head restarting; hints can wait for the next drain


def query(name: str, keys: Optional[Iterable] = None,
          timeout: float = 5.0) -> Optional[dict]:
    """Read entries from a head directory: {"v": int, "entries": {...}}.
    None when no cluster runtime / the head is unreachable — absence of
    the directory, not failure, per the hint contract."""
    from . import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    if rt is None:
        return None
    if isinstance(rt, rt_mod.Runtime):
        return rt.dirs.lookup(name, keys)
    if not hasattr(rt, "_rpc_frame"):
        return None  # local-mode runtime
    try:
        return rt._rpc_frame(
            {"t": "dir_query", "d": name,
             "keys": list(keys) if keys is not None else None},
            f"dir_query {name}", timeout=timeout)
    except Exception:
        # head unreachable / timeout: the directory is a hint service,
        # absence is a valid answer and the caller falls back cold
        return None
