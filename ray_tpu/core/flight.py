"""Flight recorder — always-on, sub-microsecond event tracing for the
zero-dispatch fast paths.

Why this exists: span tracing (util/tracing.py) is keyed to task
dispatches, and PRs 3/5/6 removed per-item dispatches from exactly the
paths that now dominate latency — batched control frames, sealed ring
channels, compiled-DAG loops, the serve static decode plan, Podracer
fragment queues. Those paths are invisible to dispatch-keyed tracing by
construction. The flight recorder is the always-on instrument for them:
a per-process, preallocated, struct-packed ring buffer whose ``evt()``
costs well under a microsecond — cheap enough to leave on in production
(TorchTitan makes built-in flight-recorder debugging a first-class
requirement for a training stack; this is that layer for ray_tpu).

Design constraints, in order:

- **No locks, no allocation on the hot path.** ``evt(code, a0..a3)``
  packs one fixed 44-byte record (monotonic ns, code, thread id low
  bits, four int64 args) into a preallocated ``bytearray`` ring. The
  slot index comes from ``itertools.count`` (its ``__next__`` is a
  single C call, atomic under the GIL), so concurrent emitters never
  contend. Argument errors (non-int, overflow) drop the record and bump
  a counter — the recorder can never raise into instrumented code.
- **Bounded memory, drop-counted overflow.** The ring holds
  ``cfg.flight_ring_slots`` records (rounded to a power of two); older
  events are overwritten (evicted) and ``dropped`` counts them. The
  recorder never blocks and never grows.
- **Strings never enter the ring.** Event codes are integers resolved
  against the catalogue below at EXPORT time; args are integers (object
  ids compressed to their low 48 bits via :func:`lo48`). graftlint
  GL010 enforces this at emit sites: f-strings, %-formatting,
  ``.format()`` calls and dict/list literals passed to ``evt()`` are
  findings — the cost of formatting must never ride the hot path.

Cluster collection: the head pulls each worker's ring on demand over
the existing control plane (``flight_pull``/``flight_ring`` frames —
protocol v5) and estimates each process's monotonic-clock offset
through the WALL clock as a bridge: each snapshot samples (mono, wall)
together, the head samples its own pair at receipt, and
offset = (their mono - their wall) - (our mono - our wall) — immune to
transport queueing delay, exact whenever wall clocks agree (always on
one host, NTP-close across hosts). Same-host processes share
CLOCK_MONOTONIC, so sub-millisecond residue is clamped to zero —
cross-process edges (producer seal -> consumer wake) then line up
exactly. :func:`export_chrome` renders the stitched
timeline as Chrome-trace/Perfetto JSON with flow arrows binding each
channel seal to the wake that consumed it. Surfaced as
``state.timeline(flight=True)`` and ``python -m ray_tpu.cli timeline``.

Enable/disable: on by default (``cfg.flight_recorder`` /
``RTPU_FLIGHT_RECORDER=0`` to disable — the A/B knob the overhead gate
uses). ``set_enabled(False)`` rebinds ``evt`` to a no-op, so disabled
cost is one no-op function call.
"""
from __future__ import annotations

import itertools
import os
import struct
import threading
import time
from typing import Any, Optional

# --------------------------------------------------------------------- #
# record layout
# --------------------------------------------------------------------- #

RECORD = struct.Struct("<QHHqqqq")   # ts_ns, code, tid16, a0..a3
RECSZ = RECORD.size                  # 44 bytes
_ZERO8 = bytes(8)                    # ts wipe for torn/dropped records

# --------------------------------------------------------------------- #
# event catalogue — codes are wire-stable integers; names/phases live
# here and are applied at export time only
# --------------------------------------------------------------------- #

# phases: "B"/"E" chrome begin/end (nest per thread track), "i" instant.
# flow: "s" opens a flow arrow keyed on (a0, a1); "f" closes it.

# head / scheduler
TASK_STATE = 1        # i  (task48, state_code)
SCHED_BEGIN = 2       # B  ()
SCHED_END = 3         # E  ()
BATCH_RECV = 4        # i  (n_msgs,)
TASK_STUCK = 5        # i  (task48, over_ms)  stall-doctor watchdog flag
DEADLOCK = 6          # i  (n_parties,)       wait-graph cycle reported

# worker
EXEC_BEGIN = 10       # B  (task48,)
EXEC_END = 11         # E  (task48, ok)
CTRL_FLUSH = 12       # i  (n_msgs,)
OBJ_MISS = 13         # i  (oid48,)

# object store
OBJ_CREATE = 20       # i  (oid48, size)
OBJ_SEAL = 21         # i  (oid48,)
WAIT_BEGIN = 22       # B  (n, min_count)
WAIT_END = 23         # E  (n_sealed,)

# sealed ring channels
CHAN_SEAL = 30        # i + flow s  (chan48, seq)
CHAN_WAKE = 31        # i + flow f  (chan48, seq)
CHAN_ACK = 32         # i  (ackchan48, seq)
CREDIT_BEGIN = 33     # B  (chan48, seq)
CREDIT_END = 34       # E  (chan48,)
CHAN_STOP = 35        # i  (stop48,)

# completion mux
MUX_WAKE = 40         # i  (n_fired, n_watched)

# compiled DAGs
DAG_STEP_BEGIN = 45   # B  (node_idx, seq)
DAG_STEP_END = 46     # E  (node_idx, seq)
DAG_EXEC = 47         # i  (seq,)

# serve
SRV_DISPATCH = 50     # i  (replica_idx, stream)
SRV_REQ_BEGIN = 51    # B  (req_seq,)
SRV_REQ_END = 52      # E  (req_seq, ok)
SRV_STREAM_START = 53  # i  (sid, transport)   transport: 0 poll, 1 chan
SRV_DRAIN_BEGIN = 54  # B  (sid,)
SRV_DRAIN_END = 55    # E  (sid, items)

# podracer / rl
FRAG_PUT = 60         # i  (producer_idx, seq)
FRAG_GET = 61         # i  (producer_idx,)
WEIGHT_PUB = 62       # i  (version,)
WEIGHT_FETCH = 63     # i  (version,)
SAMPLE_BEGIN = 64     # B  (producer_idx,)
SAMPLE_END = 65       # E  (producer_idx, frags)

# data streaming pipelines (data/streaming) — per-block seal/consume
# flow arrows ride the CHAN_SEAL/CHAN_WAKE records the channel layer
# already emits (chan48:seq flow ids); these add the stage spans and
# block-index annotations the timeline groups a pipeline by
DATA_STAGE_BEGIN = 80  # B  (stage_idx, worker_idx)
DATA_STAGE_END = 81    # E  (stage_idx, blocks)
DATA_BLOCK = 82        # i  (stage_idx, block_idx)

# metrics plane (obs/slo.py) — SLO alert state-machine transitions
SLO_TRANSITION = 90   # i  (slo_idx, to_state, from_state) 0 ok/1 warn/2 page

# llm prefix-cache heat plane (llm/paged_engine.py) — cache churn
PREFIX_EVICT = 91     # i  (pid, chain_slot)
PREFIX_IMPORT = 92    # i  (pages, chain_slot)

# jax step profiling (util/profiling.py)
STEP_BEGIN = 70       # B  (kind,)
STEP_END = 71         # E  (kind,)
JIT_COMPILE_BEGIN = 72  # B  (key48,)
JIT_COMPILE_END = 73  # E  (key48,)

#: code -> (name, category, phase, flow, (argname, ...))
CODES: dict[int, tuple] = {
    TASK_STATE: ("task_state", "task", "i", None, ("task", "state")),
    SCHED_BEGIN: ("sched_pass", "sched", "B", None, ()),
    SCHED_END: ("sched_pass", "sched", "E", None, ()),
    BATCH_RECV: ("batch_recv", "ctrl", "i", None, ("n",)),
    TASK_STUCK: ("task_stuck", "task", "i", None, ("task", "over_ms")),
    DEADLOCK: ("deadlock", "task", "i", None, ("parties",)),
    EXEC_BEGIN: ("task_exec", "task", "B", None, ("task",)),
    EXEC_END: ("task_exec", "task", "E", None, ("task", "ok")),
    CTRL_FLUSH: ("ctrl_flush", "ctrl", "i", None, ("n",)),
    OBJ_MISS: ("obj_miss", "store", "i", None, ("oid",)),
    OBJ_CREATE: ("obj_create", "store", "i", None, ("oid", "size")),
    OBJ_SEAL: ("obj_seal", "store", "i", None, ("oid",)),
    WAIT_BEGIN: ("store_wait", "store", "B", None, ("n", "min")),
    WAIT_END: ("store_wait", "store", "E", None, ("sealed",)),
    CHAN_SEAL: ("chan_seal", "chan", "i", "s", ("chan", "seq")),
    CHAN_WAKE: ("chan_wake", "chan", "i", "f", ("chan", "seq")),
    CHAN_ACK: ("chan_ack", "chan", "i", None, ("chan", "seq")),
    CREDIT_BEGIN: ("chan_credit", "chan", "B", None, ("chan", "seq")),
    CREDIT_END: ("chan_credit", "chan", "E", None, ("chan",)),
    CHAN_STOP: ("chan_stop", "chan", "i", None, ("stop",)),
    MUX_WAKE: ("mux_wake", "mux", "i", None, ("fired", "watched")),
    DAG_STEP_BEGIN: ("dag_step", "dag", "B", None, ("node", "seq")),
    DAG_STEP_END: ("dag_step", "dag", "E", None, ("node", "seq")),
    DAG_EXEC: ("dag_execute", "dag", "i", None, ("seq",)),
    SRV_DISPATCH: ("serve_dispatch", "serve", "i", None,
                   ("replica", "stream")),
    SRV_REQ_BEGIN: ("serve_request", "serve", "B", None, ("req",)),
    SRV_REQ_END: ("serve_request", "serve", "E", None, ("req", "ok")),
    SRV_STREAM_START: ("serve_stream", "serve", "i", None,
                       ("sid", "transport")),
    SRV_DRAIN_BEGIN: ("serve_drain", "serve", "B", None, ("sid",)),
    SRV_DRAIN_END: ("serve_drain", "serve", "E", None, ("sid", "items")),
    FRAG_PUT: ("frag_put", "rl", "i", None, ("producer", "seq")),
    FRAG_GET: ("frag_get", "rl", "i", None, ("producer",)),
    WEIGHT_PUB: ("weight_publish", "rl", "i", None, ("version",)),
    WEIGHT_FETCH: ("weight_fetch", "rl", "i", None, ("version",)),
    SAMPLE_BEGIN: ("rollout_sample", "rl", "B", None, ("producer",)),
    SAMPLE_END: ("rollout_sample", "rl", "E", None,
                 ("producer", "frags")),
    DATA_STAGE_BEGIN: ("data_stage", "data", "B", None,
                       ("stage", "worker")),
    DATA_STAGE_END: ("data_stage", "data", "E", None,
                     ("stage", "blocks")),
    DATA_BLOCK: ("data_block", "data", "i", None, ("stage", "idx")),
    SLO_TRANSITION: ("slo_transition", "obs", "i", None,
                     ("slo", "to", "from")),
    PREFIX_EVICT: ("prefix_evict", "llm", "i", None, ("pid", "chain")),
    PREFIX_IMPORT: ("prefix_import", "llm", "i", None,
                    ("pages", "chain")),
    STEP_BEGIN: ("jax_step", "jax", "B", None, ("kind",)),
    STEP_END: ("jax_step", "jax", "E", None, ("kind",)),
    JIT_COMPILE_BEGIN: ("jit_compile", "jax", "B", None, ("key",)),
    JIT_COMPILE_END: ("jit_compile", "jax", "E", None, ("key",)),
}

#: task-state strings <-> compact codes for TASK_STATE records
TASK_STATES = {"PENDING": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 3,
               "RETRYING": 4, "CANCELLED": 5}
_TASK_STATE_NAMES = {v: k for k, v in TASK_STATES.items()}


def lo48(oid: Any) -> int:
    """Compress an ObjectID/TaskID (or raw id bytes / channel base) to
    its low 48 bits — enough to correlate records without strings."""
    b = oid if isinstance(oid, bytes) else oid.binary()
    return int.from_bytes(b[:6], "little")


# --------------------------------------------------------------------- #
# the recorder
# --------------------------------------------------------------------- #

class FlightRecorder:
    """Preallocated struct-packed ring. One per process; create via the
    module functions, not directly (tests may instantiate with a small
    slot count through install_for_test)."""

    __slots__ = ("buf", "cap", "mask", "ctr", "bad", "_peeked")

    def __init__(self, slots: int):
        cap = 1 << max(6, (max(2, slots) - 1).bit_length())
        self.buf = bytearray(cap * RECSZ)
        self.cap = cap
        self.mask = cap - 1
        self.ctr = itertools.count()
        self.bad = 0
        self._peeked = 0

    def count(self) -> int:
        """Events recorded so far. itertools.count can't be peeked, so
        this consumes one ring index and compensates in the returned
        total; the consumed slot's timestamp is zeroed so decode reads
        it as empty (after the ring wraps it would otherwise still hold
        a record from one full generation earlier — a spurious ancient
        event in every export)."""
        idx = next(self.ctr)
        off = (idx & self.mask) * RECSZ
        self.buf[off:off + 8] = _ZERO8
        n = idx - self._peeked
        self._peeked += 1
        return n

    def snapshot(self, stats_only: bool = False) -> dict:
        n = self.count()
        snap = {
            "pid": os.getpid(),
            "proc": _proc_name,
            "cap": self.cap,
            "recorded": n,   # same key stats() uses — one snapshot shape
            "dropped": max(0, n - self.cap),
            "bad": self.bad,
            "mono_ns": time.monotonic_ns(),
            "wall_ns": time.time_ns(),
            "counters": dict(counters),
        }
        if not stats_only:
            snap["buf"] = bytes(self.buf)
        return snap


def decode(buf: bytes) -> list[tuple]:
    """Ring bytes -> [(ts_ns, code, tid, a0, a1, a2, a3)] sorted by ts.
    Empty slots (ts == 0) are skipped; a record mid-overwrite at capture
    time can tear (diagnostic tool, not a transactional log) — unknown
    codes are dropped at export."""
    out = []
    for off in range(0, len(buf) - RECSZ + 1, RECSZ):
        rec = RECORD.unpack_from(buf, off)
        if rec[0]:
            out.append(rec)
    out.sort(key=lambda r: r[0])
    return out


# --------------------------------------------------------------------- #
# module singleton + hot-path emit
# --------------------------------------------------------------------- #

_rec: Optional[FlightRecorder] = None
_resolved = False
_proc_name = ""

#: cheap module-level monotonic counters maintained by instrumented
#: subsystems (channel endpoints open/close feed the state.summary()
#: active-channel estimate); ints only, mutated under the GIL
counters: dict[str, int] = {"chan_open": 0, "chan_closed": 0}


def _noop(code, a0=0, a1=0, a2=0, a3=0):
    return None


def _make_evt(rec: FlightRecorder):
    # everything the hot path touches lives in closure cells: no
    # attribute lookups, no globals beyond the two clock/tid callables
    pack = RECORD.pack_into
    buf = rec.buf
    mask = rec.mask
    nxt = rec.ctr.__next__
    mono = time.monotonic_ns
    tid = threading.get_ident

    def evt(code, a0=0, a1=0, a2=0, a3=0):
        off = (nxt() & mask) * RECSZ
        try:
            pack(buf, off, mono(), code, tid() & 0xFFFF, a0, a1, a2, a3)
        except (struct.error, OverflowError, TypeError):
            # bad args drop the record, never raise; pack_into may have
            # torn a partial record into the slot — zero its timestamp
            # so decode() reads the slot as empty
            buf[off:off + 8] = _ZERO8
            rec.bad += 1

    return evt


def _ensure() -> Optional[FlightRecorder]:
    global _rec, _resolved, evt, _proc_name
    if _resolved:
        return _rec
    _resolved = True
    if not _proc_name:
        _proc_name = f"pid-{os.getpid()}"
    from .config import cfg
    if cfg.flight_recorder:
        _rec = FlightRecorder(cfg.flight_ring_slots)
        evt = _make_evt(_rec)
    else:
        evt = _noop
    return _rec


def _evt_unresolved(code, a0=0, a1=0, a2=0, a3=0):
    if _ensure() is not None:
        evt(code, a0, a1, a2, a3)


#: THE emit function. Call as ``flight.evt(CODE, a0, a1)`` — module
#: attribute lookup keeps the binding current across enable/disable.
evt = _evt_unresolved


def enabled() -> bool:
    return _ensure() is not None


def set_enabled(flag: bool) -> None:
    """Runtime toggle (tests, the overhead A/B). Enabling after a
    disable starts a fresh ring."""
    global _rec, _resolved, evt
    from .config import cfg
    cfg.override(flight_recorder=bool(flag))
    _resolved = False
    _rec = None
    evt = _evt_unresolved
    _ensure()


def install_for_test(slots: int) -> FlightRecorder:
    """Swap in a fresh recorder with a custom ring size (tests)."""
    global _rec, _resolved, evt
    _resolved = True
    _rec = FlightRecorder(slots)
    evt = _make_evt(_rec)
    return _rec


def set_proc_name(name: str) -> None:
    global _proc_name
    _proc_name = name


def proc_name() -> str:
    return _proc_name or f"pid-{os.getpid()}"


def chan_opened(n: int = 1) -> None:
    counters["chan_open"] += n


def chan_closed(n: int = 1) -> None:
    counters["chan_closed"] += n


def snapshot(stats_only: bool = False) -> Optional[dict]:
    """This process's ring + stats (None when the recorder is off)."""
    r = _ensure()
    if r is None:
        return None
    return r.snapshot(stats_only)


def pull_reply(msg: dict) -> dict:
    """The ``flight_ring`` answer to a ``flight_pull`` frame — the one
    place the protocol-v5 reply payload is built (worker loop and
    driver conn loop both send exactly this)."""
    return {"t": "flight_ring", "nonce": msg["nonce"],
            "snap": snapshot(msg.get("stats_only", False)) or stats()}


def stats() -> dict:
    """Recorder health for state.summary(): recorded/dropped/bad plus
    the channel-endpoint counters. Works (all zeros) when disabled."""
    r = _ensure()
    base = {"proc": proc_name(), "pid": os.getpid(),
            "enabled": r is not None, "recorded": 0, "dropped": 0,
            "bad": 0, "ring_slots": 0,
            "mono_ns": time.monotonic_ns(), "wall_ns": time.time_ns()}
    if r is not None:
        n = r.count()
        base.update(recorded=n, dropped=max(0, n - r.cap), bad=r.bad,
                    ring_slots=r.cap)
    base["counters"] = dict(counters)
    return base


# --------------------------------------------------------------------- #
# chrome-trace / Perfetto export
# --------------------------------------------------------------------- #

def export_chrome(snaps: list[dict], since_ns: int = 0) -> dict:
    """Stitch per-process snapshots into one Chrome-trace object.

    Each snapshot may carry ``offset_ns`` (remote monotonic minus head
    monotonic, estimated by flight_collect through the wall-clock
    bridge — (their mono − their wall) − (our mono − our wall), NOT the
    pull round-trip midpoint, which transport queueing would skew);
    exported timestamps are remote_ts - offset, i.e. head-clock
    microseconds. Channel seal/wake records additionally emit chrome
    flow events (``ph: s/f``) keyed on (chan48, seq) so Perfetto draws
    the producer->consumer arrow for every message — the per-token
    seal->wake edge on a decode stream."""
    events: list[dict] = []
    for snap in snaps:
        if snap is None or "buf" not in snap:
            continue
        pid = snap["pid"]
        off = int(snap.get("offset_ns", 0))
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": snap.get("proc") or f"pid-{pid}"}})
        for ts, code, tid, a0, a1, a2, a3 in decode(snap["buf"]):
            meta = CODES.get(code)
            if meta is None:
                continue   # torn/unknown record
            if ts - off < since_ns:
                continue   # head-clock cutoff (bench --trace windows)
            name, cat, ph, flow, argnames = meta
            us = (ts - off) / 1000.0
            args = {}
            for k, v in zip(argnames, (a0, a1, a2, a3)):
                args[k] = v
            if code == TASK_STATE:
                args["state"] = _TASK_STATE_NAMES.get(args.get("state"),
                                                      args.get("state"))
            ev = {"name": name, "cat": cat, "ph": ph, "pid": pid,
                  "tid": tid, "ts": us, "args": args}
            if ph == "i":
                ev["s"] = "t"
            events.append(ev)
            if flow is not None:
                fid = ((a0 & 0xFFFFFFFF) << 32) | (a1 & 0xFFFFFFFF)
                fev = {"name": "chan", "cat": "flow", "ph": flow,
                       "pid": pid, "tid": tid, "ts": us, "id": fid}
                if flow == "f":
                    fev["bp"] = "e"
                events.append(fev)
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def capture_report(rt, since_ns: int, out_path: str) -> dict:
    """bench --trace helper: collect+export the cluster flight trace
    since ``since_ns`` (head monotonic), write it to ``out_path``, and
    return the wait/dispatch breakdown for the printed report. With no
    runtime (cluster-less benches driving an engine in-process), exports
    this process's ring alone."""
    import json
    if rt is not None:
        trace = rt.flight_timeline(since_ns=since_ns)
    else:
        snap = snapshot()
        snaps = [dict(snap, offset_ns=0)] if snap else []
        trace = export_chrome(snaps, since_ns=since_ns)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return breakdown(trace)


def breakdown(trace: dict) -> dict:
    """Wait/dispatch summary of an exported trace (the bench --trace
    report): per-category time spent parked in store waits / credit
    waits, counts of control flushes, channel messages and dispatches.
    B/E pairs are matched per (pid, tid, name); unmatched ends (ring
    truncation) are ignored."""
    waits = {"store_wait": 0.0, "chan_credit": 0.0}
    counts = {"ctrl_flush": 0, "chan_seal": 0, "chan_wake": 0,
              "serve_dispatch": 0, "task_state": 0, "sched_pass": 0}
    open_b: dict[tuple, float] = {}
    for ev in trace.get("traceEvents", []):
        name, ph = ev.get("name"), ev.get("ph")
        if name in counts and ph in ("i", "B"):
            counts[name] += 1
        if name not in waits:
            continue
        key = (ev.get("pid"), ev.get("tid"), name)
        if ph == "B":
            open_b[key] = ev["ts"]
        elif ph == "E":
            t0 = open_b.pop(key, None)
            if t0 is not None:
                waits[name] += max(0.0, ev["ts"] - t0)
    return {
        "wait_s": {k: v / 1e6 for k, v in waits.items()},
        "counts": counts,
        "events": sum(1 for e in trace.get("traceEvents", [])
                      if e.get("ph") != "M"),
    }
