"""GCS metadata persistence: a namespaced KV store + head-state snapshots.

Reference parity: the GCS storage backends (gcs/store_client/
redis_store_client.h, in_memory_store_client.h) and the internal KV
surface (gcs_kv_manager.h, ray.experimental.internal_kv). The reference
persists GCS tables to Redis so a restarted GCS can serve a live cluster;
here the head IS the driver, so the recovery unit is a NEW head process
resuming durable state from the previous session: named actors are
re-created from their specs, placement groups re-reserved, and the job
table carried over (running jobs marked failed — their drivers died with
the old head).

sqlite (WAL mode) replaces Redis: single-host durability without a
server, and the file rides the session dir.
"""
from __future__ import annotations

import os
import pickle
import sqlite3
import threading
import time

from .protocol import SNAPSHOT_SCHEMA_VERSION


class GcsStore:
    """Namespaced KV over sqlite. Thread-safe; every op commits."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._lock = threading.Lock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv ("
            "ns TEXT NOT NULL, k TEXT NOT NULL, v BLOB NOT NULL, "
            "PRIMARY KEY (ns, k))")
        self._db.commit()

    def put(self, ns: str, key: str, value: bytes) -> None:
        with self._lock:
            self._db.execute(
                "INSERT INTO kv (ns, k, v) VALUES (?, ?, ?) "
                "ON CONFLICT (ns, k) DO UPDATE SET v = excluded.v",
                (ns, key, value))
            self._db.commit()

    def get(self, ns: str, key: str) -> bytes | None:
        with self._lock:
            row = self._db.execute(
                "SELECT v FROM kv WHERE ns = ? AND k = ?",
                (ns, key)).fetchone()
        return None if row is None else row[0]

    def delete(self, ns: str, key: str) -> bool:
        with self._lock:
            cur = self._db.execute(
                "DELETE FROM kv WHERE ns = ? AND k = ?", (ns, key))
            self._db.commit()
            return cur.rowcount > 0

    def keys(self, ns: str) -> list[str]:
        with self._lock:
            rows = self._db.execute(
                "SELECT k FROM kv WHERE ns = ?", (ns,)).fetchall()
        return [r[0] for r in rows]

    def items(self, ns: str) -> list[tuple[str, bytes]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT k, v FROM kv WHERE ns = ?", (ns,)).fetchall()
        return list(rows)

    def close(self):
        with self._lock:
            self._db.close()


# --------------------------------------------------------------------- #
# head-state snapshot / restore
# --------------------------------------------------------------------- #

def snapshot(rt) -> None:
    """Persist restorable head state (called by the snapshot loop)."""
    kv = rt.kv
    with rt.lock:
        named = []
        for name, aid in rt.named_actors.items():
            a = rt.actors.get(aid)
            if a is None or a.state == "dead":
                continue
            blob = rt.func_registry.get(a.spec.class_id)
            if blob is None:
                continue
            named.append((name, a.spec, blob))
        pgs = [(pg.pg_id, [dict(b.resources) for b in pg.bundles],
                pg.strategy, pg.name, pg.same_label,
                list(pg.bundle_selectors))
               for pg in rt.pgs.values() if pg.state != "removed"]
    jobs = rt.jobs.list()
    kv.put("snapshot", "named_actors", pickle.dumps(named))
    kv.put("snapshot", "placement_groups", pickle.dumps(pgs))
    kv.put("snapshot", "jobs", pickle.dumps(jobs))
    kv.put("snapshot", "meta", pickle.dumps(
        {"ts": time.time(), "session_dir": rt.session_dir,
         "schema_version": SNAPSHOT_SCHEMA_VERSION}))


def restore(rt, old_session_dir: str) -> dict:
    """Resume durable state from a previous session's gcs.sqlite into the
    (fresh) runtime `rt`. Returns a summary of what was restored."""
    path = os.path.join(old_session_dir, "gcs.sqlite")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no GCS snapshot at {path}")
    old = GcsStore(path)
    try:
        meta_blob = old.get("snapshot", "meta")
        if meta_blob is not None:
            sv = pickle.loads(meta_blob).get("schema_version", 0)
            if sv > SNAPSHOT_SCHEMA_VERSION:
                raise RuntimeError(
                    f"GCS snapshot at {path} has schema version {sv}, "
                    f"this build reads <= {SNAPSHOT_SCHEMA_VERSION}; "
                    f"resume with a build at least as new as the one "
                    f"that wrote it")
        named = pickle.loads(old.get("snapshot", "named_actors") or b"\x80\x04]\x94.")
        pgs = pickle.loads(old.get("snapshot", "placement_groups") or b"\x80\x04]\x94.")
        jobs = pickle.loads(old.get("snapshot", "jobs") or b"\x80\x04]\x94.")
        user_kv = old.items("user")  # durable internal KV carries over
    finally:
        old.close()

    restored = {"actors": 0, "placement_groups": 0, "jobs": 0, "kv_keys": 0}
    for row in pgs:
        # keep the OLD id: restored actor specs reference it. Rows may be
        # 4-tuples (pre-slice-scheduling snapshots) or 6-tuples.
        pg_id, bundles, strategy, name = row[:4]
        same_label = row[4] if len(row) > 4 else None
        selectors = row[5] if len(row) > 5 else None
        rt.create_placement_group(bundles, strategy, name, pg_id=pg_id,
                                  same_label=same_label,
                                  bundle_selectors=selectors)
        restored["placement_groups"] += 1
    import dataclasses
    from .ids import ActorID, ObjectID
    for name, spec, blob in named:
        # v1->v2 migration: pre-namespace snapshots stored unqualified
        # names; qualify into the shared default namespace so
        # get_actor("x") (which qualifies to "default/x") still finds
        # every restored actor (actor.py qualify_actor_name)
        if name and "/" not in name and not name.startswith("rtpu:"):
            name = f"default/{name}"
            spec = dataclasses.replace(spec, named=name)
        rt.register_function(spec.class_id, blob)
        # fresh ids: the old actor process is gone; what survives is the
        # named identity + class + init args (reference: detached actors
        # are re-created by name after GCS failover only if restartable —
        # we always re-create, the stronger contract)
        spec = dataclasses.replace(
            spec, actor_id=ActorID.from_random(),
            ready_oid=ObjectID.from_random())
        rt.create_actor(spec)
        restored["actors"] += 1
    for j in jobs:
        info = rt.jobs.import_record(j)
        if info is not None:
            restored["jobs"] += 1
    for key, value in user_kv:
        rt.kv.put("user", key, value)
        restored["kv_keys"] += 1
    return restored


def start_snapshot_loop(rt, period_s: float) -> threading.Event:
    stop = threading.Event()

    def loop():
        while not stop.wait(period_s):
            try:
                snapshot(rt)
            except Exception:
                pass  # a failed snapshot must never hurt the live cluster

    threading.Thread(target=loop, daemon=True,
                     name="rtpu-gcs-snapshot").start()
    return stop
