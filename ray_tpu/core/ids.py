"""Binary IDs for objects, tasks, actors, nodes and jobs.

Reference parity: src/ray/common/id.h defines Job/Task/Object/Actor/NodeID as
fixed-width binary ids. We use 16 random bytes for everything (no embedded
task-index structure — ownership metadata lives in the driver's object
directory instead, see core/runtime.py).
"""
from __future__ import annotations

import os
import random
import threading

# id generation: a urandom-seeded PRNG per (process, thread). Minting an
# id costs one getrandbits (C-level, no syscall) — ids are minted twice
# per task submit, which is hot in burst submission. The earlier
# prefix+counter scheme was cheaper still but made every id on a thread
# share its leading bytes, colliding everything derived from an id
# PREFIX (session dirs, /dev/shm store names, truncated display ids);
# ids must look random end to end.
_LOCAL = threading.local()

# Fork detection WITHOUT a per-mint getpid(): glibc >= 2.25 makes every
# getpid() a real syscall (~10-20us on virtualized hosts — it dominated
# burst submission). The child-side at-fork hook bumps the epoch instead;
# a mint compares two Python ints. Threads other than the forking one
# don't survive a fork, so their stale thread-locals can never be read.
# Accepted blind spot: a native library calling fork(2) directly bypasses
# Python's at-fork hooks — but a child like that re-entering the
# interpreter is unsupported by CPython generally (thread/lock state),
# and every Python-level fork (os.fork, multiprocessing, pty) runs hooks.
_FORK_EPOCH = [0]
if hasattr(os, "register_at_fork"):
    os.register_at_fork(
        after_in_child=lambda: _FORK_EPOCH.__setitem__(0, _FORK_EPOCH[0] + 1))


def _mint(size: int) -> bytes:
    gen = getattr(_LOCAL, "gen", None)
    if gen is None or gen[1] != _FORK_EPOCH[0]:
        # (re)seed on first use and after fork — a forked worker must
        # not continue its parent's stream
        gen = (random.Random(os.urandom(24)), _FORK_EPOCH[0])
        _LOCAL.gen = gen
    return gen[0].getrandbits(size * 8).to_bytes(size, "little")


class BaseID:
    __slots__ = ("_bytes",)
    SIZE = 16

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(f"{type(self).__name__} must be {self.SIZE} bytes")
        self._bytes = binary

    @classmethod
    def from_random(cls):
        return cls(_mint(cls.SIZE))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:12]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class ObjectID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class NodeID(BaseID):
    pass


class JobID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass
