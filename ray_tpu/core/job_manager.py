"""Job submission: run driver scripts against a live cluster.

Reference parity: dashboard/modules/job/job_manager.py:60 (JobManager
.submit_job), job_supervisor.py:55 (per-job supervisor tailing the driver),
and the job table half of GcsJobManager (gcs_job_manager.h:52).

Design differences, by design: the reference runs a supervisor *actor* per
job whose node placement the scheduler picks; here jobs are head-host
subprocesses supervised by a watcher thread — on a TPU pod the head host
drives and the scheduler places *work*, not drivers (SURVEY.md §7
inversion). The submitted entrypoint connects back as a driver client
(``ray_tpu.init(address="auto")``) through the cluster file the runtime
exports, exactly like a reference job driver dialing its cluster's GCS.
"""
from __future__ import annotations

import os
import shlex
import subprocess
import threading
import time

# terminal states (reference: JobStatus in job/common.py)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobInfo:
    def __init__(self, job_id: str, entrypoint: str, log_path: str,
                 metadata: dict | None = None):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.status = PENDING
        self.message = ""
        self.start_time = time.time()
        self.end_time: float | None = None
        self.log_path = log_path
        self.metadata = metadata or {}
        self.pid: int | None = None

    def to_dict(self) -> dict:
        return {"job_id": self.job_id, "entrypoint": self.entrypoint,
                "status": self.status, "message": self.message,
                "start_time": self.start_time, "end_time": self.end_time,
                "metadata": dict(self.metadata), "pid": self.pid}


class JobManager:
    """Head-side job table + driver-subprocess supervision."""

    def __init__(self, session_dir: str, cluster_file: str):
        self.jobs_dir = os.path.join(session_dir, "jobs")
        os.makedirs(self.jobs_dir, exist_ok=True)
        self.cluster_file = cluster_file
        self.lock = threading.Lock()
        self.jobs: dict[str, JobInfo] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._seq = 0
        # status-change hook (the head wires this to its pubsub "jobs"
        # channel); called outside self.lock
        self.on_status = lambda job_id, status: None

    def submit(self, entrypoint: str, env: dict | None = None,
               working_dir_zip: bytes | None = None,
               metadata: dict | None = None,
               job_id: str | None = None) -> str:
        # reserve the id + table entry under the lock; do filesystem work
        # (zip extraction, process spawn) outside it so concurrent job RPCs
        # aren't stalled behind a large working_dir
        with self.lock:
            if job_id is None:
                self._seq += 1
                job_id = f"job-{self._seq:05d}"
            elif job_id in self.jobs:
                raise ValueError(f"job id {job_id!r} already exists")
            job_dir = os.path.join(self.jobs_dir, job_id)
            log_path = os.path.join(job_dir, "driver.log")
            info = JobInfo(job_id, entrypoint, log_path, metadata)
            self.jobs[job_id] = info
        try:
            os.makedirs(job_dir, exist_ok=True)
            cwd = os.getcwd()
            if working_dir_zip is not None:
                # _safe_extract creates the dir (atomically; it no-ops on
                # an existing one, so don't pre-create it)
                cwd = os.path.join(job_dir, "working_dir")
                _safe_extract(working_dir_zip, cwd)
        except (OSError, ValueError) as e:
            with self.lock:
                info.status = FAILED
                info.message = f"working_dir setup failed: {e}"
                info.end_time = time.time()
            return job_id

        penv = dict(os.environ)
        penv.update(env or {})
        penv["RTPU_ADDRESS"] = self.cluster_file
        penv["RTPU_JOB_ID"] = job_id
        # the framework isn't pip-installed; make `import ray_tpu` work
        # in the driver regardless of its cwd (reference relies on ray
        # being installed in the job's interpreter)
        fw_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        paths = [fw_root]
        if working_dir_zip is not None:
            # the extracted dir is the job's import root, like the
            # reference's working_dir runtime env
            paths.insert(0, cwd)
        if penv.get("PYTHONPATH"):
            paths.append(penv["PYTHONPATH"])
        penv["PYTHONPATH"] = os.pathsep.join(paths)
        log_f = open(log_path, "wb", buffering=0)
        try:
            proc = subprocess.Popen(
                shlex.split(entrypoint), cwd=cwd, env=penv,
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            with self.lock:
                info.status = FAILED
                info.message = f"failed to start: {e}"
                info.end_time = time.time()
            log_f.close()
            return job_id
        log_f.close()  # the child holds its own fd now
        with self.lock:
            if info.status == STOPPED:  # stop() raced the spawn
                stopped = True
            else:
                stopped = False
                info.status = RUNNING
                info.pid = proc.pid
                self._procs[job_id] = proc
        if stopped:
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                pass
            return job_id
        self.on_status(job_id, RUNNING)
        threading.Thread(target=self._watch, args=(job_id, proc),
                         daemon=True, name=f"rtpu-job-{job_id}").start()
        return job_id

    def _watch(self, job_id: str, proc: subprocess.Popen):
        rc = proc.wait()
        with self.lock:
            info = self.jobs.get(job_id)
            self._procs.pop(job_id, None)
            if info is None or info.status == STOPPED:
                return
            info.end_time = time.time()
            if rc == 0:
                info.status = SUCCEEDED
            else:
                info.status = FAILED
                info.message = f"driver exited with code {rc}"
            status = info.status
        self.on_status(job_id, status)

    def stop(self, job_id: str) -> bool:
        with self.lock:
            info = self.jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            proc = self._procs.get(job_id)
            if proc is None:
                return False
            info.status = STOPPED
            info.message = "stopped by user"
            info.end_time = time.time()
        self.on_status(job_id, STOPPED)
        try:
            # the whole session group: the driver may have forked
            os.killpg(os.getpgid(proc.pid), 15)
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def status(self, job_id: str) -> dict:
        with self.lock:
            info = self.jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            return info.to_dict()

    def list(self) -> list[dict]:
        with self.lock:
            return [j.to_dict() for j in self.jobs.values()]

    def logs(self, job_id: str, tail_bytes: int = 1 << 20,
             offset: int | None = None) -> str:
        """Driver log: last ``tail_bytes``, or — when ``offset`` is given —
        everything from that byte onward (cursor-based streaming for
        `job logs --follow`, unbounded by the tail window)."""
        with self.lock:
            info = self.jobs.get(job_id)
            if info is None:
                raise ValueError(f"no job {job_id!r}")
            path = info.log_path
        try:
            with open(path, "rb") as f:
                if offset is not None:
                    f.seek(max(0, offset))
                else:
                    f.seek(0, os.SEEK_END)
                    f.seek(max(0, f.tell() - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def import_record(self, rec: dict) -> JobInfo | None:
        """Adopt a job row from a previous session's snapshot (gcs_store
        restore). RUNNING/PENDING become FAILED — their driver processes
        died with the old head."""
        with self.lock:
            job_id = rec.get("job_id")
            if not job_id or job_id in self.jobs:
                return None
            info = JobInfo(job_id, rec.get("entrypoint", ""),
                           rec.get("log_path", ""), rec.get("metadata"))
            info.status = rec.get("status", FAILED)
            info.message = rec.get("message", "")
            info.start_time = rec.get("start_time", 0.0)
            info.end_time = rec.get("end_time")
            if info.status in (PENDING, RUNNING):
                info.status = FAILED
                info.message = "head restarted while job was running"
                info.end_time = info.end_time or time.time()
            self.jobs[job_id] = info
            # keep new ids past imported ones
            try:
                n = int(job_id.rsplit("-", 1)[1])
                self._seq = max(self._seq, n)
            except (IndexError, ValueError):
                pass
            return info

    def shutdown(self):
        with self.lock:
            procs = dict(self._procs)
        for job_id, proc in procs.items():
            try:
                os.killpg(os.getpgid(proc.pid), 15)
            except (ProcessLookupError, PermissionError):
                pass


def pack_working_dir(path: str) -> bytes:
    """Zip a directory for submission (reference: working_dir upload to the
    GCS KV store, _private/runtime_env/working_dir.py). One packer serves
    jobs and runtime envs — see runtime_env._zip_path."""
    from .runtime_env import _zip_path
    return _zip_path(path)


def _safe_extract(zip_bytes: bytes, dest: str) -> None:
    """Extract with zip-slip protection (shared impl:
    runtime_env._extract)."""
    from .runtime_env import _extract
    _extract(zip_bytes, dest)
