"""Host memory monitor + OOM worker-killing policy.

Reference parity: the raylet memory monitor (common/memory_monitor.h:52 —
polls cgroup/system usage, fires a callback over threshold) and its
worker-killing policies (raylet/worker_killing_policy.h: prefer
RETRIABLE tasks, newest first, so the task most likely to succeed later
dies instead of long-running work).

When host usage crosses ``cfg.memory_usage_threshold`` the monitor kills
one victim per tick: the most-recently-dispatched busy worker whose task
has retries left (it will be re-queued by the normal worker-crash path);
if none is retriable, the newest busy worker dies anyway — trading one
task failure for host survival (the reference does the same, annotating
the error as an OOM kill).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional


def system_memory_usage() -> float:
    """Fraction of host memory in use, from /proc/meminfo (no psutil in
    the image). MemAvailable is the kernel's own reclaimable estimate."""
    total = avail = None
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1])
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1])
            if total is not None and avail is not None:
                break
    if not total or avail is None:
        return 0.0
    return 1.0 - avail / total


def pick_victim(workers: list) -> Optional[object]:
    """Worker-killing policy over WorkerInfo-shaped objects (state,
    current TaskSpec with retries_left, dispatch order by .current
    started implicit in list order): retriable-newest first, else
    newest busy."""
    busy = [w for w in workers
            if w.state == "busy" and w.current is not None]
    if not busy:
        return None
    retriable = [w for w in busy if w.current.retries_left > 0]
    pool = retriable or busy
    return pool[-1]  # newest dispatch (callers pass dispatch-ordered)


class MemoryMonitor:
    def __init__(self, runtime, threshold: Optional[float] = None,
                 period_s: Optional[float] = None,
                 usage_fn: Callable[[], float] = system_memory_usage):
        from .config import cfg
        self.rt = runtime
        self.threshold = (cfg.memory_usage_threshold
                          if threshold is None else threshold)
        self.period_s = (cfg.memory_monitor_refresh_ms / 1000.0
                         if period_s is None else period_s)
        self.usage_fn = usage_fn
        self.kills = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MemoryMonitor":
        if self.period_s > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="rtpu-memmon")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.tick()
            except Exception:
                pass  # monitor outlives a bad poll (/proc races)

    def tick(self) -> bool:
        """One check; returns True if a worker was killed."""
        usage = self.usage_fn()
        if usage < self.threshold:
            return False
        rt = self.rt
        with rt.lock:
            # dispatch order ≈ insertion order of the workers dict.
            # Victims come from THIS host only — the monitor reads head-
            # host /proc/meminfo, and killing a remote agent's worker
            # would not relieve it (per-node monitoring is the node
            # agent's job on a multi-host cluster)
            head_nid = rt.head_node.node_id
            victim = pick_victim([w for w in rt.workers.values()
                                  if w.node_id == head_nid])
            if victim is None:
                return False
            name = victim.current.name if victim.current else "?"
            wid = victim.wid
        self.kills += 1
        rt.pubsub.publish("oom", {
            "worker": wid, "task": name, "usage": round(usage, 4)})
        rt.events.append({"name": f"oom_kill:{name}", "cat": "oom",
                          "ph": "i", "pid": wid, "ts": time.time() * 1e6})
        try:
            victim.proc.kill()  # worker-crash path retries/report
        except Exception:
            return False
        return True
