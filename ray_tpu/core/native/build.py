"""Lazy build of the native object store shared library.

The reference ships prebuilt bazel binaries (src/ray/object_manager/plasma);
here we compile on first import and cache next to the source. g++ is in the
image; the build takes <2s.

Sanitizer mode (the reference runs its C++ store tests under ASan/TSan in
CI): set ``RTPU_OBJSTORE_SANITIZE=address,undefined`` (any comma-joined
``-fsanitize=`` list) and every process that builds/loads the store in that
environment gets a ``libobjstore.<mode>.so`` debug build (-O1 -g, frame
pointers) instead of the production one. The sanitized variant caches
under its own name + source-hash file, so flipping the env never clobbers
the production binary. Loading an ASan build into a non-instrumented
python requires LD_PRELOADing libasan/libubsan — tests/test_sanitizers.py
shows the full recipe.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "objstore.cc")
_lock = threading.Lock()


def _san_mode() -> str:
    """Normalized sanitizer list from the env ('' = production build)."""
    mode = os.environ.get("RTPU_OBJSTORE_SANITIZE", "").strip()
    return ",".join(s.strip() for s in mode.split(",") if s.strip())


def _lib_path(mode: str) -> str:
    if not mode:
        return os.path.join(_DIR, "libobjstore.so")
    tag = mode.replace(",", "-")
    return os.path.join(_DIR, f"libobjstore.{tag}.so")


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _compile_and_swap(mode: str) -> None:
    """Compile to a tmp path and atomically replace the .so + hash.
    Caller holds _lock. Raises CalledProcessError on compile errors and
    OSError when the compiler is missing / checkout is read-only."""
    lib = _lib_path(mode)
    tmp = lib + ".tmp"
    if mode:
        # debug-grade opt level + frame pointers: sanitizer reports with
        # usable stacks beat a fast binary nobody profiles
        flags = [f"-fsanitize={mode}", "-O1", "-g",
                 "-fno-omit-frame-pointer"]
    else:
        flags = ["-O2", "-g"]
    subprocess.run(
        ["g++", *flags, "-shared", "-fPIC", "-std=c++17",
         "-o", tmp, _SRC, "-lpthread"],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, lib)
    with open(lib + ".srchash", "w") as f:
        f.write(_src_hash())


def ensure_built() -> str:
    """Compile objstore.cc -> libobjstore[.<san>].so if missing or stale.

    Staleness is a CONTENT hash of the source, not mtimes: a fresh git
    checkout gives every file the same mtime, which let a committed .so
    shadow newer committed source (missing-symbol crashes at import).
    """
    mode = _san_mode()
    lib = _lib_path(mode)
    with _lock:
        want = _src_hash()
        have = None
        if os.path.exists(lib) and os.path.exists(lib + ".srchash"):
            try:
                with open(lib + ".srchash") as f:
                    have = f.read().strip()
            except OSError:
                pass
        if have != want:
            try:
                _compile_and_swap(mode)
            except subprocess.CalledProcessError as e:
                # a real compile error must surface (silently loading the
                # stale .so is the failure mode this hash scheme prevents)
                raise RuntimeError(
                    "objstore.cc failed to compile:\n"
                    + e.stderr.decode(errors="replace")) from e
            except OSError:
                # no compiler / read-only checkout: a shipped .so is still
                # usable (it may just predate the latest source). Only the
                # production variant ships — a sanitizer build with no
                # compiler has nothing to fall back to.
                if mode or not os.path.exists(lib):
                    raise
    return lib


def rebuild() -> str:
    """Recompile for THIS host and swap in the result. Used when a
    shipped binary fails to LOAD (e.g. built against a newer glibc than
    this host) — the content hash can't catch that, only dlopen can.
    The existing .so is replaced only AFTER a successful compile: a
    compiler-less host, or a checkout shared over NFS with hosts where
    the shipped binary loads fine, must never lose it to a failed
    attempt."""
    mode = _san_mode()
    with _lock:
        try:
            _compile_and_swap(mode)
        except (subprocess.CalledProcessError, OSError) as e:
            stderr = getattr(e, "stderr", None) or b""
            raise RuntimeError(
                "libobjstore.so failed to load and recompiling for this "
                "host failed:\n" + stderr.decode(errors="replace")) from e
    return _lib_path(mode)
