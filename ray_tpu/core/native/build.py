"""Lazy build of the native object store shared library.

The reference ships prebuilt bazel binaries (src/ray/object_manager/plasma);
here we compile on first import and cache next to the source. g++ is in the
image; the build takes <2s.
"""
from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "objstore.cc")
_LIB = os.path.join(_DIR, "libobjstore.so")
_lock = threading.Lock()


def ensure_built() -> str:
    """Compile objstore.cc -> libobjstore.so if missing or stale."""
    with _lock:
        if (
            not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)
        ):
            tmp = _LIB + ".tmp"
            subprocess.run(
                [
                    "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
                    "-o", tmp, _SRC, "-lpthread",
                ],
                check=True,
                capture_output=True,
            )
            os.replace(tmp, _LIB)
    return _LIB
