"""Lazy build of the native object store shared library.

The reference ships prebuilt bazel binaries (src/ray/object_manager/plasma);
here we compile on first import and cache next to the source. g++ is in the
image; the build takes <2s.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "objstore.cc")
_LIB = os.path.join(_DIR, "libobjstore.so")
_HASH = _LIB + ".srchash"
_lock = threading.Lock()


def _src_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()


def _compile_and_swap() -> None:
    """Compile to a tmp path and atomically replace the .so + hash.
    Caller holds _lock. Raises CalledProcessError on compile errors and
    OSError when the compiler is missing / checkout is read-only."""
    tmp = _LIB + ".tmp"
    subprocess.run(
        [
            "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, _SRC, "-lpthread",
        ],
        check=True,
        capture_output=True,
    )
    os.replace(tmp, _LIB)
    with open(_HASH, "w") as f:
        f.write(_src_hash())


def ensure_built() -> str:
    """Compile objstore.cc -> libobjstore.so if missing or stale.

    Staleness is a CONTENT hash of the source, not mtimes: a fresh git
    checkout gives every file the same mtime, which let a committed .so
    shadow newer committed source (missing-symbol crashes at import).
    """
    with _lock:
        want = _src_hash()
        have = None
        if os.path.exists(_LIB) and os.path.exists(_HASH):
            try:
                with open(_HASH) as f:
                    have = f.read().strip()
            except OSError:
                pass
        if have != want:
            try:
                _compile_and_swap()
            except subprocess.CalledProcessError as e:
                # a real compile error must surface (silently loading the
                # stale .so is the failure mode this hash scheme prevents)
                raise RuntimeError(
                    "objstore.cc failed to compile:\n"
                    + e.stderr.decode(errors="replace")) from e
            except OSError:
                # no compiler / read-only checkout: a shipped .so is still
                # usable (it may just predate the latest source)
                if not os.path.exists(_LIB):
                    raise
    return _LIB


def rebuild() -> str:
    """Recompile for THIS host and swap in the result. Used when a
    shipped binary fails to LOAD (e.g. built against a newer glibc than
    this host) — the content hash can't catch that, only dlopen can.
    The existing .so is replaced only AFTER a successful compile: a
    compiler-less host, or a checkout shared over NFS with hosts where
    the shipped binary loads fine, must never lose it to a failed
    attempt."""
    with _lock:
        try:
            _compile_and_swap()
        except (subprocess.CalledProcessError, OSError) as e:
            stderr = getattr(e, "stderr", None) or b""
            raise RuntimeError(
                "libobjstore.so failed to load and recompiling for this "
                "host failed:\n" + stderr.decode(errors="replace")) from e
    return _LIB
