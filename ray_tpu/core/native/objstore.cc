// objstore.cc — shared-memory immutable object store (plasma-equivalent).
//
// TPU-native re-design of the reference's per-node object store
// (reference: src/ray/object_manager/plasma/store.h:55, object_store.cc,
// eviction_policy.h). Unlike plasma's socket-server architecture (clients talk
// to the store over a unix socket with fd-passing, plasma/client.h), this store
// is a *single file-backed mmap region shared by all processes on the node*,
// with a process-shared robust mutex in the header. Rationale: on a
// TPU host the heavy data plane (gradients/activations) lives inside XLA
// programs on-device; the host object store serves control payloads, dataset
// blocks and checkpoints, so a lock-based shm design is simpler and has lower
// latency than a socket protocol (no round trip, no fd passing).
//
// Blocking get does NOT use a pthread condvar: process-shared condvars are
// not robust — a client SIGKILLed inside pthread_cond_(timed)wait leaves its
// group reference behind, and the next pthread_cond_broadcast blocks forever
// in the group-switch quiesce (observed as a cluster-wide wedge with the
// broadcaster holding the store mutex). Instead waiters block on a raw
// futex over a seal-sequence counter: seal/delete bump the counter and
// FUTEX_WAKE; the kernel keeps no per-waiter state, so a killed waiter
// simply disappears.
//
// Crash robustness (workers are SIGKILLed by design — ray.kill parity):
//   - robust mutex: owner death => EOWNERDEAD recovery on next lock
//   - futex wait:   waiter death => nothing to clean up
//   - pins:         per-pid pin slots; os_reclaim_pid(pid) drops pins and
//                   aborts unsealed creates of a dead worker
//   - free list:    walks are cycle-bounded so a torn list can never spin
//                   forever while holding the mutex
//
// Features (parity targets):
//   - create/seal/get/contains/delete/acquire/release  (plasma client.h ops)
//   - blocking Get with timeout via futex               (plasma store.h:55 wait)
//   - LRU eviction of sealed, unreferenced objects      (eviction_policy.h)
//   - first-fit free-list allocator with coalescing     (dlmalloc.cc stand-in)
//   - robust-mutex crash recovery (owner dies holding lock)
//
// Exposed as a C ABI consumed from Python via ctypes (no pybind11 in image).

#include <atomic>
#include <cerrno>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <linux/futex.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7452617954505531ULL;  // "tRayTPU1"
constexpr uint32_t kIdSize = 16;

enum ObjState : int32_t {
  kFree = 0,      // entry slot never used (probe chains END here)
  kCreated = 1,   // allocated, writer filling
  kSealed = 2,    // immutable, readable
  kTomb = 3,      // deleted; keeps probe chains intact (swept by rehash)
};

// Per-pid pin bookkeeping so pins leaked by a SIGKILLed process can be
// reclaimed (os_reclaim_pid). Pins from more than kPinSlots distinct pids
// overflow into an aggregate count that cannot be reclaimed (rare; pins are
// short-lived).
constexpr int kPinSlots = 4;
struct PinSlot {
  int32_t pid;
  int32_t count;
};

struct ObjEntry {
  uint8_t id[kIdSize];
  uint64_t offset;   // payload offset from region base
  uint64_t size;
  int32_t state;
  int32_t refcnt;    // pins against eviction (incl. creator pin pre-seal)
  uint64_t lru_tick;
  int32_t creator_pid;   // pid that os_create'd (abortable while kCreated)
  int32_t overflow_pins;
  PinSlot pins[kPinSlots];
};

// Free block header, stored inside the heap region itself.
struct FreeBlock {
  uint64_t size;        // total block size incl. nothing (just span)
  uint64_t next;        // offset of next free block, 0 = end
};

struct Header {
  uint64_t magic;
  uint64_t capacity;        // total file size
  uint64_t heap_off;        // where the allocatable heap begins
  uint64_t heap_size;
  uint32_t max_entries;
  uint32_t pad0;
  pthread_mutex_t mutex;
  uint32_t seal_seq;        // bumped on every seal/delete; futex wait target
  uint32_t n_waiters;       // processes blocked in futex_wait on seal_seq
  uint64_t lru_counter;
  uint64_t free_head;       // offset of first free block (0 = none)
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t evictions;       // stat: count of evicted objects
  uint64_t num_tombs;       // tombstoned entry slots awaiting rehash
  // ObjEntry table follows, then heap.
};

struct Handle {
  int fd;
  uint8_t* base;
  Header* hdr;
  ObjEntry* entries;
  // getpid() cached at create/attach: glibc >= 2.25 makes every getpid() a
  // real syscall, and pin bookkeeping calls it on the get/release hot path
  // (measured ~13us per syscall on virtualized hosts — more than the whole
  // rest of os_get). One Handle per process: attach after fork, never share
  // a handle across fork, or pin accounting keys on the wrong pid.
  int32_t pid;
};

inline ObjEntry* entry_table(Header* h) {
  return reinterpret_cast<ObjEntry*>(reinterpret_cast<uint8_t*>(h) + sizeof(Header));
}

inline uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

void lock(Handle* h) {
  int rc = pthread_mutex_lock(&h->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // A process died holding the lock; state may be torn but entries are
    // updated with care (state flag written last on create), so recover.
    pthread_mutex_consistent(&h->hdr->mutex);
  }
}

void unlock(Handle* h) { pthread_mutex_unlock(&h->hdr->mutex); }

// Raw futex wait/wake on the seal-sequence word (process-shared: no
// FUTEX_PRIVATE flag). FUTEX_WAIT_BITSET takes an *absolute* CLOCK_MONOTONIC
// deadline, matching the deadline os_get already computes.
int futex_wait_abs(uint32_t* addr, uint32_t expected,
                   const struct timespec* deadline) {
  return (int)syscall(SYS_futex, addr, FUTEX_WAIT_BITSET, expected, deadline,
                      nullptr, FUTEX_BITSET_MATCH_ANY);
}

// Absolute CLOCK_MONOTONIC deadline `timeout_ms` from now (the one
// deadline computation every blocking wait entry point shares).
struct timespec abs_deadline(int64_t timeout_ms) {
  struct timespec d;
  clock_gettime(CLOCK_MONOTONIC, &d);
  d.tv_sec += timeout_ms / 1000;
  d.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (d.tv_nsec >= 1000000000L) { d.tv_sec++; d.tv_nsec -= 1000000000L; }
  return d;
}

void futex_wake_all(uint32_t* addr) {
  syscall(SYS_futex, addr, FUTEX_WAKE, INT_MAX, nullptr, nullptr, 0);
}

// Seal/delete notification. The seq bump is unconditional (waiters key
// their re-check on it), but the FUTEX_WAKE syscall is elided when no one
// is registered in n_waiters — on hosts with slow syscalls the wake was
// costing every uncontended seal ~10-20us. Ordering: a waiter increments
// n_waiters (seq_cst) BEFORE loading seal_seq for its futex_wait, so any
// seal that the waiter's load missed must observe n_waiters > 0 and wake.
// A waiter SIGKILLed inside futex_wait leaks its count, which only makes
// wakes unconditional again (never lost) — saturating, self-limiting.
void bump_seal_seq(Handle* h) {
  __atomic_fetch_add(&h->hdr->seal_seq, 1, __ATOMIC_SEQ_CST);
  if (__atomic_load_n(&h->hdr->n_waiters, __ATOMIC_SEQ_CST) != 0)
    futex_wake_all(&h->hdr->seal_seq);
}

// Register/deregister around a futex_wait on seal_seq.
inline void waiter_enter(Handle* h) {
  __atomic_fetch_add(&h->hdr->n_waiters, 1, __ATOMIC_SEQ_CST);
}

inline void waiter_exit(Handle* h) {
  // saturating: never go below zero even if a leaked count was clamped
  uint32_t n = __atomic_load_n(&h->hdr->n_waiters, __ATOMIC_SEQ_CST);
  while (n != 0 && !__atomic_compare_exchange_n(
             &h->hdr->n_waiters, &n, n - 1, false, __ATOMIC_SEQ_CST,
             __ATOMIC_SEQ_CST)) {
  }
}

// Per-pid pin bookkeeping. Caller holds the store mutex.
void pin(ObjEntry* e, int32_t pid) {
  e->refcnt++;
  PinSlot* empty = nullptr;
  for (int i = 0; i < kPinSlots; i++) {
    if (e->pins[i].pid == pid) { e->pins[i].count++; return; }
    if (!empty && e->pins[i].pid == 0) empty = &e->pins[i];
  }
  if (empty) { empty->pid = pid; empty->count = 1; return; }
  e->overflow_pins++;
}

void unpin(ObjEntry* e, int32_t pid) {
  if (e->refcnt > 0) e->refcnt--;
  for (int i = 0; i < kPinSlots; i++) {
    if (e->pins[i].pid == pid) {
      if (--e->pins[i].count <= 0) { e->pins[i].pid = 0; e->pins[i].count = 0; }
      return;
    }
  }
  if (e->overflow_pins > 0) e->overflow_pins--;
}

ObjEntry* find(Handle* h, const uint8_t* id) {
  // Linear-probed open addressing over the entry table, hashed by id
  // prefix. Deleted slots become kTomb (NOT kFree) so probe chains stay
  // intact and an absent-key lookup stops at the first never-used slot
  // instead of scanning all max_entries — absent lookups are the common
  // case (every os_create probes its fresh random id) and a full 64k-slot
  // scan cost ~0.4 ms per create before tombstones.
  Header* hdr = h->hdr;
  uint64_t hash;
  memcpy(&hash, id, 8);
  uint32_t n = hdr->max_entries;
  for (uint32_t i = 0; i < n; i++) {
    ObjEntry* e = &h->entries[(hash + i) % n];
    if (e->state == kFree) return nullptr;
    if (e->state != kTomb && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

ObjEntry* find_slot(Handle* h, const uint8_t* id) {
  // Insertion slot: first tombstone on the probe path if any (reuse keeps
  // chains short), else the terminating free slot; nullptr if the id
  // already exists or the table is full of live entries.
  Header* hdr = h->hdr;
  uint64_t hash;
  memcpy(&hash, id, 8);
  uint32_t n = hdr->max_entries;
  ObjEntry* tomb = nullptr;
  for (uint32_t i = 0; i < n; i++) {
    ObjEntry* e = &h->entries[(hash + i) % n];
    if (e->state == kFree) return tomb ? tomb : e;
    if (e->state == kTomb) {
      if (!tomb) tomb = e;
      continue;
    }
    if (memcmp(e->id, id, kIdSize) == 0) return nullptr;  // exists
  }
  return tomb;  // table has no never-used slots left
}

// Tombstone a live entry slot (caller already dealloc'd its payload).
inline void tombstone(Header* hdr, ObjEntry* e) {
  e->state = kTomb;
  hdr->num_objects--;
  hdr->num_tombs++;
}

// Sweep tombstones by rebuilding the table once they pile up (they
// lengthen every probe chain). O(max_entries) but amortized across the
// >= n/4 deletions that accumulated them. Caller holds the lock and must
// not use ObjEntry pointers obtained before the call.
void maybe_rehash(Handle* h) {
  Header* hdr = h->hdr;
  uint32_t n = hdr->max_entries;
  if (hdr->num_tombs < 64 || hdr->num_tombs < n / 4) return;
  ObjEntry* scratch =
      (ObjEntry*)malloc((size_t)hdr->num_objects * sizeof(ObjEntry));
  if (!scratch && hdr->num_objects > 0) return;  // slow beats failing
  uint64_t live = 0;
  for (uint32_t i = 0; i < n; i++) {
    ObjEntry* e = &h->entries[i];
    if (e->state == kCreated || e->state == kSealed) scratch[live++] = *e;
  }
  memset(h->entries, 0, (size_t)n * sizeof(ObjEntry));
  hdr->num_tombs = 0;
  for (uint64_t j = 0; j < live; j++) {
    ObjEntry* slot = find_slot(h, scratch[j].id);
    *slot = scratch[j];  // table was just cleared: slot is never null
  }
  free(scratch);
}

// First-fit allocation from the free list. Each allocated block carries an
// 8-byte span header (the actual block size, including absorbed remainders
// too small to split off) so dealloc always returns the exact span —
// otherwise absorbed tails would leak permanently. Returns the *payload*
// offset (block + 8) or 0 on failure.
// Upper bound on free-list length: every free block is bordered by
// allocated spans, so a healthy list never exceeds max_entries + 1 nodes.
// A torn list (process died mid-surgery under EOWNERDEAD) could contain a
// cycle; bounding the walk turns "deadlock holding the mutex" into a
// recoverable allocation failure.
inline uint64_t walk_limit(Header* hdr) {
  return (uint64_t)hdr->max_entries + 16;
}

uint64_t alloc(Handle* h, uint64_t size) {
  uint64_t want = align8(size) + 8;
  if (want < sizeof(FreeBlock)) want = sizeof(FreeBlock);
  Header* hdr = h->hdr;
  uint64_t prev = 0, cur = hdr->free_head;
  uint64_t steps = walk_limit(hdr);
  while (cur && steps--) {
    FreeBlock* fb = reinterpret_cast<FreeBlock*>(h->base + cur);
    if (fb->size >= want) {
      uint64_t span = want;
      uint64_t remain = fb->size - want;
      if (remain >= sizeof(FreeBlock) + 64) {
        // split: keep tail as free block
        uint64_t tail_off = cur + want;
        FreeBlock* tail = reinterpret_cast<FreeBlock*>(h->base + tail_off);
        tail->size = remain;
        tail->next = fb->next;
        if (prev) reinterpret_cast<FreeBlock*>(h->base + prev)->next = tail_off;
        else hdr->free_head = tail_off;
      } else {
        span = fb->size;  // absorb remainder
        if (prev) reinterpret_cast<FreeBlock*>(h->base + prev)->next = fb->next;
        else hdr->free_head = fb->next;
      }
      hdr->bytes_in_use += span;
      *reinterpret_cast<uint64_t*>(h->base + cur) = span;
      return cur + 8;
    }
    prev = cur;
    cur = fb->next;
  }
  return 0;
}

// Return an allocated block (by payload offset) to the free list, coalescing
// with neighbours (list kept sorted by offset so coalescing is O(1) at the
// insertion point).
void dealloc(Handle* h, uint64_t payload_off) {
  uint64_t off = payload_off - 8;
  uint64_t size = *reinterpret_cast<uint64_t*>(h->base + off);
  Header* hdr = h->hdr;
  uint64_t prev = 0, cur = hdr->free_head;
  uint64_t steps = walk_limit(hdr);
  while (cur && cur < off) {
    if (!steps--) return;  // torn/cyclic list: leak the block, don't spin
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(h->base + cur)->next;
  }
  if (cur == off) return;  // double-free guard: already on the free list
  hdr->bytes_in_use -= size;
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(h->base + off);
  nb->size = size;
  nb->next = cur;
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(h->base + prev);
    pb->next = off;
    if (prev + pb->size == off) {  // coalesce with prev
      pb->size += nb->size;
      pb->next = nb->next;
      nb = pb;
      off = prev;
    }
  } else {
    hdr->free_head = off;
  }
  if (nb->next && off + nb->size == nb->next) {  // coalesce with next
    FreeBlock* xb = reinterpret_cast<FreeBlock*>(h->base + nb->next);
    nb->size += xb->size;
    nb->next = xb->next;
  }
}

// Evict sealed refcnt==0 objects in LRU order until `need` bytes could fit.
// Caller holds lock. Returns true if anything was evicted.
bool evict_lru(Handle* h, uint64_t need) {
  Header* hdr = h->hdr;
  bool any = false;
  while (true) {
    // Check if a block of `need` is plausible: conservative — try alloc.
    uint64_t off = alloc(h, need);
    if (off) { dealloc(h, off); return true; }
    // find LRU evictable
    ObjEntry* victim = nullptr;
    for (uint32_t i = 0; i < hdr->max_entries; i++) {
      ObjEntry* e = &h->entries[i];
      if (e->state == kSealed && e->refcnt == 0) {
        if (!victim || e->lru_tick < victim->lru_tick) victim = e;
      }
    }
    if (!victim) return any;
    dealloc(h, victim->offset);
    tombstone(hdr, victim);
    hdr->evictions++;
    any = true;
  }
}

}  // namespace

extern "C" {

// Create a new store region backing file at `path` with `capacity` bytes and
// room for `max_entries` objects. Returns handle or nullptr.
void* os_store_create(const char* path, uint64_t capacity, uint32_t max_entries) {
  // the metadata (header + entry table) must FIT with heap to spare —
  // otherwise the memsets below scribble past the mapping (segfault)
  uint64_t meta = align8(sizeof(Header))
      + align8((uint64_t)max_entries * sizeof(ObjEntry));
  if (capacity < meta + (64 << 10)) return nullptr;
  int fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)capacity) != 0) { close(fd); return nullptr; }
  uint8_t* base = (uint8_t*)mmap(nullptr, capacity, PROT_READ | PROT_WRITE,
                                 MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Header* hdr = reinterpret_cast<Header*>(base);
  memset(hdr, 0, sizeof(Header));
  hdr->capacity = capacity;
  hdr->max_entries = max_entries;
  uint64_t table_bytes = align8((uint64_t)max_entries * sizeof(ObjEntry));
  hdr->heap_off = align8(sizeof(Header) + table_bytes);
  hdr->heap_size = capacity - hdr->heap_off;
  memset(entry_table(hdr), 0, table_bytes);

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &ma);
  hdr->seal_seq = 0;

  // one big free block spanning the heap
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(base + hdr->heap_off);
  fb->size = hdr->heap_size;
  fb->next = 0;
  hdr->free_head = hdr->heap_off;
  hdr->magic = kMagic;  // written last: attachers spin on this

  Handle* h = new Handle{fd, base, hdr, entry_table(hdr), (int32_t)getpid()};
  return h;
}

void* os_store_attach(const char* path) {
  int fd = open(path, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  uint8_t* base = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE,
                                 MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { close(fd); return nullptr; }
  Header* hdr = reinterpret_cast<Header*>(base);
  if (hdr->magic != kMagic) { munmap(base, st.st_size); close(fd); return nullptr; }
  Handle* h = new Handle{fd, base, hdr, entry_table(hdr), (int32_t)getpid()};
  return h;
}

void os_store_close(void* hv) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  munmap(h->base, h->hdr->capacity);
  close(h->fd);
  delete h;
}

// Refresh the handle's cached pid after a fork: a child inheriting the
// parent's handle must pin under ITS pid, or os_reclaim_pid(parent) would
// strip pins the child still relies on (Python registers this via
// os.register_at_fork, object_store.py).
void os_store_refresh_pid(void* hv) {
  reinterpret_cast<Handle*>(hv)->pid = (int32_t)getpid();
}

// Allocate an object buffer. Returns payload offset (>0), 0 if out of memory
// after eviction, or UINT64_MAX if the id already exists.
uint64_t os_create(void* hv, const uint8_t* id, uint64_t size) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  lock(h);
  if (find(h, id)) { unlock(h); return UINT64_MAX; }
  uint64_t off = alloc(h, size);
  if (!off) {
    evict_lru(h, size);
    off = alloc(h, size);
  }
  if (!off) { unlock(h); return 0; }
  ObjEntry* e = find_slot(h, id);
  if (!e) { dealloc(h, off); unlock(h); return 0; }
  if (e->state == kTomb) h->hdr->num_tombs--;
  memcpy(e->id, id, kIdSize);
  e->offset = off;
  e->size = size;
  e->refcnt = 1;  // creator holds a pin until seal
  e->lru_tick = ++h->hdr->lru_counter;
  e->creator_pid = h->pid;
  e->overflow_pins = 0;
  memset(e->pins, 0, sizeof(e->pins));
  e->state = kCreated;
  h->hdr->num_objects++;
  // churn workloads (eviction-heavy, no explicit deletes) accumulate
  // tombstones here; sweep before they degrade probes
  maybe_rehash(h);
  unlock(h);
  return off;
}

int os_seal(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  lock(h);
  ObjEntry* e = find(h, id);
  if (!e || e->state != kCreated) { unlock(h); return -1; }
  e->state = kSealed;
  e->refcnt -= 1;  // drop creator pin
  e->creator_pid = 0;
  bump_seal_seq(h);
  unlock(h);
  return 0;
}

// Blocking get: waits up to timeout_ms for the object to be sealed.
// On success pins the object (caller must os_release) and fills offset/size.
// Returns 0 ok, -1 timeout, -2 would-block (timeout_ms == 0 and not present).
// Waiting is a raw futex on seal_seq — kill-safe (see file header), and the
// mutex is NEVER held while blocked.
int os_get(void* hv, const uint8_t* id, int64_t timeout_ms,
           uint64_t* offset, uint64_t* size) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  struct timespec deadline = abs_deadline(timeout_ms);
  lock(h);
  while (true) {
    ObjEntry* e = find(h, id);
    if (e && e->state == kSealed) {
      pin(e, h->pid);
      e->lru_tick = ++h->hdr->lru_counter;
      *offset = e->offset;
      *size = e->size;
      unlock(h);
      return 0;
    }
    if (timeout_ms == 0) { unlock(h); return -2; }
    waiter_enter(h);  // BEFORE the seq load — see bump_seal_seq
    uint32_t seq = __atomic_load_n(&h->hdr->seal_seq, __ATOMIC_SEQ_CST);
    unlock(h);
    int rc = futex_wait_abs(&h->hdr->seal_seq, seq, &deadline);
    waiter_exit(h);
    if (rc != 0 && errno == ETIMEDOUT) return -1;
    // 0 (woken), EAGAIN (seq already moved) or EINTR: re-check under lock.
    lock(h);
  }
}

// Stop-aware blocking get — the consumer half of a sealed ring channel
// (ray_tpu/dag/channel.py). Like os_get, but a second `stop_id` aborts
// the wait the INSTANT it seals: one native call both waits for the
// message and watches teardown, so a channel read costs exactly what a
// plain blocking get does (the old transport burned an extra
// os_wait_sealed round-trip per message, measurable under cross-process
// mutex contention). Data wins over a concurrent stop — consumers drain
// what was produced, then observe the close.
// Returns 0 ok (object pinned; caller must os_release), -1 timeout,
// -2 would-block (timeout_ms == 0 and absent), -3 stop sealed and data
// absent.
int os_chan_get(void* hv, const uint8_t* id, const uint8_t* stop_id,
                int64_t timeout_ms, uint64_t* offset, uint64_t* size) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  struct timespec deadline = abs_deadline(timeout_ms);
  lock(h);
  while (true) {
    ObjEntry* e = find(h, id);
    if (e && e->state == kSealed) {
      pin(e, h->pid);
      e->lru_tick = ++h->hdr->lru_counter;
      *offset = e->offset;
      *size = e->size;
      unlock(h);
      return 0;
    }
    ObjEntry* s = find(h, stop_id);
    if (s && s->state == kSealed) { unlock(h); return -3; }
    if (timeout_ms == 0) { unlock(h); return -2; }
    waiter_enter(h);  // BEFORE the seq load — see bump_seal_seq
    uint32_t seq = __atomic_load_n(&h->hdr->seal_seq, __ATOMIC_SEQ_CST);
    unlock(h);
    int rc = futex_wait_abs(&h->hdr->seal_seq, seq, &deadline);
    waiter_exit(h);
    if (rc != 0 && errno == ETIMEDOUT) return -1;
    lock(h);
  }
}

// Multi-object wait: block until at least `min_count` of the `n` ids are
// sealed in the store, or the timeout expires. out[i] is set to 1 once
// id i has been OBSERVED sealed (sticky for the duration of the call —
// a concurrent evict after observation does not unset it; callers that
// then read the object re-enter through os_get and retry on a miss).
// Returns the number of set out[] flags. timeout_ms == 0 is a single
// non-blocking scan. This is the control plane's seal-notification
// primitive: one futex wait services whichever of N results seals first
// (worker-side bulk ray.get / ray.wait), replacing per-ref poll slices.
// Each wake rescans only the not-yet-observed ids, so a call over n ids
// costs O(n) probes per seal event while waiting — fine for the list
// sizes get()/wait() see; callers with huge lists should chunk.
int os_wait_sealed(void* hv, const uint8_t* ids, uint32_t n,
                   uint32_t min_count, int64_t timeout_ms, uint8_t* out) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  struct timespec deadline = abs_deadline(timeout_ms);
  if (min_count > n) min_count = n;
  memset(out, 0, n);
  uint32_t have = 0;
  lock(h);
  while (true) {
    for (uint32_t i = 0; i < n && have < n; i++) {
      if (out[i]) continue;
      ObjEntry* e = find(h, ids + (uint64_t)i * kIdSize);
      if (e && e->state == kSealed) { out[i] = 1; have++; }
    }
    if (have >= min_count || timeout_ms == 0) { unlock(h); return (int)have; }
    waiter_enter(h);
    uint32_t seq = __atomic_load_n(&h->hdr->seal_seq, __ATOMIC_SEQ_CST);
    unlock(h);
    int rc = futex_wait_abs(&h->hdr->seal_seq, seq, &deadline);
    waiter_exit(h);
    if (rc != 0 && errno == ETIMEDOUT) {
      // final rescan: a seal may have slipped between our last scan and
      // the wait (its wake then raced the timeout)
      lock(h);
      for (uint32_t i = 0; i < n; i++) {
        if (out[i]) continue;
        ObjEntry* e = find(h, ids + (uint64_t)i * kIdSize);
        if (e && e->state == kSealed) { out[i] = 1; have++; }
      }
      unlock(h);
      return (int)have;
    }
    lock(h);
  }
}

// Seqlock-style building blocks for chunked multi-waits from Python: read
// the seal sequence, scan in bounded chunks (each a short mutex hold),
// then block until the sequence moves. Any seal/delete wakes the waiter;
// the caller rescans. Lets a partial wait over a huge id list avoid one
// O(n) probe pass under the mutex per seal event.
uint32_t os_seal_seq(void* hv) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  return __atomic_load_n(&h->hdr->seal_seq, __ATOMIC_SEQ_CST);
}

// Block until seal_seq != seq or timeout. 0 = changed, -1 = timeout.
int os_wait_seq(void* hv, uint32_t seq, int64_t timeout_ms) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  struct timespec deadline = abs_deadline(timeout_ms);
  waiter_enter(h);
  while (__atomic_load_n(&h->hdr->seal_seq, __ATOMIC_SEQ_CST) == seq) {
    int rc = futex_wait_abs(&h->hdr->seal_seq, seq, &deadline);
    if (rc != 0 && errno == ETIMEDOUT) { waiter_exit(h); return -1; }
  }
  waiter_exit(h);
  return 0;
}

int os_contains(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  lock(h);
  ObjEntry* e = find(h, id);
  int r = (e && e->state == kSealed) ? 1 : 0;
  unlock(h);
  return r;
}

void os_release(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  lock(h);
  ObjEntry* e = find(h, id);
  if (e) unpin(e, h->pid);
  unlock(h);
}

// Drop all store state owned by a dead process: its unsealed creates are
// aborted and its leaked read pins removed, so objects become evictable
// again. Called by the head when it reaps a worker (reference analog:
// NodeManager worker-death cleanup, raylet/node_manager.h:124). Returns the
// number of entries touched.
int os_reclaim_pid(void* hv, int32_t pid) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  int touched = 0;
  lock(h);
  Header* hdr = h->hdr;
  for (uint32_t i = 0; i < hdr->max_entries; i++) {
    ObjEntry* e = &h->entries[i];
    if (e->state == kCreated && e->creator_pid == pid) {
      dealloc(h, e->offset);
      tombstone(hdr, e);
      touched++;
      continue;
    }
    if (e->state == kSealed) {
      for (int s = 0; s < kPinSlots; s++) {
        if (e->pins[s].pid == pid && e->pins[s].count > 0) {
          e->refcnt -= e->pins[s].count;
          if (e->refcnt < 0) e->refcnt = 0;
          e->pins[s].pid = 0;
          e->pins[s].count = 0;
          touched++;
        }
      }
    }
  }
  maybe_rehash(h);
  // a worker that died mid-create will never seal: wake blocked getters so
  // their timeouts can fire against a now-consistent table
  bump_seal_seq(h);
  unlock(h);
  return touched;
}

// Delete an object (abort an unsealed create or free a sealed object).
// Objects pinned by readers are deleted lazily: marked unreferenced-sealed and
// reclaimed by eviction; here we only free immediately when refcnt hits 0.
int os_delete(void* hv, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  lock(h);
  ObjEntry* e = find(h, id);
  if (!e) { unlock(h); return -1; }
  if (e->refcnt <= (e->state == kCreated ? 1 : 0)) {
    dealloc(h, e->offset);
    tombstone(h->hdr, e);
    maybe_rehash(h);
    // keep the documented contract: every removal wakes waiters so a
    // delete-then-recreate (error overwrite) never strands a blocked get
    bump_seal_seq(h);
  } else {
    // readers still hold it: make it evictable as soon as they release
    e->lru_tick = 0;
    e->state = kSealed;
  }
  unlock(h);
  return 0;
}

// Fault in + write-warm the heap with a userspace memset. Call once after
// create, BEFORE any allocation (it scribbles zeros over free heap space —
// only the initial whole-heap FreeBlock may be live, and its header is
// skipped). A plain memset is used instead of MADV_POPULATE_WRITE because
// both pay the same page-zeroing cost on bare metal, but on virtualized
// hosts populate leaves pages in a state where the first real store still
// faults host-side (~3x slower copies measured) while a memset does not.
void os_prefault(void* hv) {
  Handle* h = reinterpret_cast<Handle*>(hv);
  uint8_t* heap = h->base + h->hdr->heap_off;
  uint64_t skip = sizeof(FreeBlock);
  if (h->hdr->heap_size > skip)
    memset(heap + skip, 0, h->hdr->heap_size - skip);
}

uint64_t os_capacity(void* hv) { return reinterpret_cast<Handle*>(hv)->hdr->heap_size; }
uint64_t os_bytes_in_use(void* hv) { return reinterpret_cast<Handle*>(hv)->hdr->bytes_in_use; }
uint64_t os_num_objects(void* hv) { return reinterpret_cast<Handle*>(hv)->hdr->num_objects; }
uint64_t os_evictions(void* hv) { return reinterpret_cast<Handle*>(hv)->hdr->evictions; }

}  // extern "C"
