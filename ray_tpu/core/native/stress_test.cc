// Sanitizer stress harness for the shm object store.
//
// Reference parity: the reference runs its C++ object-store tests under
// TSAN/ASAN in CI (SURVEY.md §5.2; .bazelrc sanitizer configs). The store
// is cross-process shared memory — TSAN instruments the in-process side
// (many threads hammering one attached handle) and ASAN catches
// heap/region overruns on both paths.
//
// Build+run (tests/test_sanitizers.py drives this):
//   g++ -fsanitize=thread  -O1 -g -std=c++17 stress_test.cc -o t_tsan -lpthread
//   g++ -fsanitize=address -O1 -g -std=c++17 stress_test.cc -o t_asan -lpthread
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "objstore.cc"  // single-TU build: the store is one .cc by design

namespace {

void fill_id(uint8_t* id, int thread_i, int obj_i) {
  std::memset(id, 0, 16);
  std::memcpy(id, &thread_i, sizeof(int));
  std::memcpy(id + 4, &obj_i, sizeof(int));
}

std::atomic<int> failures{0};

void worker(void* h, int thread_i, int n_objs, int rounds) {
  uint8_t id[16];
  for (int r = 0; r < rounds; ++r) {
    for (int i = 0; i < n_objs; ++i) {
      fill_id(id, thread_i, i);
      uint64_t off = os_create(h, id, 4096 + (i % 7) * 1024);
      if (off == 0 || off == UINT64_MAX) continue;  // full or duplicate
      auto* base = reinterpret_cast<uint8_t*>(
          reinterpret_cast<Handle*>(h)->base);
      std::memset(base + off, thread_i & 0xff, 4096);
      if (os_seal(h, id) != 0) failures.fetch_add(1);
    }
    for (int i = 0; i < n_objs; ++i) {
      fill_id(id, thread_i, i);
      uint64_t off = 0, size = 0;
      if (os_get(h, id, 0, &off, &size) == 0) {
        auto* base = reinterpret_cast<uint8_t*>(
            reinterpret_cast<Handle*>(h)->base);
        volatile uint8_t sink = base[off];  // touch payload
        (void)sink;
        os_release(h, id);
      }
      if (i % 3 == 0) {
        os_delete(h, id);
      }
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  const char* path = argc > 1 ? argv[1] : "/dev/shm/rtpu_stress";
  int n_threads = argc > 2 ? std::atoi(argv[2]) : 8;
  int rounds = argc > 3 ? std::atoi(argv[3]) : 20;
  ::unlink(path);
  // small store -> constant eviction + free-list churn
  void* h = os_store_create(path, 1 << 20, 4096);
  if (h == nullptr) {
    std::fprintf(stderr, "store create failed\n");
    return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; ++t) {
    threads.emplace_back(worker, h, t, 64, rounds);
  }
  for (auto& th : threads) th.join();
  std::printf("stress done: seal_failures=%d objects=%llu in_use=%llu "
              "evictions=%llu\n",
              failures.load(),
              (unsigned long long)os_num_objects(h),
              (unsigned long long)os_bytes_in_use(h),
              (unsigned long long)os_evictions(h));
  os_store_close(h);
  ::unlink(path);
  return 0;
}
