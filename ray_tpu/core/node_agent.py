"""Node agent: joins a host to a running cluster over TCP.

Reference parity: the per-node raylet daemon (reference:
src/ray/raylet/main.cc:139 + node_manager.h:124) reduced to its worker-pool
role — it registers the host's resources with the head, forks/kills worker
processes on request, and reports their exits. Scheduling stays centralized
in the head (unlike the reference's per-node scheduler) because on a TPU pod
the unit of placement is the slice, not the node (SURVEY.md §7 inversion).

Current scope: the agent's workers attach the head's shared-memory object
store, so the agent must run on a host that can see it (same machine or a
shared /dev/shm). The cross-host data plane (object push/pull over DCN,
reference object_manager.h:119) is the next layer on top of this control
plane.

Usage:
    python -m ray_tpu.core.node_agent --head HOST:PORT --authkey HEX \
        --num-cpus 4 [--name NAME] [--resources '{"TPU": 4}']
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time
import traceback
from multiprocessing.connection import Client

from .protocol import PROTOCOL_VERSION, ProtocolMismatchError


class NodeAgent:
    def __init__(self, head: str, authkey: bytes, resources: dict,
                 name: str = "", own_store: bool = False,
                 store_capacity: int = 1 << 30,
                 labels: dict | None = None,
                 reconnect_timeout_s: float = 30.0):
        host, port = head.rsplit(":", 1)
        name = name or f"agent-{os.uname().nodename}"
        self.head_addr = (host, int(port))
        self._authkey_bytes = authkey
        self._resources = dict(resources)
        self._name = name
        self.reconnect_timeout_s = reconnect_timeout_s
        self.conn = Client(self.head_addr, authkey=authkey)
        self.head_host = host
        self.send_lock = threading.Lock()

        # own-store mode: this node has its own shm store + spill dir +
        # data server — the true multi-host shape (objects cross nodes via
        # object_transfer pulls). Shared-store mode (default) requires the
        # head's /dev/shm to be visible (same machine).
        self.own_store = own_store
        self.local_store = None
        self.data_server = None
        data_addr = None
        if own_store:
            import atexit

            from .object_store import SharedObjectStore, SpillStore
            from .object_transfer import ObjectDataServer
            from .runtime import host_ip
            safe = "".join(c if c.isalnum() else "_" for c in name)
            self._own_store_path = f"/dev/shm/rtpu_node_{safe}_{os.getpid()}"
            self._own_spill_dir = f"/tmp/ray_tpu/node_{safe}_{os.getpid()}/spill"
            self.local_store = SharedObjectStore(
                self._own_store_path, capacity=store_capacity, create=True)
            # registered the instant the shm file exists: a SIGTERM that
            # lands anywhere after this point (even mid-__init__, before
            # run()'s finally is armed) still unlinks the store
            atexit.register(self.teardown)
            self.local_spill = SpillStore(self._own_spill_dir)
            self.data_server = ObjectDataServer(
                self.local_store, self.local_spill, host="0.0.0.0")
            port_part = self.data_server.address.rsplit(":", 1)[1]
            data_addr = f"{host_ip()}:{port_part}"

        # TPU VM identity labels come from the environment (TPU_NAME etc.,
        # set by the TPU runtime) — never from a jax import, which would
        # touch the accelerator tunnel during agent startup.
        from ..util.tpu import discover_tpu_labels
        self._data_addr = data_addr
        self._labels = {**discover_tpu_labels(), **(labels or {})}
        self.procs: dict[str, subprocess.Popen] = {}
        self._register()

    def _register(self):
        """Register (or re-register after a head restart) over the current
        connection (reference: raylet re-announcing itself to a failed-over
        GCS)."""
        self.conn.send({"t": "register_node", "resources": self._resources,
                        "name": self._name, "own_store": self.own_store,
                        "data_addr": self._data_addr,
                        "labels": self._labels, "pv": PROTOCOL_VERSION})
        # registration handshake: runs from the run loop only while the
        # control link is down/new, so there are no frames to stall
        reply = self.conn.recv()  # graftlint: disable=GL013
        if reply.get("t") == "rejected":
            raise ProtocolMismatchError(reply.get("error", "rejected"))
        if reply.get("pv") != PROTOCOL_VERSION:
            # symmetric check: a pre-versioning head never sends pv
            raise ProtocolMismatchError(
                f"head speaks wire-protocol version {reply.get('pv')!r}, "
                f"this node agent speaks {PROTOCOL_VERSION}")
        if reply.get("t") != "registered":
            raise RuntimeError(f"head rejected registration: {reply}")
        self.node_id = reply["node_id"]
        if self.own_store:
            self.store_path = self._own_store_path
            self.spill_dir = self._own_spill_dir
        else:
            self.store_path = reply["store_path"]
            self.spill_dir = reply.get("spill_dir", "")
            if not os.path.exists(self.store_path):
                raise RuntimeError(
                    f"object store {self.store_path} is not visible from "
                    f"this host; run with --own-store so objects move via "
                    f"the transfer service")
        # the head never echoes the authkey; we authenticated with our copy
        self.authkey = self._authkey_bytes.hex()
        self.tcp_port = reply["tcp_port"]

    def _reconnect(self) -> bool:
        """The head went away: kill orphaned workers (their control conns
        died with it) and re-dial the SAME address with backoff — a head
        restarted with cfg.head_tcp_port + RTPU_CLUSTER_AUTHKEY comes back
        dialable (the Redis-fixed-address role in reference GCS FT)."""
        if self.reconnect_timeout_s <= 0:
            return False
        for p in list(self.procs.values()):
            try:
                p.kill()
            except Exception:
                pass  # already exited
        self.procs.clear()
        deadline = time.monotonic() + self.reconnect_timeout_s
        delay = 0.25
        while time.monotonic() < deadline:
            try:
                conn = Client(self.head_addr, authkey=self._authkey_bytes)
                # swap + register atomically vs the heartbeat thread: its
                # send() takes send_lock, so no heartbeat can interleave
                # into the new conn before register_node goes out
                with self.send_lock:
                    self.conn = conn
                    self._register()
                print(f"node_agent: re-joined as node {self.node_id}",
                      flush=True)
                return True
            except ProtocolMismatchError as e:
                # deterministic refusal — retrying cannot succeed
                print(f"node_agent: rejoin refused: {e}", flush=True)
                return False
            except Exception:
                # backoff while the head is unreachable: link down, no
                # inbound frames to stall
                time.sleep(delay)  # graftlint: disable=GL013
                delay = min(delay * 2, 2.0)
        return False

    def send(self, msg):
        with self.send_lock:
            self.conn.send(msg)

    def _spawn(self, wid: str, node_id: str, tpu: bool):
        from .runtime import build_worker_env

        env = build_worker_env(
            store_path=self.store_path,
            head_addr=f"{self.head_host}:{self.tcp_port}",
            head_family="AF_INET", authkey_hex=self.authkey,
            wid=wid, node_id_hex=node_id, tpu=tpu,
            spill_dir=self.spill_dir, own_store=self.own_store)
        log_dir = os.environ.get("RTPU_AGENT_LOG_DIR", "/tmp/ray_tpu_agent")
        os.makedirs(log_dir, exist_ok=True)
        log = open(os.path.join(log_dir, f"worker-{wid}.log"), "wb")
        # fork+exec on the control loop is this frame's entire job;
        # heartbeats ride a separate timer thread, and spawning async
        # would reorder spawn_worker against a racing kill_worker
        proc = subprocess.Popen(  # graftlint: disable=GL013
            [sys.executable, "-m", "ray_tpu.core.worker"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        self.procs[wid] = proc
        self.send({"t": "worker_spawned", "wid": wid, "pid": proc.pid})
        threading.Thread(target=self._watch, args=(wid, proc),
                         daemon=True).start()

    def _watch(self, wid: str, proc: subprocess.Popen):
        rc = proc.wait()
        self.procs.pop(wid, None)
        try:
            self.send({"t": "worker_exit", "wid": wid, "rc": rc})
        except Exception:
            pass  # head gone; its EOF cleanup covers this

    def _heartbeat_loop(self):
        from .config import cfg
        period = cfg.health_check_period_ms / 1000.0
        if period <= 0:
            return
        while True:
            time.sleep(period)
            try:
                self.send({"t": "heartbeat"})
            except Exception:
                # conn gone: run() may be mid-reconnect (it swaps self.conn
                # in) — keep looping; the daemon thread dies with teardown
                continue

    def run(self):
        threading.Thread(target=self._heartbeat_loop, daemon=True,
                         name="agent-heartbeat").start()
        try:
            while True:
                try:
                    msg = self.conn.recv()
                except (EOFError, OSError):
                    if self._reconnect():
                        continue
                    break
                t = msg.get("t")
                if t == "spawn_worker":
                    try:
                        self._spawn(msg["wid"], msg["node_id"],
                                    msg.get("tpu", False))
                    except Exception:
                        traceback.print_exc()
                        self.send({"t": "worker_exit", "wid": msg["wid"],
                                   "rc": -1})
                elif t == "free_objects":
                    if self.local_store is not None:
                        from .ids import ObjectID
                        for ob in msg["oids"]:
                            try:
                                self.local_store.delete(ObjectID(ob))
                            except Exception:
                                pass  # already evicted/deleted
                            self.local_spill.delete(ObjectID(ob))
                elif t == "kill_worker":
                    p = self.procs.get(msg["wid"])
                    if p is not None:
                        try:
                            p.kill()
                        except Exception:
                            pass  # already exited
                elif t == "shutdown":
                    break
        except (EOFError, OSError):
            pass  # head went away
        finally:
            self.teardown()

    _torn_down = False

    def teardown(self):
        """Idempotent full cleanup (kill workers, unlink the own-store shm
        file). Runs from run()'s finally, atexit, and the SIGTERM path; a
        second SIGTERM mid-teardown is ignored so the unlink completes."""
        if self._torn_down:
            return
        import signal
        try:
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
        except (ValueError, OSError):
            pass  # not the main thread / already exiting
        # flag AFTER masking SIGTERM: a signal landing between the two
        # would abort this run while the atexit retry no-ops on the flag
        self._torn_down = True
        try:
            # announce the exit so the head removes this node NOW instead
            # of on conn EOF / heartbeat timeout (runtime._agent_loop's
            # "deregister" branch); moot when the head initiated it
            self.send({"t": "deregister"})
        except Exception:
            pass  # head already gone; EOF-side cleanup covers it
        for p in list(self.procs.values()):
            try:
                p.kill()
            except Exception:
                pass  # already exited
        deadline = time.monotonic() + 2.0
        for p in list(self.procs.values()):
            try:
                p.wait(timeout=max(0.01, deadline - time.monotonic()))
            except Exception:
                pass  # unkillable child; we exit anyway
        if self.data_server is not None:
            try:
                self.data_server.stop()
            except Exception:
                pass  # server thread died with its socket
        if self.local_store is not None:
            try:
                self.local_store.close(unlink=True)
            except Exception:
                pass  # shm file may already be unlinked


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--head", required=True, help="head TCP address host:port")
    ap.add_argument("--authkey", default=None,
                    help="cluster authkey hex (or env RTPU_AUTHKEY)")
    ap.add_argument("--num-cpus", type=float, default=1.0)
    ap.add_argument("--resources", default="{}",
                    help='extra resources JSON, e.g. \'{"TPU": 4}\'')
    ap.add_argument("--name", default="")
    ap.add_argument("--labels", default="{}",
                    help='node labels JSON, e.g. '
                         '\'{"rtpu.tpu.slice": "pod-0"}\'')
    ap.add_argument("--own-store", action="store_true",
                    help="node-local object store + transfer service "
                         "(required off the head host)")
    ap.add_argument("--store-capacity", type=int, default=1 << 30)
    ap.add_argument("--reconnect-timeout", type=float, default=30.0,
                    help="seconds to retry re-dialing a restarted head "
                         "(0 disables)")
    args = ap.parse_args(argv)
    # terminate() must run the teardown path (kill workers, unlink the
    # own-store shm file) — without this, every terminated agent leaks
    # its /dev/shm store for the host's lifetime
    import signal
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    authkey = bytes.fromhex(args.authkey or os.environ["RTPU_AUTHKEY"])
    resources = {"CPU": args.num_cpus, **json.loads(args.resources)}
    agent = NodeAgent(args.head, authkey, resources, args.name,
                      own_store=args.own_store,
                      store_capacity=args.store_capacity,
                      labels=json.loads(args.labels),
                      reconnect_timeout_s=args.reconnect_timeout)
    print(f"node_agent: joined as node {agent.node_id}", flush=True)
    agent.run()


if __name__ == "__main__":
    main()
