"""Python client for the native shared-memory object store.

Reference parity: python side of plasma (reference:
src/ray/object_manager/plasma/client.h + the flatbuffer protocol plasma.fbs).
Our design has no store server process — every process mmaps the same region
and synchronizes through a process-shared robust mutex (see
native/objstore.cc for rationale). Payloads are framed as:

    [1B flags][4B n_bufs][8B pickle_len][pickle bytes][(8B len, raw bytes)*]

where out-of-band pickle-5 buffers carry numpy/jax arrays without an extra
copy on the serialize side (reference analog: _private/serialization.py:123
zero-copy numpy handling).
"""
from __future__ import annotations

import ctypes
import mmap
import os
import pickle
import struct
import sys
import threading
from typing import Any, Optional

import cloudpickle
import numpy as np

from .ids import ObjectID
from .native.build import ensure_built
from . import flight
from . import stacks

_FLAG_NORMAL = 0
_FLAG_EXCEPTION = 1

_HEADER = struct.Struct("<BxxxIQ")  # flags, n_bufs, pickle_len

# Pieces at least this large are copied with ctypes.memmove in
# _FramedValue.write_into (see comment there); smaller ones stay on the
# simpler slice-assignment path.
_MEMMOVE_MIN = 256 * 1024

# Pieces at least this large copy on a small thread pool: ctypes.memmove
# releases the GIL, so slicing one multi-hundred-MiB memmove across
# threads tracks the machine's memory bandwidth instead of one core's
# share of it (the put-bandwidth path — bench_core
# single_client_put_gigabytes profiles as ~97% this copy).
_PARALLEL_MIN = 32 * 1024 * 1024
_COPY_THREADS_AUTO = 4
_COPY_THREADS_MAX = 16
_copy_pool = None          # guarded by: _copy_pool_lock
_copy_pool_width = 0       # guarded by: _copy_pool_lock
_copy_pool_pid = 0         # guarded by: _copy_pool_lock
_copy_pool_lock = threading.Lock()


def _ensure_copy_pool_locked(threads: int):
    """The per-process copy pool, built/regrown. CALLER HOLDS
    _copy_pool_lock — and every submit happens under the same lock
    (_copy_parallel), which is what makes the regrow swap safe: once
    this function replaces the pool, no racing put can still be between
    "fetched the old pool" and "submitted to it", so the old pool can
    be drained with shutdown(wait=False) immediately — queued slices
    finish, its threads then retire, nothing is left to GC timing.
    Fork safety: a child inheriting the parent's pool object has no
    live worker threads, so a pid change forces a rebuild (the ghost
    pool is NOT shutdown — its internal lock state is whatever the
    parent froze at fork time)."""
    global _copy_pool, _copy_pool_width, _copy_pool_pid
    if _copy_pool is None or _copy_pool_pid != os.getpid() \
            or _copy_pool_width < threads:
        import concurrent.futures as cf
        old, old_pid = _copy_pool, _copy_pool_pid
        _copy_pool = cf.ThreadPoolExecutor(
            max_workers=threads, thread_name_prefix="rtpu-copy")
        _copy_pool_width = threads
        _copy_pool_pid = os.getpid()
        if old is not None and old_pid == os.getpid():
            # drain, don't drop: in-flight futures complete, idle
            # threads exit once the queue empties (wait=False: never
            # block a put on another put's copies)
            old.shutdown(wait=False)
    return _copy_pool


def _copy_parallel(dst: int, src, n: int) -> None:
    """memmove(dst, src, n), sliced across the copy pool for large n.
    `src` is an int address or a bytes object. Slices are SUBMITTED
    under _copy_pool_lock (cheap queue puts) so a concurrent regrow
    (cfg.put_copy_threads raised mid-run) can never shut the pool down
    between our fetch and our submit; the actual copying — and the
    wait for it — happens outside the lock on the pool threads."""
    from .config import cfg
    threads = min(cfg.put_copy_threads or _COPY_THREADS_AUTO,
                  _COPY_THREADS_MAX)
    if n < _PARALLEL_MIN or threads <= 1:
        ctypes.memmove(dst, src, n)
        return
    if isinstance(src, bytes):
        # zero-copy readonly view; keeps `src` alive across the workers
        src_arr = np.frombuffer(src, np.uint8)
        src = src_arr.ctypes.data
    step = -(-n // threads)  # ceil
    with _copy_pool_lock:
        pool = _ensure_copy_pool_locked(threads)
        futs = [pool.submit(ctypes.memmove, dst + off, src + off,
                            min(step, n - off))
                for off in range(0, n, step)]
    for f in futs:
        f.result()


class ObjectStoreFullError(MemoryError):
    pass


class ObjectLostError(Exception):
    """Object was evicted and is no longer in the store (lineage needed)."""


class GetTimeoutError(TimeoutError):
    pass


class ChannelStopped(Exception):
    """A stop-aware channel get aborted: the stop flag sealed while
    waiting and the data slot never arrived (dag/channel.py teardown)."""


def _load_lib() -> ctypes.CDLL:
    try:
        lib = ctypes.CDLL(ensure_built())
    except OSError:
        # a shipped .so can be source-current yet unloadable here (built
        # against a newer glibc); recompile for this host
        from .native.build import rebuild
        lib = ctypes.CDLL(rebuild())
    lib.os_store_create.restype = ctypes.c_void_p
    lib.os_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32]
    lib.os_store_attach.restype = ctypes.c_void_p
    lib.os_store_attach.argtypes = [ctypes.c_char_p]
    lib.os_store_close.argtypes = [ctypes.c_void_p]
    lib.os_create.restype = ctypes.c_uint64
    lib.os_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    lib.os_seal.restype = ctypes.c_int
    lib.os_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.os_get.restype = ctypes.c_int
    lib.os_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.os_contains.restype = ctypes.c_int
    lib.os_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.os_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.os_delete.restype = ctypes.c_int
    lib.os_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.os_reclaim_pid.restype = ctypes.c_int
    lib.os_reclaim_pid.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.os_wait_sealed.restype = ctypes.c_int
    lib.os_wait_sealed.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint32,
        ctypes.c_int64, ctypes.c_char_p,
    ]
    lib.os_chan_get.restype = ctypes.c_int
    lib.os_chan_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.os_seal_seq.restype = ctypes.c_uint32
    lib.os_seal_seq.argtypes = [ctypes.c_void_p]
    lib.os_wait_seq.restype = ctypes.c_int
    lib.os_wait_seq.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.c_int64]
    lib.os_prefault.restype = None
    lib.os_prefault.argtypes = [ctypes.c_void_p]
    lib.os_store_refresh_pid.restype = None
    lib.os_store_refresh_pid.argtypes = [ctypes.c_void_p]
    for fn in ("os_capacity", "os_bytes_in_use", "os_num_objects", "os_evictions"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    return lib


class _FramedValue:
    """One serialization of a value in the store's wire framing, writable
    to either a shm buffer or a spill file (serialize once, place anywhere).

    Copy audit (put-bandwidth path): pickle-5 out-of-band buffers are
    REFERENCED (`b.raw()` is a view into the caller's array), never copied
    at serialize time; the single copy a put pays is write_into's memmove
    from the source array into the (MADV_HUGEPAGE-advised, optionally
    prefaulted) store mapping. Spill streams the same pieces to disk
    without materializing the frame (SpillStore.spill_frame).
    """

    def __init__(self, value: Any, is_exception: bool):
        buffers: list[pickle.PickleBuffer] = []
        self.payload = cloudpickle.dumps(value, protocol=5,
                                         buffer_callback=buffers.append)
        self.raws = [b.raw() for b in buffers]
        self.flags = _FLAG_EXCEPTION if is_exception else _FLAG_NORMAL
        self.total = (_HEADER.size + len(self.payload)
                      + sum(8 + len(r) for r in self.raws))

    def write_into(self, buf) -> None:
        pos = 0
        dst_addr = None
        for piece in self.iter_wire():
            n = len(piece)
            if n >= _MEMMOVE_MIN:
                # ctypes.memmove is ~2x the bandwidth of memoryview slice
                # assignment on multi-MiB pieces (the slice path goes
                # through PyBuffer item copying; memmove is glibc's
                # vectorized copy). Only worth the address plumbing for
                # large pieces.
                if dst_addr is None:
                    dst_addr = ctypes.addressof(
                        ctypes.c_char.from_buffer(buf))
                src = piece if isinstance(piece, bytes) else \
                    np.frombuffer(piece, np.uint8).ctypes.data
                _copy_parallel(dst_addr + pos, src, n)
            else:
                buf[pos:pos + n] = piece
            pos += n

    def iter_wire(self):
        """The frame as a sequence of buffers in wire order — lets senders
        stream it (socket sendall per piece) without materializing a
        second full-size copy."""
        yield _HEADER.pack(self.flags, len(self.raws), len(self.payload))
        yield self.payload
        for r in self.raws:
            yield struct.pack("<Q", len(r))
            yield r


class _PinnedBuffer:
    """Zero-copy view of one pickle-5 buffer inside the shm store.

    Exposes the buffer protocol (PEP 688, Python >= 3.12), so numpy
    reconstructs arrays directly over store memory and keeps this object
    alive as their base. When the LAST consumer array is GC'd, the
    object's read pin is released and it becomes evictable again — the
    same lifetime rule plasma gives the reference
    (plasma/client.h Get/Release). Views are read-only, like reference
    arrays out of plasma.
    """

    __slots__ = ("_view", "_on_release")

    def __init__(self, view: memoryview, on_release):
        self._view = view.toreadonly()
        self._on_release = on_release

    def __buffer__(self, flags):
        return self._view

    def __del__(self):
        try:
            self._view.release()
        except BufferError:
            pass  # an export is mid-release; the view dies with us anyway
        finally:
            self._on_release()


def _parse_frame(view, pinned_release=None) -> Any:
    """Inverse of _FramedValue over a buffer; raises stored exceptions.

    With `pinned_release` (a callable releasing the store read pin), large
    out-of-band buffers deserialize ZERO-COPY as read-only views pinned in
    the store; `pinned_release` fires when the last one dies. Without it,
    buffers are copied out and the caller releases the pin itself.
    """
    from .ref import loading_stored_refs
    flags, n_bufs, plen = _HEADER.unpack_from(view, 0)
    pos = _HEADER.size
    payload = bytes(view[pos:pos + plen])
    pos += plen
    bufs = []
    zero_copy = pinned_release is not None and flags != _FLAG_EXCEPTION \
        and n_bufs > 0
    refcnt = {"n": 0}

    def buffer_died():
        refcnt["n"] -= 1
        if refcnt["n"] == 0:
            pinned_release()

    for _ in range(n_bufs):
        (blen,) = struct.unpack_from("<Q", view, pos)
        pos += 8
        if zero_copy:
            bufs.append(_PinnedBuffer(view[pos:pos + blen], buffer_died))
            refcnt["n"] += 1
        else:
            bufs.append(bytes(view[pos:pos + blen]))
        pos += blen
    with loading_stored_refs():
        value = pickle.loads(payload, buffers=bufs)
    del bufs  # drop parse-time references: consumers now own the pins
    if flags == _FLAG_EXCEPTION:
        raise value
    return value if pinned_release is None else (value, zero_copy)


class SpillStore:
    """Disk spill area for objects the shm store can't hold (reference:
    raylet/local_object_manager.h:42 SpillObjects :112 +
    _private/external_storage.py FileSystemStorage). One file per object in
    the store's wire framing, written atomically (tmp + rename) so readers
    never see partials."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, oid: ObjectID) -> str:
        return os.path.join(self.dir, oid.hex() + ".bin")

    def spill(self, oid: ObjectID, value: Any,
              is_exception: bool = False) -> int:
        return self.spill_frame(oid, _FramedValue(value, is_exception))

    def spill_frame(self, oid: ObjectID, frame: "_FramedValue") -> int:
        # stream the frame piecewise: materializing a full-size bytearray
        # first doubled the copy volume for multi-GiB spills (write_into +
        # write); the out-of-band buffers go straight from their owner's
        # memory to the page cache
        tmp = self._path(oid) + ".tmp"
        with open(tmp, "wb") as f:
            for piece in frame.iter_wire():
                f.write(piece)
        os.replace(tmp, self._path(oid))
        return frame.total

    def contains(self, oid: ObjectID) -> bool:
        return os.path.exists(self._path(oid))

    def load(self, oid: ObjectID) -> Any:
        with open(self._path(oid), "rb") as f:
            return _parse_frame(f.read())

    def delete(self, oid: ObjectID) -> None:
        try:
            os.unlink(self._path(oid))
        except OSError:
            pass


# Live stores in this process, so the at-fork hook can re-key their
# cached pid (the native handle pins objects under Handle.pid; a forked
# child inheriting the parent's handle must pin under ITS pid or the
# parent's exit reclaim would strip pins the child still reads through).
import weakref

_LIVE_STORES: "weakref.WeakSet[SharedObjectStore]" = weakref.WeakSet()


def _refresh_store_pids_after_fork() -> None:
    for s in list(_LIVE_STORES):
        h = s._h
        if h:
            try:
                s._lib.os_store_refresh_pid(h)
            except Exception:
                pass  # store handle mid-close in the parent


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_refresh_store_pids_after_fork)


class SharedObjectStore:
    """One per process; created by the head (driver), attached by workers."""

    def __init__(self, path: str, capacity: int = 0, max_entries: int = 65536,
                 create: bool = False):
        self._lib = _load_lib()
        self.path = path
        if create:
            self._h = self._lib.os_store_create(path.encode(), capacity, max_entries)
        else:
            self._h = self._lib.os_store_attach(path.encode())
        if not self._h:
            raise RuntimeError(f"failed to open object store at {path}")
        self._fd = os.open(path, os.O_RDWR)
        size = os.fstat(self._fd).st_size
        self._mm = mmap.mmap(self._fd, size)
        self._advise_mapping(create)
        self._view = memoryview(self._mm)
        self._owner = create
        # heap capacity is fixed for the store's lifetime: cache it so the
        # per-put spill-threshold check costs one ctypes call, not two
        self._capacity = int(self._lib.os_capacity(self._h))
        _LIVE_STORES.add(self)

    # Linux madvise constants Python's mmap module doesn't export yet.
    _MADV_HUGEPAGE = 14
    _MADV_POPULATE_READ = 22

    def _advise_mapping(self, create: bool) -> None:
        """THP always (cheap, helps TLB on multi-MiB memcpys); full
        pre-fault only when cfg.store_prefault — put/get bandwidth is
        bounded by first-touch faulting otherwise (measured ~1.8 vs ~6.4
        GiB/s for 128 MiB frames on shm), but faulting the whole capacity
        costs seconds per GiB at create, which short-lived test clusters
        don't want. The creator write-warms the heap via os_prefault's
        memset (see objstore.cc for why not MADV_POPULATE_WRITE);
        attachers populate READ-only PTEs."""
        from .config import cfg
        try:
            self._mm.madvise(getattr(mmap, "MADV_HUGEPAGE",
                                     self._MADV_HUGEPAGE))
        except (OSError, ValueError):
            pass
        if cfg.store_prefault:
            if create:
                # Creator prefault: the C side memsets the heap write-warm
                # (see os_prefault in objstore.cc for why not
                # MADV_POPULATE_WRITE). Must run before any allocation.
                self._lib.os_prefault(self._h)
            else:
                try:
                    self._mm.madvise(
                        getattr(mmap, "MADV_POPULATE_READ",
                                self._MADV_POPULATE_READ))
                except (OSError, ValueError):
                    pass  # pre-5.14 kernel: stay lazy

    # -- raw byte-level API ------------------------------------------------

    def _handle(self):
        h = self._h
        if h is None:
            raise RuntimeError("object store is closed")
        return h

    def create_raw(self, oid: ObjectID, size: int) -> memoryview:
        off = self._lib.os_create(self._handle(), oid.binary(), size)
        if off == 2**64 - 1:
            raise FileExistsError(f"object {oid} already exists")
        if off == 0:
            raise ObjectStoreFullError(
                f"object store full ({self.bytes_in_use()}/{self.capacity()} "
                f"bytes in use) while allocating {size} bytes")
        flight.evt(flight.OBJ_CREATE, flight.lo48(oid), size)
        return self._view[off:off + size]

    def seal(self, oid: ObjectID) -> None:
        if self._lib.os_seal(self._handle(), oid.binary()) != 0:
            raise RuntimeError(f"seal failed for {oid}")
        flight.evt(flight.OBJ_SEAL, flight.lo48(oid))

    def get_raw(self, oid: ObjectID, timeout_ms: int = -1) -> Optional[memoryview]:
        """Pin + return the payload view, or None on timeout. Caller must
        release(oid) when done with the view."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if timeout_ms < 0:
            timeout_ms = 2**31  # ~24 days; effectively infinite
        # non-blocking first: the hot path (object already sealed — every
        # get of a computed result) pays NO beacon traffic and the same
        # single native call as before
        rc = self._lib.os_get(self._handle(), oid.binary(), 0,
                              ctypes.byref(off), ctypes.byref(size))
        if rc != 0 and timeout_ms != 0:
            # about to actually park: arm the wait beacon (stacks.py) so
            # a live stack dump shows WHAT the native futex wait is
            # waiting for. `armed` keeps a more specific outer beacon
            # (channel credit waits) from being overwritten.
            b = stacks.beacon()
            armed = not b[0]
            if armed:
                ob = oid.binary()
                stacks.set_wait(b, stacks.WAIT_GET, flight.lo48(ob),
                                tag=stacks.wait_tag(ob))
            try:
                rc = self._lib.os_get(self._handle(), oid.binary(),
                                      timeout_ms, ctypes.byref(off),
                                      ctypes.byref(size))
            finally:
                if armed:
                    stacks.clear_wait(b)
        if rc != 0:
            return None
        return self._view[off.value:off.value + size.value]

    def release(self, oid: ObjectID) -> None:
        h = self._h
        if h is None:
            return  # closed (teardown): zero-copy pins die with the mapping
        self._lib.os_release(h, oid.binary())

    def contains(self, oid: ObjectID) -> bool:
        return bool(self._lib.os_contains(self._handle(), oid.binary()))

    # Chunk bound for full waits: os_wait_sealed rescans the whole
    # not-yet-observed set under the store mutex on every seal event, so
    # waiting on one huge list costs O(n^2) probes while serializing other
    # processes' store ops. A full wait (min_count >= n) decomposes
    # exactly into waiting each chunk to completion in turn.
    _WAIT_CHUNK = 1024

    def wait_sealed(self, oids, min_count: int,
                    timeout_ms: int) -> list[bool]:
        """Block until at least `min_count` of `oids` are sealed (or the
        timeout fires); returns one observed-sealed flag per oid. One futex
        wait on the store header's seal-sequence word services whichever
        object seals first — the event-driven multi-object primitive behind
        bulk get()/wait() (timeout_ms=0 is a non-blocking bulk contains).
        Spilled objects never seal in shm: callers re-check their spill
        fallback between bounded slices."""
        n = len(oids)
        if n == 0:
            return []
        if timeout_ms == 0:
            # non-blocking bulk contains: no flight record — depth probes
            # and sealed_now() polls would flood the ring with non-events
            if n > self._WAIT_CHUNK:
                return self._wait_sealed_chunked(oids, min_count, 0)
            return self._wait_sealed_call(oids, min_count, 0)
        flight.evt(flight.WAIT_BEGIN, n, min_count)
        # wait beacon: this thread is about to park on these ids — a live
        # stack dump (stacks.py) names the first one + the count. An
        # already-armed beacon (an outer channel-credit wait driving this
        # wait_sealed) wins; we only arm/clear when we armed.
        b = stacks.beacon()
        armed = not b[0]
        if armed:
            ob = oids[0].binary()
            stacks.set_wait(b, stacks.WAIT_OBJ, flight.lo48(ob), n,
                            tag=stacks.wait_tag(ob))
        try:
            if n > self._WAIT_CHUNK:
                out = self._wait_sealed_chunked(oids, min_count,
                                                timeout_ms)
            else:
                out = self._wait_sealed_call(oids, min_count, timeout_ms)
        finally:
            if armed:
                stacks.clear_wait(b)
        flight.evt(flight.WAIT_END, sum(out))
        return out

    def _wait_sealed_chunked(self, oids, min_count: int,
                             timeout_ms: int) -> list[bool]:
        """wait_sealed over a huge list: seqlock-style. Scan in bounded
        chunks (each a short store-mutex hold, so other processes' store
        ops never stall behind one O(n) probe pass), then block on the
        seal-sequence word until something seals and rescan only the
        still-unmet ids."""
        import time as _time
        n = len(oids)
        deadline = _time.monotonic() + timeout_ms / 1000.0
        flags = [False] * n
        unmet = list(range(n))
        while True:
            seq = self._lib.os_seal_seq(self._handle())
            for s in range(0, len(unmet), self._WAIT_CHUNK):
                idxs = unmet[s:s + self._WAIT_CHUNK]
                got = self._wait_sealed_call([oids[i] for i in idxs], 0, 0)
                for i, f in zip(idxs, got):
                    if f:
                        flags[i] = True
            unmet = [i for i in unmet if not flags[i]]
            if n - len(unmet) >= min_count or not unmet:
                return flags
            if timeout_ms == 0:
                return flags
            remain_ms = int((deadline - _time.monotonic()) * 1000)
            if remain_ms <= 0:
                return flags
            # a seal between our seq read and this wait returns
            # immediately (seq moved); otherwise any seal/delete wakes us
            self._lib.os_wait_seq(self._handle(), seq, remain_ms)

    def wait_sealed_indices(self, oids, min_count: int,
                            timeout_ms: int) -> list[int]:
        """wait_sealed, returning the INDICES observed sealed instead of
        per-oid flags. The multi-producer fan-in consumers (rl rollout
        queue over dag/channel.MultiRingReader) park in one of these over
        {every producer's next slot, stop} and service whichever sealed —
        the multi-oid analog of os_chan_get's {data, stop} pair."""
        return [i for i, f in enumerate(
            self.wait_sealed(oids, min_count, timeout_ms)) if f]

    def _wait_sealed_call(self, oids, min_count: int,
                          timeout_ms: int) -> list[bool]:
        n = len(oids)
        ids = b"".join(o.binary() for o in oids)
        out = ctypes.create_string_buffer(n)
        self._lib.os_wait_sealed(self._handle(), ids, n,
                                 max(0, min_count), timeout_ms, out)
        return [b != 0 for b in out.raw]

    def delete(self, oid: ObjectID) -> None:
        self._lib.os_delete(self._handle(), oid.binary())

    def reclaim_pid(self, pid: int) -> int:
        """Abort unsealed creates and drop read pins leaked by a dead
        process (call when a worker is reaped)."""
        return self._lib.os_reclaim_pid(self._handle(), pid)

    # -- object-level API --------------------------------------------------

    def put(self, oid: ObjectID, value: Any, is_exception: bool = False) -> int:
        """Serialize `value` into the store under `oid`. Returns payload size.

        Atomic on failure: a raise between create_raw and seal deletes
        the half-written object, so `oid` never wedges in the unsealed
        state (a stranded unsealed object makes every retry die with
        FileExistsError and parks wait_sealed callers forever)."""
        frame = _FramedValue(value, is_exception)
        buf = self.create_raw(oid, frame.total)
        try:
            frame.write_into(buf)
            del buf
            self.seal(oid)
        except BaseException:
            buf = None  # release the view before delete, or the segment pins
            try:
                self.delete(oid)
            except Exception:
                pass  # store closing / already reclaimed
            raise
        return frame.total

    def put_or_spill(self, oid: ObjectID, value: Any, is_exception: bool,
                     spill: Optional["SpillStore"]) -> bool:
        """Store `value`, spilling the SAME serialized frame to disk when
        the store is full (one serialization either way). Returns True if
        spilled. Raises ObjectStoreFullError when full and spill is None.

        Proactive spilling (local_object_manager.h:112 analog): once the
        store passes ``cfg.object_spilling_threshold`` fill, frames at least
        ``cfg.min_spilling_size`` go straight to disk instead of forcing
        LRU eviction of hot shm objects."""
        from .config import cfg
        frame = _FramedValue(value, is_exception)
        if (spill is not None
                and frame.total >= cfg.min_spilling_size
                and self.bytes_in_use()
                    > cfg.object_spilling_threshold * self.capacity()):
            spill.spill_frame(oid, frame)
            return True
        try:
            buf = self.create_raw(oid, frame.total)
        except ObjectStoreFullError:
            if spill is None:
                raise
            spill.spill_frame(oid, frame)
            return True
        try:
            frame.write_into(buf)
            del buf
            self.seal(oid)
        except BaseException:
            buf = None
            try:
                self.delete(oid)
            except Exception:
                pass  # store closing / already reclaimed
            raise
        return False

    def get(self, oid: ObjectID, timeout_ms: int = -1,
            zero_copy: Optional[bool] = None) -> Any:
        """Deserialize the object. Raises GetTimeoutError on timeout and
        re-raises stored exceptions. With cfg.zero_copy_get, large buffers
        come back as read-only views pinned in the store until their
        arrays are GC'd (plasma semantics). Pass zero_copy=False to force
        the copy path — required by LEGACY consume-once readers (polling
        DAG channels) whose delete-then-recreate of the same id cannot
        tolerate a lazy, pin-deferred delete; sealed ring channels never
        reuse an id, so they read under the cfg default."""
        view = self.get_raw(oid, timeout_ms)
        if view is None:
            raise GetTimeoutError(f"timed out waiting for {oid}")
        return self._materialize(oid, view, zero_copy)

    def get_chan(self, oid: ObjectID, stop_oid: ObjectID,
                 timeout_ms: int = -1,
                 zero_copy: Optional[bool] = None) -> Any:
        """Stop-aware channel get (os_chan_get): one native blocking call
        that wakes on either the data seal or the stop seal. Raises
        ChannelStopped when the stop flag sealed and no data arrived;
        otherwise behaves like get()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        if timeout_ms < 0:
            timeout_ms = 2**31  # ~24 days; effectively infinite
        # channel-wait beacon: lo48 of a slot oid equals lo48 of its
        # channel base (slot ids share the base's first 12 bytes), so the
        # stack report and the wait-graph fold resolve this directly
        # against the producer endpoint tables
        b = stacks.beacon()
        armed = not b[0]
        if armed:
            ob = oid.binary()
            stacks.set_wait(b, stacks.WAIT_CHAN, flight.lo48(ob),
                            tag=stacks.wait_tag(ob))
        try:
            rc = self._lib.os_chan_get(self._handle(), oid.binary(),
                                       stop_oid.binary(), timeout_ms,
                                       ctypes.byref(off),
                                       ctypes.byref(size))
        finally:
            if armed:
                stacks.clear_wait(b)
        if rc == -3:
            raise ChannelStopped(f"stop flag sealed while waiting for {oid}")
        if rc != 0:
            raise GetTimeoutError(f"timed out waiting for {oid}")
        view = self._view[off.value:off.value + size.value]
        return self._materialize(oid, view, zero_copy)

    def _materialize(self, oid: ObjectID, view, zero_copy: Optional[bool]):
        """Shared tail of get()/get_chan(): deserialize a pinned view and
        manage the read pin across the copy and zero-copy paths."""
        from .config import cfg
        if zero_copy is None:
            # _PinnedBuffer needs __buffer__ (PEP 688, CPython >= 3.12);
            # older interpreters silently fall back to the copy path
            zero_copy = cfg.zero_copy_get and sys.version_info >= (3, 12)
        if not zero_copy:
            try:
                return _parse_frame(view)
            finally:
                del view
                self.release(oid)
        state = {"released": False}

        def rel_once():
            # one pin, many possible release paths (error + wrapper deaths
            # of partially-consumed buffers): never unpin twice
            if not state["released"]:
                state["released"] = True
                self.release(oid)

        try:
            value, transferred = _parse_frame(view, pinned_release=rel_once)
        except BaseException:
            del view
            rel_once()
            raise
        del view
        if not transferred:   # no out-of-band buffers: nothing stayed pinned
            rel_once()
        return value

    # -- stats -------------------------------------------------------------

    def capacity(self) -> int:
        return self._capacity

    def bytes_in_use(self) -> int:
        return self._lib.os_bytes_in_use(self._handle())

    def num_objects(self) -> int:
        return self._lib.os_num_objects(self._handle())

    def evictions(self) -> int:
        return self._lib.os_evictions(self._handle())

    def close(self, unlink: bool = False) -> None:
        if self._h:
            h, self._h = self._h, None  # new calls now fail cleanly
            # let in-flight os_get slices (<=200ms waits) drain before unmap
            import time
            time.sleep(0.25)
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                pass  # a reader still holds a view; leak the map, not a SEGV
            os.close(self._fd)
            self._lib.os_store_close(h)
            if unlink and self._owner:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
