"""Node-to-node object transfer: the cross-host data plane.

Reference parity: src/ray/object_manager/object_manager.h:119 (Push :209 /
Pull :217 — chunked object movement between per-node plasma stores over
gRPC) + the ownership-based object directory locating copies.

TPU-first reduction: one data server per node-local store serving whole
frames over a raw TCP socket (objects move between HOSTS over DCN — the
hot tensor path inside a slice is XLA collectives over ICI, so this
service carries control-plane-adjacent payloads: task args/results,
checkpoints, datasets blocks). A puller asks the head for locations
(the directory tracks which node produced each object), dials the owner's
data server, and writes the received frame into its LOCAL store — after
which the object is served locally and the head records the new copy.

Wire protocol (per request, connections are reused; 1-byte verb first):
  G (get):  -> 'G' + 16B object id
            <- 8B little-endian frame length (0 = not here) + frame bytes
  R (range):-> 'R' + 16B object id + 8B offset + 8B max bytes
            <- 8B TOTAL frame length (0 = not here)
               + min(max, total-offset) payload bytes from offset
  P (push): -> 'P' + 16B object id + 8B frame length + frame bytes
            <- 1B status (1 = stored/already-present, 0 = failed)
Push is how producers place data INTO a peer store without a directory
round-trip — compiled-DAG channels and bulk broadcast use it (reference
Push: object_manager.h:209). Ranged gets are the chunked/resumable pull
path (reference chunked Pull: object_manager.h:217, pull_manager.h:49):
fetch_resilient pulls a large frame in cfg.transfer_chunk_bytes pieces,
resumes from the last good byte after a transport error, fails over
across every known holder, and streams frames bigger than the local
store straight to the spill directory.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .ids import ObjectID
from .object_store import SharedObjectStore, SpillStore


class ObjectDataServer:
    """Serves frames out of a local store (+ its spill dir)."""

    def __init__(self, store: SharedObjectStore,
                 spill: Optional[SpillStore] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.spill = spill
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="rtpu-objdata").start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                verb = _recv_exact(conn, 1)
                if verb is None:
                    return
                if verb == b"G":
                    if not self._serve_get(conn):
                        return
                elif verb == b"R":
                    if not self._serve_range(conn):
                        return
                elif verb == b"P":
                    if not self._serve_push(conn):
                        return
                else:
                    return  # unknown verb: drop the connection
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_get(self, conn: socket.socket) -> bool:
        oid_bytes = _recv_exact(conn, ObjectID.SIZE)
        if oid_bytes is None:
            return False
        oid = ObjectID(oid_bytes)
        view = None
        try:
            view = self.store.get_raw(oid, timeout_ms=0)
            if view is not None:
                conn.sendall(struct.pack("<Q", len(view)))
                conn.sendall(view)
            elif self.spill is not None and self.spill.contains(oid):
                with open(self.spill._path(oid), "rb") as f:
                    data = f.read()
                conn.sendall(struct.pack("<Q", len(data)))
                conn.sendall(data)
            else:
                conn.sendall(struct.pack("<Q", 0))
        finally:
            if view is not None:
                del view
                self.store.release(oid)
        return True

    def _serve_range(self, conn: socket.socket) -> bool:
        hdr = _recv_exact(conn, ObjectID.SIZE + 16)
        if hdr is None:
            return False
        oid = ObjectID(hdr[:ObjectID.SIZE])
        offset, maxlen = struct.unpack("<QQ", hdr[ObjectID.SIZE:])
        view = None
        try:
            view = self.store.get_raw(oid, timeout_ms=0)
            if view is not None:
                total = len(view)
                lo = min(offset, total)
                hi = min(lo + maxlen, total)
                conn.sendall(struct.pack("<Q", total))
                if hi > lo:
                    conn.sendall(view[lo:hi])
            elif self.spill is not None and self.spill.contains(oid):
                import os as _os
                path = self.spill._path(oid)
                total = _os.path.getsize(path)
                lo = min(offset, total)
                hi = min(lo + maxlen, total)
                conn.sendall(struct.pack("<Q", total))
                if hi > lo:
                    with open(path, "rb") as f:
                        f.seek(lo)
                        conn.sendall(f.read(hi - lo))
            else:
                conn.sendall(struct.pack("<Q", 0))
        finally:
            if view is not None:
                del view
                self.store.release(oid)
        return True

    def _serve_push(self, conn: socket.socket) -> bool:
        from .object_store import ObjectStoreFullError
        hdr = _recv_exact(conn, ObjectID.SIZE + 8)
        if hdr is None:
            return False
        oid = ObjectID(hdr[:ObjectID.SIZE])
        (length,) = struct.unpack("<Q", hdr[ObjectID.SIZE:])
        # Pushed objects must land in the SHM store (consumers poll it
        # directly — a spill-file "delivery" would be invisible to them),
        # so there is no spill fallback here: full store = status 0.
        # _drain is only legal before any payload byte was consumed; late
        # failures (seal) drop the connection instead.
        try:
            buf = self.store.create_raw(oid, length)
        except FileExistsError:
            _drain(conn, length)
            conn.sendall(b"\x01")   # already present: push is idempotent
            return True
        except ObjectStoreFullError:
            _drain(conn, length)
            conn.sendall(b"\x00")
            return True
        ok = _recv_into_exact(conn, buf)
        del buf
        if not ok:
            self.store.delete(oid)
            return False
        self.store.seal(oid)
        conn.sendall(b"\x01")
        return True

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


# per-process pool of puller connections, keyed by address (connections
# are serially reused; pulls are infrequent enough that one socket per
# peer is plenty)
_conn_pool: dict[str, socket.socket] = {}
_pool_lock = threading.Lock()


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_into_exact(conn: socket.socket, view: memoryview) -> bool:
    got = 0
    while got < len(view):
        n = conn.recv_into(view[got:])
        if n == 0:
            return False
        got += n
    return True


def fetch_object(addr: str, oid: ObjectID, local_store: SharedObjectStore,
                 spill: Optional[SpillStore] = None,
                 timeout_s: float = 30.0) -> bool:
    """Pull one object from `addr` into the local store (spill fallback
    when the local store can't hold it). Returns False if the peer does
    not have the object; raises OSError on transport failure."""
    conn = _checkout_conn(addr, timeout_s)
    ok = False
    try:
        conn.sendall(b"G" + oid.binary())
        hdr = _recv_exact(conn, 8)
        if hdr is None:
            raise OSError("peer closed during fetch")
        (length,) = struct.unpack("<Q", hdr)
        if length == 0:
            result = False
        elif local_store.contains(oid):
            _drain(conn, length)
            result = True
        else:
            result = _receive_frame(conn, oid, length, local_store, spill)
        ok = True   # healthy exchange: pool the connection
        return result
    finally:
        if ok:
            _checkin_conn(addr, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass


def push_object(addr: str, oid: ObjectID, value=None, frame=None,
                is_exception: bool = False, timeout_s: float = 30.0) -> bool:
    """Push a value (or pre-built _FramedValue) INTO the store behind
    `addr` (reference Push, object_manager.h:209). Returns True when the
    peer stored it (or already had it)."""
    from .object_store import _FramedValue
    if frame is None:
        frame = _FramedValue(value, is_exception)
    conn = _checkout_conn(addr, timeout_s)
    ok = False
    try:
        conn.sendall(b"P" + oid.binary() + struct.pack("<Q", frame.total))
        # stream the frame piecewise: no second full-size buffer
        for piece in frame.iter_wire():
            conn.sendall(piece)
        status = _recv_exact(conn, 1)
        if status is None:
            raise OSError("peer closed during push")
        ok = True
        return status == b"\x01"
    finally:
        if ok:
            _checkin_conn(addr, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass


def _checkout_conn(addr: str, timeout_s: float,
                   connect_timeout_s: Optional[float] = None,
                   ) -> socket.socket:
    with _pool_lock:
        conn = _conn_pool.pop(addr, None)
    if conn is None:
        host, port = addr.rsplit(":", 1)
        conn = socket.create_connection(
            (host, int(port)),
            timeout=connect_timeout_s or timeout_s)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    conn.settimeout(timeout_s)
    return conn


def _checkin_conn(addr: str, conn: socket.socket) -> None:
    with _pool_lock:
        if addr not in _conn_pool:
            _conn_pool[addr] = conn
            return
    try:
        conn.close()
    except OSError:
        pass


def _range_once(addr: str, oid: ObjectID, offset: int, maxlen: int,
                sink, timeout_s: float) -> Optional[int]:
    """One ranged request; `sink(view_or_bytes)` consumes the payload.
    Returns the TOTAL frame size, or None when the peer lacks the object.
    Raises OSError on transport trouble. The dial is bounded separately
    (black-holed holders must not eat the full data timeout — the caller
    sits in a synchronous ray.get loop)."""
    conn = _checkout_conn(addr, timeout_s,
                          connect_timeout_s=min(5.0, timeout_s))
    ok = False
    try:
        conn.sendall(b"R" + oid.binary() + struct.pack("<QQ", offset,
                                                       maxlen))
        hdr = _recv_exact(conn, 8)
        if hdr is None:
            raise OSError("peer closed during ranged fetch")
        (total,) = struct.unpack("<Q", hdr)
        if total == 0:
            ok = True
            return None
        want = min(maxlen, max(0, total - offset))
        left = want
        while left > 0:
            piece = conn.recv(min(1 << 20, left))
            if not piece:
                raise OSError("peer closed mid-range")
            sink(piece)
            left -= len(piece)
        ok = True
        return total
    finally:
        if ok:
            _checkin_conn(addr, conn)
        else:
            try:
                conn.close()
            except OSError:
                pass


def fetch_resilient(addrs: list[str], oid: ObjectID,
                    local_store: SharedObjectStore,
                    spill: Optional[SpillStore] = None,
                    timeout_s: float = 30.0,
                    max_rounds: int = 3) -> bool:
    """Chunked, resumable, failover pull (reference: chunked Pull with
    retry, object_manager.h:217 + pull_manager.h:49). The frame moves in
    cfg.transfer_chunk_bytes pieces; a transport error resumes from the
    last good byte against the NEXT holder; frames the local store cannot
    hold stream piecewise into the spill directory (so objects up to disk
    size cross nodes without ever fitting in shm or RAM). Returns False
    only when no holder has the object."""
    from .config import cfg
    from .object_store import ObjectStoreFullError
    if local_store.contains(oid):
        return True
    chunk = max(1 << 16, cfg.transfer_chunk_bytes)
    holders = [a for a in addrs if a]
    if not holders:
        return False

    state = {"total": None, "got": 0, "buf": None, "file": None}

    def sink(piece: bytes):
        if state["file"] is not None:
            state["file"].write(piece)
        else:
            got = state["got"]
            state["buf"][got:got + len(piece)] = piece
        state["got"] += len(piece)

    max_failures = max_rounds * len(holders)
    failures = 0          # only ERRORS consume budget, not chunk steps
    exhausted = 0
    i = 0
    done = False
    try:
        while failures < max_failures:
            addr = holders[i % len(holders)]
            try:
                if state["total"] is None:
                    # first request doubles as the size probe AND carries
                    # the first chunk (small objects stay one round trip);
                    # the prefix buffers until the destination exists
                    prefix = bytearray()
                    total = _range_once(addr, oid, 0, chunk,
                                        prefix.extend, timeout_s)
                    if total is None:
                        exhausted += 1
                        i += 1
                        if exhausted >= len(holders):
                            return False
                        continue
                    exhausted = 0
                    state["total"] = total
                    try:
                        state["buf"] = local_store.create_raw(oid, total)
                    except FileExistsError:
                        done = True   # raced: another puller created it
                        return True
                    except ObjectStoreFullError:
                        if spill is None:
                            raise
                        state["file"] = open(
                            spill._path(oid) + ".tmp", "wb")
                    sink(bytes(prefix))
                    if state["got"] < state["total"]:
                        continue
                else:
                    before = state["got"]
                    total = _range_once(addr, oid, state["got"], chunk,
                                        sink, timeout_s)
                    if total is None or state["got"] == before:
                        # holder lost the object mid-pull (eviction):
                        # others may still serve it
                        failures += 1
                        i += 1
                        continue
            except OSError:
                # transient transport trouble must not count toward the
                # all-holders-lack-it verdict
                exhausted = 0
                failures += 1
                i += 1        # failover: resume against the next holder
                if state["total"] is None and failures >= len(holders):
                    # nothing fetched yet and every holder errored once:
                    # return to the caller's 1 Hz locate/retry loop
                    # instead of burning max_rounds x timeout here
                    raise
                continue
            if state["got"] >= state["total"]:
                if state["file"] is not None:
                    state["file"].close()
                    state["file"] = None
                    import os as _os
                    _os.replace(spill._path(oid) + ".tmp",
                                spill._path(oid))
                else:
                    del state["buf"]
                    state["buf"] = None
                    local_store.seal(oid)
                done = True
                return True
        raise OSError(
            f"fetch of {oid} failed after {max_rounds} rounds over "
            f"{len(holders)} holder(s); got {state['got']} of "
            f"{state['total']} bytes")
    finally:
        if not done:
            if state["file"] is not None:
                state["file"].close()
                import os as _os
                try:   # don't leak partial multi-GB .tmp files on abort
                    _os.remove(spill._path(oid) + ".tmp")
                except OSError:
                    pass
            if state["buf"] is not None:
                del state["buf"]
                local_store.delete(oid)   # abort the unsealed create


def _receive_frame(conn, oid, length, local_store, spill) -> bool:
    from .object_store import ObjectStoreFullError
    try:
        buf = local_store.create_raw(oid, length)
    except FileExistsError:
        _drain(conn, length)
        return True
    except ObjectStoreFullError:
        if spill is None:
            raise
        data = _recv_exact(conn, length)
        if data is None:
            raise OSError("peer closed mid-frame")
        _write_spill_raw(spill, oid, data)
        return True
    ok = _recv_into_exact(conn, buf)
    del buf
    if not ok:
        local_store.delete(oid)
        raise OSError("peer closed mid-frame")
    local_store.seal(oid)
    return True


def _drain(conn: socket.socket, n: int) -> None:
    left = n
    while left > 0:
        chunk = conn.recv(min(65536, left))
        if not chunk:
            raise OSError("peer closed while draining")
        left -= len(chunk)


def _write_spill_raw(spill: SpillStore, oid: ObjectID, data: bytes) -> None:
    import os
    tmp = spill._path(oid) + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, spill._path(oid))
