"""Node-to-node object transfer: the cross-host data plane.

Reference parity: src/ray/object_manager/object_manager.h:119 (Push :209 /
Pull :217 — chunked object movement between per-node plasma stores over
gRPC) + the ownership-based object directory locating copies.

TPU-first reduction: one data server per node-local store serving whole
frames over a raw TCP socket (objects move between HOSTS over DCN — the
hot tensor path inside a slice is XLA collectives over ICI, so this
service carries control-plane-adjacent payloads: task args/results,
checkpoints, datasets blocks). A puller asks the head for locations
(the directory tracks which node produced each object), dials the owner's
data server, and writes the received frame into its LOCAL store — after
which the object is served locally and the head records the new copy.

Wire protocol (per request, connections are reused):
  -> 16B object id
  <- 8B little-endian frame length (0 = not here) + frame bytes
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import Optional

from .ids import ObjectID
from .object_store import SharedObjectStore, SpillStore


class ObjectDataServer:
    """Serves frames out of a local store (+ its spill dir)."""

    def __init__(self, store: SharedObjectStore,
                 spill: Optional[SpillStore] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.spill = spill
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(32)
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="rtpu-objdata").start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                oid_bytes = _recv_exact(conn, ObjectID.SIZE)
                if oid_bytes is None:
                    return
                oid = ObjectID(oid_bytes)
                view = None
                try:
                    view = self.store.get_raw(oid, timeout_ms=0)
                    if view is not None:
                        conn.sendall(struct.pack("<Q", len(view)))
                        conn.sendall(view)
                    elif self.spill is not None and self.spill.contains(oid):
                        with open(self.spill._path(oid), "rb") as f:
                            data = f.read()
                        conn.sendall(struct.pack("<Q", len(data)))
                        conn.sendall(data)
                    else:
                        conn.sendall(struct.pack("<Q", 0))
                finally:
                    if view is not None:
                        del view
                        self.store.release(oid)
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass


# per-process pool of puller connections, keyed by address (connections
# are serially reused; pulls are infrequent enough that one socket per
# peer is plenty)
_conn_pool: dict[str, socket.socket] = {}
_pool_lock = threading.Lock()


def _recv_exact(conn: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_into_exact(conn: socket.socket, view: memoryview) -> bool:
    got = 0
    while got < len(view):
        n = conn.recv_into(view[got:])
        if n == 0:
            return False
        got += n
    return True


def fetch_object(addr: str, oid: ObjectID, local_store: SharedObjectStore,
                 spill: Optional[SpillStore] = None,
                 timeout_s: float = 30.0) -> bool:
    """Pull one object from `addr` into the local store (spill fallback
    when the local store can't hold it). Returns False if the peer does
    not have the object; raises OSError on transport failure."""
    with _pool_lock:
        conn = _conn_pool.pop(addr, None)
    try:
        if conn is None:
            host, port = addr.rsplit(":", 1)
            conn = socket.create_connection((host, int(port)),
                                            timeout=timeout_s)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(timeout_s)
        conn.sendall(oid.binary())
        hdr = _recv_exact(conn, 8)
        if hdr is None:
            raise OSError("peer closed during fetch")
        (length,) = struct.unpack("<Q", hdr)
        if length == 0:
            result = False
        elif local_store.contains(oid):
            _drain(conn, length)
            result = True
        else:
            result = _receive_frame(conn, oid, length, local_store, spill)
        # healthy exchange: keep the connection for the next pull
        with _pool_lock:
            if addr not in _conn_pool:
                _conn_pool[addr] = conn
                conn = None
        return result
    finally:
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass


def _receive_frame(conn, oid, length, local_store, spill) -> bool:
    from .object_store import ObjectStoreFullError
    try:
        buf = local_store.create_raw(oid, length)
    except FileExistsError:
        _drain(conn, length)
        return True
    except ObjectStoreFullError:
        if spill is None:
            raise
        data = _recv_exact(conn, length)
        if data is None:
            raise OSError("peer closed mid-frame")
        _write_spill_raw(spill, oid, data)
        return True
    ok = _recv_into_exact(conn, buf)
    del buf
    if not ok:
        local_store.delete(oid)
        raise OSError("peer closed mid-frame")
    local_store.seal(oid)
    return True


def _drain(conn: socket.socket, n: int) -> None:
    left = n
    while left > 0:
        chunk = conn.recv(min(65536, left))
        if not chunk:
            raise OSError("peer closed while draining")
        left -= len(chunk)


def _write_spill_raw(spill: SpillStore, oid: ObjectID, data: bytes) -> None:
    import os
    tmp = spill._path(oid) + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, spill._path(oid))
