"""Wire-protocol versioning for the control plane.

The reference versions every cross-process message through generated
protobuf schemas (reference: src/ray/protobuf/*.proto, 36 files) so peers
from different releases fail loudly instead of mis-parsing each other. This
runtime deliberately keeps pickled dataclasses on an authkeyed channel
(single-language cluster, no cross-language marshalling) — but the
cross-VERSION guarantee still matters: a worker, node agent, or driver
built from a different checkout must be rejected at the handshake, not
crash mid-job on a missing dataclass field.

Every register message (`register`, `register_node`, `register_driver`)
carries ``pv``; the head compares it against its own PROTOCOL_VERSION and
refuses mismatches with a structured error the peer surfaces to the user.
Bump PROTOCOL_VERSION whenever a control-message shape, TaskSpec/ActorSpec
field, or the object-store wire framing changes incompatibly.

GCS snapshots embed SNAPSHOT_SCHEMA_VERSION the same way so
``init(resume_from=...)`` across an incompatible upgrade fails with a
clear message instead of restoring garbage state (reference analog: the
GCS table schema version in gcs_storage).
"""
from __future__ import annotations

# Bump on any incompatible control-plane or store-framing change.
# v2: submit/actor_call imply the submitter's interest in return_ids
#     (no per-task ref_add), batched ref_drops, positional-tuple
#     TaskSpec/ActorSpec pickling (+ max_calls field).
PROTOCOL_VERSION = 2

# Bump on any incompatible change to the sqlite snapshot contents.
# v2: named-actor keys are namespace-qualified ("ns/name"); v1 snapshots
#     are migrated on restore (unqualified names -> "default/name").
SNAPSHOT_SCHEMA_VERSION = 2


class ProtocolMismatchError(ConnectionError):
    """Peer speaks a different wire-protocol version than this process."""


def check_peer_version(peer_pv, who: str) -> None:
    """Raise ProtocolMismatchError unless `peer_pv` matches ours.

    `who` names the peer for the error message ("worker", "node agent",
    "driver client"). Peers that predate versioning send no ``pv`` at
    all (None) — rejected with the same message, since they are by
    definition an older build.
    """
    if peer_pv != PROTOCOL_VERSION:
        raise ProtocolMismatchError(
            f"{who} speaks wire-protocol version {peer_pv!r}, this process "
            f"speaks {PROTOCOL_VERSION}; mixing builds in one cluster is "
            f"not supported — restart the cluster from one checkout")
