"""Wire-protocol versioning for the control plane.

The reference versions every cross-process message through generated
protobuf schemas (reference: src/ray/protobuf/*.proto, 36 files) so peers
from different releases fail loudly instead of mis-parsing each other. This
runtime deliberately keeps pickled dataclasses on an authkeyed channel
(single-language cluster, no cross-language marshalling) — but the
cross-VERSION guarantee still matters: a worker, node agent, or driver
built from a different checkout must be rejected at the handshake, not
crash mid-job on a missing dataclass field.

Every register message (`register`, `register_node`, `register_driver`)
carries ``pv``; the head compares it against its own PROTOCOL_VERSION and
refuses mismatches with a structured error the peer surfaces to the user.
Bump PROTOCOL_VERSION whenever a control-message shape, TaskSpec/ActorSpec
field, or the object-store wire framing changes incompatibly.

GCS snapshots embed SNAPSHOT_SCHEMA_VERSION the same way so
``init(resume_from=...)`` across an incompatible upgrade fails with a
clear message instead of restoring garbage state (reference analog: the
GCS table schema version in gcs_storage).

The batch frame (v3)
--------------------
Every peer may coalesce consecutive control messages into one frame::

    {"t": "batch", "msgs": [msg, msg, ...]}

with the contained messages processed strictly in list order — a batch is
a transport optimization, never a reordering point, so per-connection
FIFO invariants (func_def before the submits that reference it, ref_add
before a later ref_drop) survive batching unchanged. Senders fill batches
through an adaptive flush buffer (core/worker.py WorkerRuntime.send_async)
drained combining-lock style — no flusher thread: an async sender appends
and try-acquires the connection, shipping its own message immediately
when uncontended, while under a burst the first sender becomes the
shipper and everything appended during its pipe write coalesces into
large frames (one pickle + one syscall amortized over N; every holder
re-checks the buffer after releasing, so nothing strands). Synchronous
messages (ensure/blocked/rpc/...) drain the buffer in-order and ship
immediately with it. Receivers handle a batch with one scheduler lock
acquisition and one deferred scheduling pass (head: Runtime._handle_batch;
workers splice batches into their ordered backlog). v2 peers know none of
this, so v3 is a handshake-incompatible bump.
"""
from __future__ import annotations

# Bump on any incompatible control-plane or store-framing change.
# v2: submit/actor_call imply the submitter's interest in return_ids
#     (no per-task ref_add), batched ref_drops, positional-tuple
#     TaskSpec/ActorSpec pickling (+ max_calls field).
# v3: client->head "batch" frames (adaptive flush buffer, see module
#     docstring); multi-oid "ensure" remains but is now sent once up
#     front for every missing ref of a bulk get/wait.
# v4: sealed ring channels (dag/channel.py). No NEW control frames, but
#     two cross-build store contracts changed: the native store gained
#     os_chan_get (stop-aware blocking get — an old-build worker's
#     libobjstore lacks the symbol, and channel consumers rely on its
#     stop-wake semantics), and serve's handle_request_streaming grew a
#     `chan` argument whose dict reply an old-build handle would treat
#     as a stream id. Same-build clusters only, as ever.
# v5: flight-recorder collection frames (core/flight.py): the head may
#     send "flight_pull" {nonce, stats_only} to any worker, which
#     answers "flight_ring" {nonce, snap} carrying its event-ring
#     snapshot + (mono_ns, wall_ns) clock pair for offset estimation.
#     An old-build worker would drop flight_pull on the floor and the
#     head would wait out its collection timeout per pull — reject at
#     the handshake instead.
# v6: stall-doctor live-stack collection frames (core/stacks.py): the
#     head may send "stack_dump" {nonce, no_stacks} to any worker OR
#     driver, answered with "stack_reply" {nonce, snap} carrying every
#     thread's frames plus wait-beacon/task annotations. Like
#     flight_pull, the reply is built on the per-connection recv
#     threads, so a dump succeeds while the target's executor threads
#     are wedged; an old-build peer would drop the frame and stall
#     every stack/hang report for its full collection timeout — reject
#     at the handshake instead.
# v7: shared-directory frames (core/directory.py): any peer may send
#     "dir_update" {d, put, drop} (async merge into a head-side named
#     hint map, owner-stamped and swept on disconnect) and "dir_query"
#     {d, keys, reply_oid} (answered inline on the head recv thread via
#     the rpc_reply plumbing). The serve front door rides these for its
#     shared proxy route table and the cluster-wide prefix-cache
#     directory; an old-build head would drop both frames and every
#     proxy route refresh / prefix lookup would wait out its timeout —
#     reject at the handshake instead.
PROTOCOL_VERSION = 7

# Bump on any incompatible change to the sqlite snapshot contents.
# v2: named-actor keys are namespace-qualified ("ns/name"); v1 snapshots
#     are migrated on restore (unqualified names -> "default/name").
SNAPSHOT_SCHEMA_VERSION = 2


class ProtocolMismatchError(ConnectionError):
    """Peer speaks a different wire-protocol version than this process."""


def check_peer_version(peer_pv, who: str) -> None:
    """Raise ProtocolMismatchError unless `peer_pv` matches ours.

    `who` names the peer for the error message ("worker", "node agent",
    "driver client"). Peers that predate versioning send no ``pv`` at
    all (None) — rejected with the same message, since they are by
    definition an older build.
    """
    if peer_pv != PROTOCOL_VERSION:
        raise ProtocolMismatchError(
            f"{who} speaks wire-protocol version {peer_pv!r}, this process "
            f"speaks {PROTOCOL_VERSION}; mixing builds in one cluster is "
            f"not supported — restart the cluster from one checkout")
