"""Head-side pub/sub with long-poll subscribers.

Reference parity: the GCS pubsub module (src/ray/pubsub/publisher.h:300
Publisher, subscriber.h:73 SubscriberChannel) — long-poll based fan-out of
control-plane notifications (actor/node/job lifecycle) to any process in
the cluster.

Design: the head keeps a bounded per-channel ring of (seq, message); a
subscriber long-polls with its cursor via the worker→head RPC channel and
receives everything newer (or blocks until something arrives / timeout).
Cursor-based polling makes delivery at-least-once and restart-safe; a
subscriber that lags more than the ring size observes a gap (returned in
the reply) rather than silently losing its place — same contract as the
reference's publisher buffer eviction.

Built-in channels (published by the runtime):
  actors  — {"actor_id", "state": "alive"|"restarting"|"dead", "name", ...}
  nodes   — {"node_id", "event": "added"|"removed", "name"}
  jobs    — {"job_id", "status"}
"""
from __future__ import annotations

import threading
import time
from collections import deque


class Publisher:
    RING = 1000

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._channels: dict[str, deque] = {}
        self._seq: dict[str, int] = {}

    def publish(self, channel: str, message: dict) -> int:
        with self._lock:
            ring = self._channels.setdefault(
                channel, deque(maxlen=self.RING))
            seq = self._seq.get(channel, 0) + 1
            self._seq[channel] = seq
            ring.append((seq, dict(message, _seq=seq, _ts=time.time())))
            self._cv.notify_all()
            return seq

    def poll(self, channel: str, cursor: int = 0,
             timeout_s: float = 20.0) -> dict:
        """Messages with seq > cursor; blocks up to timeout_s when none.
        Returns {"cursor", "messages", "gap"} — gap=True when the ring
        evicted messages the caller never saw."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._lock:
            while True:
                ring = self._channels.get(channel, ())
                msgs = [m for s, m in ring if s > cursor]
                if msgs:
                    oldest = ring[0][0]
                    return {"cursor": msgs[-1]["_seq"], "messages": msgs,
                            "gap": cursor + 1 < oldest}
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return {"cursor": cursor, "messages": [], "gap": False}
                self._cv.wait(remain)


class Subscriber:
    """Client-side cursor wrapper; works on the head (direct) and in
    workers/driver clients (via the pubsub_poll head RPC)."""

    def __init__(self, channel: str):
        from . import runtime as rt_mod
        self.channel = channel
        self.cursor = 0
        rt = rt_mod.get_runtime_if_exists()
        if rt is None:
            raise RuntimeError("ray_tpu.init() has not been called")
        self._rt = rt

    def poll(self, timeout_s: float = 20.0) -> list[dict]:
        rt = self._rt
        if hasattr(rt, "pubsub"):  # head
            reply = rt.pubsub.poll(self.channel, self.cursor, timeout_s)
        else:
            reply = rt._rpc("pubsub_poll", self.channel, self.cursor,
                            timeout_s, timeout=timeout_s + 15.0)
        self.cursor = reply["cursor"]
        return reply["messages"]
