"""ObjectRef — a future handle to a value in the object store.

Reference parity: python/ray/_raylet.pyx ObjectRef + ownership semantics from
src/ray/core_worker/reference_count.h:73. Ownership here is simplified: the
head process (driver) is the owner of all object metadata (the directory in
core/runtime.py); the payload lives in the node-shared memory store. Lineage
(the producing TaskSpec) is kept by the head until the object is pinned or
freed, enabling reconstruction after eviction — the analog of
object_recovery_manager.h:43.

Reference counting (reference_count.h:73 analog, head-centric): every live
ObjectRef registers interest with its process runtime (__init__/__del__);
pickling a ref places a transfer pin (`ref_serialized`) that the receiving
process's deserialization releases, so an object can never be freed while a
copy of its ref is on the wire. When the head sees no interested process,
no transfer pins and no pending producer, it frees the payload, spill file
and directory entry (the fix for unbounded driver memory).
"""
from __future__ import annotations

import threading
from typing import Any

from .ids import ObjectID

_pending_runtime = None

# When serializing a value INTO the object store, inner refs must outlive
# the transfer: they become containment edges (the outer object holds
# interest in the inner) instead of one-shot transfer pins. put paths
# activate this via capture_serialized_refs().
_capture = threading.local()


class capture_serialized_refs:
    """Context manager collecting ObjectIDs pickled within; while active,
    __reduce__ records the id here instead of taking a transfer pin."""

    def __enter__(self):
        self.ids: list[ObjectID] = []
        stack = getattr(_capture, "stack", None)
        if stack is None:
            stack = _capture.stack = []
        stack.append(self.ids)
        return self.ids

    def __exit__(self, *exc_info):
        _capture.stack.pop()
        return False


def _capture_target():
    stack = getattr(_capture, "stack", None)
    return stack[-1] if stack else None


# Deserializing FROM the store (or a spill file): inner refs there are
# containment-protected, so they must register plain interest WITHOUT
# consuming a transfer pin — a stored copy's deserialize must never steal
# the pin of an unrelated in-flight message transfer.
class loading_stored_refs:
    def __enter__(self):
        _capture.loading = getattr(_capture, "loading", 0) + 1

    def __exit__(self, *exc_info):
        _capture.loading -= 1
        return False


def _loading_stored() -> bool:
    return getattr(_capture, "loading", 0) > 0


def _get_runtime():
    from . import runtime as rt
    r = rt.get_runtime_if_exists()
    if r is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return r


def _tracking_runtime():
    from . import runtime as rt
    return rt.get_runtime_if_exists()


class ObjectRef:
    __slots__ = ("_id", "_tracked", "__weakref__")

    def __init__(self, oid: ObjectID, _transfer: bool = False):
        self._id = oid
        self._tracked = False
        rt = _tracking_runtime()
        if rt is not None:
            try:
                rt.ref_created(oid, _transfer)
                self._tracked = True
            except Exception:
                pass  # runtime torn down mid-construct; ref untracked

    def __del__(self):
        if getattr(self, "_tracked", False):
            try:
                rt = _tracking_runtime()
                if rt is not None:
                    rt.ref_deleted(self._id)
            except Exception:
                pass  # interpreter shutdown / runtime gone

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self.hex()[:16]})"

    def __reduce__(self):
        cap = _capture_target()
        if cap is not None:
            cap.append(self._id)
        else:
            rt = _tracking_runtime()
            if rt is not None:
                try:
                    rt.ref_serialized(self._id)
                except Exception:
                    pass  # runtime gone: pickling for a dead cluster
        return (_deserialize_ref, (self._id.binary(),))

    # Allow `await ref` inside async actors. One shared wait_sealed
    # multiplexer thread resolves every awaited ref (core/completion.py)
    # — no per-ref executor hop, and await latency stays flat as the
    # in-flight count grows. get_running_loop (not the deprecated
    # get_event_loop) so awaiting never mis-binds a foreign loop.
    def __await__(self):
        import asyncio

        from .completion import async_future
        loop = asyncio.get_running_loop()
        return async_future(self, loop).__await__()

    def future(self):
        from .completion import sync_future
        return sync_future(self)


def _deserialize_ref(binary: bytes) -> ObjectRef:
    return ObjectRef(ObjectID(binary), _transfer=not _loading_stored())
