"""ObjectRef — a future handle to a value in the object store.

Reference parity: python/ray/_raylet.pyx ObjectRef + ownership semantics from
src/ray/core_worker/reference_count.h:73. Ownership here is simplified: the
head process (driver) is the owner of all object metadata (the directory in
core/runtime.py); the payload lives in the node-shared memory store. Lineage
(the producing TaskSpec) is kept by the head until the object is pinned or
freed, enabling reconstruction after eviction — the analog of
object_recovery_manager.h:43.
"""
from __future__ import annotations

from typing import Any

from .ids import ObjectID

_pending_runtime = None


def _get_runtime():
    from . import runtime as rt
    r = rt.get_runtime_if_exists()
    if r is None:
        raise RuntimeError("ray_tpu.init() has not been called")
    return r


class ObjectRef:
    __slots__ = ("_id", "__weakref__")

    def __init__(self, oid: ObjectID):
        self._id = oid

    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self):
        return hash(self._id)

    def __repr__(self):
        return f"ObjectRef({self.hex()[:16]})"

    def __reduce__(self):
        return (_deserialize_ref, (self._id.binary(),))

    # Allow `await ref` inside async actors.
    def __await__(self):
        from .api import get as _get
        import asyncio

        def _resolve():
            return _get(self)

        loop = asyncio.get_event_loop()
        return loop.run_in_executor(None, _resolve).__await__()

    def future(self):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _resolve():
            from .api import get as _get
            try:
                fut.set_result(_get(self))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        import threading
        threading.Thread(target=_resolve, daemon=True).start()
        return fut


def _deserialize_ref(binary: bytes) -> ObjectRef:
    return ObjectRef(ObjectID(binary))
