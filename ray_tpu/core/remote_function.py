"""@ray_tpu.remote functions.

Reference parity: python/ray/remote_function.py:484 (RemoteFunction._remote)
— options handling, lazy pickling of the function, arg inlining vs
put-in-store threshold, and submission through the runtime.
"""
from __future__ import annotations

import hashlib
from typing import Any

import cloudpickle
import numpy as np

from .ids import ObjectID, TaskID
from .ref import ObjectRef
from .task_spec import TaskSpec, validate_resources

# args bigger than this are moved to the object store instead of riding the
# control-plane socket (reference: RayConfig max_direct_call_object_size)
INLINE_ARG_LIMIT = 100_000

_DEFAULT_TASK_OPTS = dict(
    num_cpus=1.0, num_tpus=0.0, resources=None, num_returns=1,
    max_retries=3, retry_exceptions=False, name=None,
    scheduling_strategy="DEFAULT", placement_group=None,
    placement_group_bundle_index=-1, _node_id=None, _node_soft=False,
    runtime_env=None, label_selector=None, max_calls=0,
)


def prepare_runtime_env(rt, renv: dict | None) -> dict | None:
    """Validate + pack a runtime_env option into its wire form, registering
    blobs with the head (zips content-cached, registration idempotent)."""
    if not renv:
        return None
    from . import runtime_env as renv_mod
    prepared = renv_mod.prepare(renv, rt.register_renv)
    return prepared or None


def _trace_ctx():
    """Submitter's trace context for the outgoing spec (None when tracing
    is off — util/tracing.py)."""
    from ..util.tracing import context_for_submit
    return context_for_submit()


def _runtime():
    from . import runtime as rt
    r = rt.get_runtime_if_exists()
    if r is None:
        raise RuntimeError(
            "ray_tpu.init() must be called before using .remote()")
    return r


def prepare_args(rt, args: tuple, kwargs: dict):
    """Replace large array-like args with store refs; collect top-level refs
    as scheduling dependencies."""
    def conv(a):
        if isinstance(a, np.ndarray) and a.nbytes > INLINE_ARG_LIMIT:
            return rt.put(a, pin=False)
        return a

    args = tuple(conv(a) for a in args)
    kwargs = {k: conv(v) for k, v in kwargs.items()}
    deps = [a.id() for a in args if isinstance(a, ObjectRef)]
    deps += [v.id() for v in kwargs.values() if isinstance(v, ObjectRef)]
    blob = cloudpickle.dumps((args, kwargs))
    return blob, deps


def resolve_strategy(opts: dict) -> dict:
    """Translate scheduling_strategy objects into spec fields."""
    from ..util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)
    out = dict(pg_id=None, pg_bundle_index=-1, node_affinity=None,
               node_affinity_soft=False, scheduling_strategy="DEFAULT")
    strat = opts.get("scheduling_strategy")
    if isinstance(strat, PlacementGroupSchedulingStrategy):
        out["pg_id"] = strat.placement_group.id
        out["pg_bundle_index"] = strat.placement_group_bundle_index
    elif isinstance(strat, NodeAffinitySchedulingStrategy):
        out["node_affinity"] = bytes.fromhex(strat.node_id)
        out["node_affinity_soft"] = strat.soft
    elif strat in ("DEFAULT", "SPREAD", None):
        out["scheduling_strategy"] = strat or "DEFAULT"
    else:
        raise ValueError(f"unknown scheduling strategy {strat!r}")
    if opts.get("placement_group") is not None:
        pg = opts["placement_group"]
        out["pg_id"] = pg.id
        out["pg_bundle_index"] = opts.get("placement_group_bundle_index", -1)
    if opts.get("_node_id") is not None:
        out["node_affinity"] = bytes.fromhex(opts["_node_id"])
        out["node_affinity_soft"] = opts.get("_node_soft", False)
    return out


class RemoteFunction:
    def __init__(self, fn, opts: dict):
        self._fn = fn
        self._opts = {**_DEFAULT_TASK_OPTS, **opts}
        self._blob: bytes | None = None
        self._fid: str | None = None
        self.__name__ = getattr(fn, "__name__", "remote_fn")

    def options(self, **kwargs) -> "RemoteFunction":
        bad = set(kwargs) - set(_DEFAULT_TASK_OPTS)
        if bad:
            raise ValueError(f"unknown options: {sorted(bad)}")
        rf = RemoteFunction(self._fn, {**self._opts, **kwargs})
        rf._blob, rf._fid = self._blob, self._fid
        return rf

    def _ensure_registered(self, rt):
        if self._blob is None:
            self._blob = cloudpickle.dumps(self._fn)
            self._fid = hashlib.sha1(self._blob).hexdigest()[:16]
        # once per runtime, not once per call: register_function takes the
        # (contended) runtime lock, which burst submission must not pay
        # per task. Reconnecting drivers re-ship from their own blob table
        # (client.py _fid_blobs), so skipping here stays correct across
        # head restarts; a re-init creates a NEW runtime object. Weakref:
        # this cache must not pin a shut-down runtime (and its store
        # mapping) alive for the life of a module-level @remote function.
        import weakref
        reg = getattr(self, "_reg_rt", None)
        if reg is None or reg() is not rt:
            rt.register_function(self._fid, self._blob)
            try:
                self._reg_rt = weakref.ref(rt)
            except TypeError:
                self._reg_rt = None  # unweakrefable runtime (test double)

    def remote(self, *args, **kwargs) -> Any:
        rt = _runtime()
        self._ensure_registered(rt)
        o = self._opts
        blob, deps = prepare_args(rt, args, kwargs)
        res = validate_resources({
            "CPU": o["num_cpus"], "TPU": o["num_tpus"],
            **(o["resources"] or {})})
        strat = resolve_strategy(o)
        nret = o["num_returns"]
        dynamic = nret == "dynamic"
        if dynamic:
            nret = 1  # one ref resolving to a list of per-item refs
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            func_id=self._fid,
            name=o["name"] or self.__name__,
            args_blob=blob,
            dep_oids=deps,
            return_ids=[ObjectID.from_random() for _ in range(nret)],
            resources=res,
            retries_left=max(0, o["max_retries"]),
            retry_exceptions=bool(o["retry_exceptions"]),
            runtime_env=prepare_runtime_env(rt, o["runtime_env"]),
            dynamic_returns=dynamic,
            trace_ctx=_trace_ctx(),
            label_selector=(dict(o["label_selector"])
                            if o["label_selector"] else None),
            max_calls=max(0, o["max_calls"]),
            namespace=getattr(rt, "namespace", None),
            **strat,
        )
        refs = rt.submit_task(spec)
        if nret == 0:
            return None
        return refs[0] if nret == 1 else refs

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; "
            f"use {self.__name__}.remote()")
