"""Head-process runtime: object directory, scheduler, worker pool, actors.

This module is the TPU-build's merged equivalent of three reference
components, collapsed into the driver process because a TPU host runs one
framework instance per node and cross-node control travels over the same
socket fabric either way:

  - GCS (global control plane): node/actor/PG/job tables, named actors —
    reference: src/ray/gcs/gcs_server/gcs_server.h:91, gcs_actor_manager.h:352,
    gcs_placement_group_mgr.h:232.
  - Raylet (per-node scheduler + worker pool): lease/dispatch of tasks onto
    workers, dependency management, resource accounting — reference:
    src/ray/raylet/node_manager.h:124, local_task_manager.h:60,
    scheduling/cluster_task_manager.h:44, worker_pool.h:283.
  - Core-worker ownership bookkeeping: object directory with lineage for
    reconstruction — reference: src/ray/core_worker/task_manager.h:175,
    reference_count.h:73, object_recovery_manager.h:43.

Transport: `multiprocessing.connection` unix sockets (control plane) +
the node-shared mmap object store (data plane, core/object_store.py).
Scheduling policy is hybrid pack-then-spread like the reference's
HybridSchedulingPolicy (scheduling/policy/hybrid_scheduling_policy.h:50):
prefer the head/local node until utilization passes a threshold, then pick
the least-utilized feasible node; SPREAD strategy round-robins.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import traceback
from collections import OrderedDict, deque
from multiprocessing.connection import Connection, Listener
from typing import Any, Optional

import cloudpickle

from .. import exceptions as exc
from . import flight
from . import stacks
from .directory import DirectoryService
from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from .object_store import GetTimeoutError as StoreTimeout
from .object_store import ObjectStoreFullError, SharedObjectStore, SpillStore
from .ref import ObjectRef
from .protocol import (PROTOCOL_VERSION, ProtocolMismatchError,
                       check_peer_version)
from .task_spec import ActorSpec, TaskSpec

# directory states
PENDING, READY, FAILED, SPILLED = 0, 1, 2, 3

_runtime: Optional["Runtime"] = None
_runtime_lock = threading.Lock()


def get_runtime_if_exists() -> Optional["Runtime"]:
    return _runtime


def set_runtime(rt) -> None:
    global _runtime
    _runtime = rt


class NodeInfo:
    def __init__(self, node_id: NodeID, resources: dict[str, float],
                 labels: dict[str, str] | None = None, name: str = ""):
        self.node_id = node_id
        self.resources_total = dict(resources)
        self.resources_avail = dict(resources)
        self.labels = labels or {}
        self.name = name
        self.alive = True
        self.workers: set[str] = set()
        # set for agent-backed nodes (a node_agent process joined over TCP);
        # worker spawn/kill on this node routes through the agent
        self.agent: Optional["_AgentHandle"] = None
        # cross-node data plane (object_transfer.py): the node's data-server
        # address, and whether it runs its OWN store (no shared /dev/shm —
        # objects move via fetch, RPC replies via the control conn)
        self.data_addr: Optional[str] = None
        self.own_store = False
        # allow one worker per CPU plus headroom for zero-cpu tasks
        self.max_workers = int(resources.get("CPU", 1)) + 4

    def utilization(self) -> float:
        tot = self.resources_total.get("CPU", 0)
        if tot <= 0:
            return 1.0
        return 1.0 - self.resources_avail.get("CPU", 0) / tot


class WorkerInfo:
    def __init__(self, wid: str, node_id: NodeID, proc, tpu: bool):
        self.wid = wid
        self.node_id = node_id
        self.proc = proc
        self.tpu = tpu
        self.conn: Optional[Connection] = None
        self.send_lock = threading.Lock()
        self.state = "starting"          # starting|idle|busy|actor|dead
        self.current: Optional[TaskSpec] = None
        # pipelined (spec, nonce) already SENT to the worker behind
        # `current` (reference analog: lease reuse / owned-task pipelining
        # on the direct task transport). The worker's single-thread
        # executor runs them FIFO; the head promotes on each done message.
        # Steals name the per-dispatch nonce, not the task id, so a stale
        # steal can never skip a later re-dispatch of the same task.
        self.queued: deque = deque()
        self.send_seq = 0
        self.funcs: set[str] = set()
        # runtime-env dedication: a worker that applied env E only runs
        # env-E work (reference worker_pool.h matching semantics)
        self.env_hash: Optional[str] = None
        self.actor_id: Optional[ActorID] = None
        self.holding: dict[str, float] = {}   # node resources acquired
        self.holding_bundle: tuple | None = None  # (pg_id, idx, res)
        self.blocked = False

    def send(self, msg) -> bool:
        c = self.conn
        if c is None or self.state == "dead":
            return False
        try:
            with self.send_lock:
                c.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False


def host_ip() -> str:
    """Best-effort externally-dialable IP of this host (connected-UDP-socket
    trick; gethostbyname(hostname) commonly resolves to loopback)."""
    import socket
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))  # no packets sent
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def build_worker_env(*, store_path: str, head_addr: str, head_family: str,
                     authkey_hex: str, wid: str, node_id_hex: str,
                     tpu: bool, spill_dir: str = "",
                     own_store: bool = False) -> dict:
    """Environment for a `python -m ray_tpu.core.worker` process — the ONE
    definition shared by the head's local pool and node agents, so worker
    behavior cannot drift by host."""
    env = dict(os.environ)
    paths = [p for p in sys.path if p] + [env.get("PYTHONPATH", "")]
    if not tpu:
        # shadow the image's sitecustomize (imports jax+TPU plugin, ~2s)
        # for workers that will never touch the accelerator; pin them to
        # the cpu platform
        boot = os.path.join(os.path.dirname(__file__), "_worker_boot")
        paths.insert(0, boot)
        env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(paths)
    # programmatic cfg.override()s made in the driver ship as RTPU_* env
    # to workers SPAWNED AFTER the override (the reference ships RAY_*
    # system config the same way). Already-running workers keep their
    # values — protocols that cross processes must compose with mixed
    # settings (e.g. collective payloads declare inline vs store-backed
    # per contribution)
    from .config import cfg as _cfg
    for name, val in _cfg.overrides_for_env().items():
        env[name] = val
    env["RTPU_STORE_PATH"] = store_path
    if spill_dir:
        env["RTPU_SPILL_DIR"] = spill_dir
    if own_store:
        # node-local store: object misses resolve via locate+fetch and RPC
        # replies arrive over the control conn (object_transfer.py)
        env["RTPU_OWN_STORE"] = "1"
    env["RTPU_HEAD_ADDR"] = head_addr
    if head_family != "AF_UNIX":
        env["RTPU_HEAD_FAMILY"] = head_family
    env["RTPU_AUTHKEY"] = authkey_hex
    env["RTPU_WORKER_ID"] = wid
    env["RTPU_NODE_ID"] = node_id_hex
    return env


class _AgentHandle:
    """Head-side handle on a node_agent control connection (the raylet-client
    analog, reference: raylet_client/raylet_client.h — here the head asks the
    agent to fork/kill workers instead of leasing from a local pool)."""

    def __init__(self, conn: Connection):
        self.conn = conn
        self.send_lock = threading.Lock()

    def send(self, msg) -> bool:
        try:
            with self.send_lock:
                self.conn.send(msg)
            return True
        except (OSError, ValueError, BrokenPipeError):
            return False


class _RemoteProc:
    """Process handle for a worker living under a node agent: mirrors the
    subprocess.Popen surface the runtime uses (pid/kill/terminate/wait/poll),
    delegating kills to the agent and completing on agent exit reports."""

    def __init__(self, agent: _AgentHandle, wid: str):
        self._agent = agent
        self._wid = wid
        self.pid: int | None = None
        self.returncode: int | None = None
        self._exited = threading.Event()

    def kill(self):
        self._agent.send({"t": "kill_worker", "wid": self._wid})

    terminate = kill

    def wait(self, timeout: float | None = None):
        if not self._exited.wait(timeout):
            raise subprocess.TimeoutExpired(f"agent-worker {self._wid}",
                                            timeout)
        return self.returncode

    def poll(self):
        return self.returncode

    def mark_exited(self, rc: int | None):
        self.returncode = rc if rc is not None else -1
        self._exited.set()


class _ExternalProc:
    """Proc shim for driver clients: the head supervises but never owns the
    process — kill/wait are no-ops beyond state tracking."""

    def __init__(self, pid: int):
        self.pid = pid

    def kill(self):
        pass

    def wait(self, timeout: float | None = None):
        return 0

    def poll(self):
        return None


class DirEntry:
    # `locations` (node-id hexes known to hold a copy) stays None on
    # single-host clusters (object_transfer.py data plane)
    __slots__ = ("state", "lineage", "error_brief", "locations")

    def __init__(self, state=PENDING, lineage: TaskSpec | None = None):
        self.state = state
        self.lineage = lineage
        self.error_brief: str | None = None
        self.locations: set[str] | None = None

    def add_location(self, node_hex: str) -> None:
        if self.locations is None:
            self.locations = set()
        self.locations.add(node_hex)


class ActorInfo:
    def __init__(self, spec: ActorSpec):
        self.spec = spec
        self.state = "pending"           # pending|alive|restarting|dead
        self.wid: Optional[str] = None
        self.restarts_left = spec.max_restarts
        self.queue: deque[TaskSpec] = deque()
        self.running: dict[TaskID, TaskSpec] = {}
        self.seq = 0
        self.death_cause: Optional[str] = None


class BundleState:
    def __init__(self, index: int, resources: dict[str, float]):
        self.index = index
        self.resources = dict(resources)
        self.avail = dict(resources)
        self.node_id: Optional[NodeID] = None


class PlacementGroupState:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict[str, float]],
                 strategy: str, name: str = "",
                 same_label: str | None = None,
                 bundle_selectors: list[dict | None] | None = None):
        self.pg_id = pg_id
        self.bundles = [BundleState(i, b) for i, b in enumerate(bundles)]
        self.strategy = strategy
        self.name = name
        # same_label: every bundle must land on nodes sharing ONE value of
        # this node-label key — how whole TPU slices (ICI domains) are
        # gang-reserved (reference encodes this as TPU-{pod}-head resources,
        # _private/accelerators/tpu.py:110).
        self.same_label = same_label
        # per-bundle exact-match node label requirements (or None)
        self.bundle_selectors = list(bundle_selectors or [])
        self.state = "pending"           # pending|created|removed
        self.ready_event = threading.Event()


def _placement_key(spec) -> tuple:
    """Everything node selection + worker acquisition depend on. Two specs
    with equal keys place identically against identical cluster state."""
    from .runtime_env import env_hash
    sel = getattr(spec, "label_selector", None)
    return (tuple(sorted(spec.resources.items())), spec.pg_id,
            spec.pg_bundle_index, spec.node_affinity,
            spec.node_affinity_soft, spec.scheduling_strategy,
            tuple(sorted(sel.items())) if sel else None,
            env_hash(spec.runtime_env))


class _PendingQueues:
    """Pending tasks bucketed by placement signature (reference analog:
    the cluster task manager's per-shape dispatch queues,
    cluster_task_manager.h:72). A scheduling pass probes one head per
    bucket instead of rescanning every pending task, so a burst of N
    same-shape submissions costs O(N) total scheduling work, not O(N^2).
    Iteration order is bucket insertion order (FIFO within a bucket)."""

    __slots__ = ("buckets",)

    def __init__(self):
        self.buckets: dict[tuple, deque] = {}

    def append(self, spec) -> None:
        self.buckets.setdefault(_placement_key(spec),
                                deque()).append(spec)

    def remove(self, spec) -> None:
        key = _placement_key(spec)
        dq = self.buckets.get(key)
        if dq is None:
            raise ValueError(f"{spec!r} not pending")
        dq.remove(spec)  # raises ValueError if absent, like deque
        if not dq:
            del self.buckets[key]

    def __len__(self) -> int:
        return sum(len(dq) for dq in self.buckets.values())

    def __bool__(self) -> bool:
        return any(self.buckets.values())

    def __iter__(self):
        for dq in list(self.buckets.values()):
            yield from list(dq)


class Runtime:
    """The head runtime. Exactly one per driver process."""

    def __init__(self, resources: dict[str, float],
                 object_store_memory: int | None = None,
                 session_dir: str | None = None,
                 head_labels: dict[str, str] | None = None,
                 enable_remote_nodes: bool = False,
                 log_to_driver: bool = True):
        from .config import cfg
        if object_store_memory is None:
            object_store_memory = cfg.object_store_memory
        self.job_id = JobID.from_random()
        sid = self.job_id.hex()[:8]
        self.session_dir = session_dir or f"/tmp/ray_tpu/session_{sid}"
        os.makedirs(self.session_dir, exist_ok=True)
        self.store_path = f"/dev/shm/ray_tpu_{sid}"
        self.store = SharedObjectStore(
            self.store_path, capacity=object_store_memory, create=True)
        self.spill = SpillStore(os.path.join(self.session_dir, "spill"))

        self.lock = threading.RLock()
        self.cv = threading.Condition(self.lock)

        # graftlint GL001 enforces the annotations: every touch of these
        # outside `with self.lock` (or a *_locked method) is a finding
        self.directory: dict[ObjectID, DirEntry] = {}  # guarded by: self.lock
        # distributed refcounting (reference_count.h:73 analog):
        # which processes hold >=1 live ObjectRef, serialized-copy pins
        # (may go negative when a receiver's add outruns the sender's pin —
        # per-connection FIFO makes that transient), driver-local counts,
        # and driver-side store pins from ray.put
        self.interest: dict[ObjectID, set[str]] = {}  # guarded by: self.lock
        self.xfer_pins: dict[ObjectID, int] = {}  # guarded by: self.lock
        # standing programmatic demand floor (autoscaler/sdk.py
        # request_resources); the autoscaler plans these every tick
        self.resource_requests: list[dict] = []
        self._local_refs: dict[ObjectID, int] = {}  # guarded by: self.lock
        self._pinned: set[ObjectID] = set()  # guarded by: self.lock
        # containment edges: outer stored object -> refs pickled inside it
        # (the outer holds interest in its inners until the outer is freed)
        self.contained: dict[ObjectID, list[ObjectID]] = {}  # guarded by: self.lock
        self.func_registry: dict[str, bytes] = {}
        # runtime-env blobs (working_dir / py_modules zips), hash-addressed
        # (reference analog: the GCS KV store runtime-env uploads)
        self.renv_registry: dict[str, bytes] = {}
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.workers: dict[str, WorkerInfo] = {}  # guarded by: self.lock
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[str, ActorID] = {}
        # dead actors' ready oids that died UNOBSERVED (no ref held, so
        # no error object was stored — storing one per dead actor leaks
        # forever); a late __ray_ready__ ref materializes the error from
        # here. guarded by: self.lock
        self._ready_failed: dict[ObjectID, str] = {}
        # ALIVE actors' ready oids (init completed): nothing is sealed
        # under a ready oid up front — one object per actor that nobody
        # reads would leak — so a __ray_ready__ ref materializes the
        # "ok" payload lazily at ref-add, and its refcount frees it.
        # guarded by: self.lock
        self._ready_ok: set[ObjectID] = set()
        self.pgs: dict[PlacementGroupID, PlacementGroupState] = {}
        self.pending = _PendingQueues()  # guarded by: self.lock
        self._sweeping_failed_deps = False
        self._abandoned_rpcs: set[ObjectID] = set()
        # timeline events, bounded so a long-lived driver doesn't grow
        # without limit
        self.events: deque[dict] = deque(maxlen=cfg.timeline_events_max)
        # per-task state records for the state API (reference analog: the
        # GCS task-event store, gcs_task_manager.h:94); bounded FIFO
        self.task_records: "OrderedDict" = OrderedDict()
        self.task_records_max = cfg.task_records_max
        # optional task-event export stream (reference: the export-events
        # schemas + task-event files the dashboard consumes)
        self._event_file = None
        if cfg.event_export_enabled:
            self._event_file = open(
                os.path.join(self.session_dir, "events.jsonl"), "a",
                buffering=1)
        self.counters = {"tasks_submitted": 0, "tasks_finished": 0,
                         "tasks_failed": 0, "tasks_retried": 0,
                         "actors_created": 0}
        self._shutdown = False
        self._worker_seq = 0
        self._spread_rr = 0
        # open per-worker message batch for the current scheduling pass
        # (see _schedule_locked); None outside a pass
        self._send_buf: dict | None = None
        # deferred-scheduling state (control-plane fast path): while
        # _defer_sched > 0, _schedule_locked only records that a pass is
        # wanted — a client batch frame or a submit burst then pays ONE
        # pass (and one batched frame per worker) instead of one per
        # message. _sched_evt wakes the scheduler pump for deferred
        # in-process submits.
        self._defer_sched = 0
        self._sched_wanted = False
        self._last_submit_ts = 0.0
        self._burst_window = (cfg.submit_burst_window_us / 1e6
                              if cfg.control_batching else 0.0)
        # in-process driver submit fast path (the v2 "submit carries the
        # submitter's interest" protocol applied to the LOCAL driver):
        # .remote() appends the spec here and marks its return oids
        # presumed; the scheduler pump registers interest and admits a
        # whole burst under ONE lock acquisition + ONE scheduling pass,
        # mirroring _handle_batch for remote clients. The driver thread
        # itself never touches the runtime lock on the submit hot path.
        self._submit_q: deque = deque()
        self._submitq_on = bool(cfg.driver_submit_queue)
        # live driver-ref counts for oids whose spec is still queued;
        # migrated into _local_refs when the pump admits the spec
        self._presumed: dict[ObjectID, int] = {}  # guarded by: self._presumed_lock
        # oids whose every presumed ref died before the pump saw the
        # spec: the pump must NOT register driver interest for them
        self._dropped_early: set[ObjectID] = set()  # guarded by: self._presumed_lock
        self._presumed_lock = threading.Lock()
        # serializes queue drains so specs admit in FIFO order even when
        # cancel() drains concurrently with the pump
        self._submitq_drain_lock = threading.Lock()
        # flight-recorder cluster collection: nonce -> {"snap"}
        # answered by the flight_ring handler as worker replies land
        self._flight_pulls: dict[bytes, dict] = {}
        self._flight_evt = threading.Event()
        # live-stack cluster collection (stall doctor, core/stacks.py):
        # same nonce protocol over the new stack_dump/stack_reply frames
        self._stack_pulls: dict[bytes, dict] = {}
        self._stack_evt = threading.Event()
        # stuck-task watchdog: per-task-name runtime EWMAs (updated on
        # every successful done) + scan/flag health counters; cycle keys
        # already reported (one DEADLOCK flight event per incident, not
        # per hang_report poll)
        self._seen_cycles: set = set()  # guarded by: self.lock
        self._task_ewma: dict[str, float] = {}  # guarded by: self.lock
        self._watchdog = {"enabled": bool(cfg.stall_watchdog), "scans": 0,
                          "flagged_total": 0, "stuck_running": 0,
                          "last_scan": 0.0}
        # cluster-shared directory service (core/directory.py, protocol
        # v7): named hint maps behind dir_update/dir_query frames. NOT
        # self.directory — that is the object directory below.
        self.dirs = DirectoryService()
        # store-path rpc replies in flight per peer: a reply written to
        # the shared store has NO directory entry (the peer reads and
        # deletes it directly), so a peer killed between sending the
        # rpc and reading the reply would leak the object forever —
        # _on_worker_death reclaims these. Pruned lazily on write.
        self._rpc_reply_pins: dict[str, set] = {}  # guarded by: self.lock
        flight.set_proc_name("head")
        self._sched_evt = threading.Event()
        threading.Thread(target=self._sched_pump_loop, daemon=True,
                         name="rtpu-sched-pump").start()
        # merged user-defined metrics (util/metrics.py):
        # name -> {kind, desc, series: {tag-tuple: value}}
        self.user_metrics: dict[str, dict] = {}
        import concurrent.futures
        # worker->head rpc handlers (blocking calls like pg_wait run here)
        # 32 threads: pg_wait parks here for up to its full timeout, and a
        # gang of waiters must not starve cheap rpcs behind it
        self._rpc_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=cfg.rpc_pool_workers, thread_name_prefix="rtpu-rpc")
        import queue
        self._drop_q: "queue.SimpleQueue" = queue.SimpleQueue()
        threading.Thread(target=self._drop_loop, daemon=True,
                         name="rtpu-ref-drops").start()

        # head node
        self.head_node = NodeInfo(NodeID.from_random(), resources,
                                  head_labels, name="head")
        self.nodes[self.head_node.node_id] = self.head_node

        # control-plane listeners: AF_UNIX for local workers, TCP for node
        # agents / remote workers (reference analog: the gRPC services every
        # raylet/worker dials, rpc/grpc_server.h:88 — one authkeyed
        # connection-oriented channel here)
        addr = os.path.join(self.session_dir, "head.sock")
        # a stable cluster authkey (RTPU_CLUSTER_AUTHKEY hex) + fixed
        # cfg.head_tcp_port let agents and drivers re-dial a RESTARTED
        # head at the same address — the role Redis's fixed address plays
        # for reference GCS failover (redis_store_client.h:111)
        ak_env = os.environ.get("RTPU_CLUSTER_AUTHKEY")
        self._authkey = bytes.fromhex(ak_env) if ak_env else os.urandom(16)
        self.listener = Listener(addr, "AF_UNIX", authkey=self._authkey)
        self.listener_addr = addr
        # loopback unless the user opts into remote nodes: the channel is
        # authkey-HMAC-gated but carries pickles, so it must not face the
        # network by default
        self._tcp_host = "0.0.0.0" if enable_remote_nodes else "127.0.0.1"
        self.tcp_listener = Listener(
            (self._tcp_host, cfg.head_tcp_port), "AF_INET",
            authkey=self._authkey)
        self.tcp_port = self.tcp_listener.address[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, args=(self.listener,),
            daemon=True, name="rtpu-accept")
        self._accept_thread.start()
        self._tcp_accept_thread = threading.Thread(
            target=self._accept_loop, args=(self.tcp_listener,),
            daemon=True, name="rtpu-accept-tcp")
        self._tcp_accept_thread.start()

        # cluster file: everything a driver client / node agent / job needs
        # to dial this cluster (reference analog: the GCS address + redis
        # password a reference driver resolves from --address; here a
        # 0600 json since the authkey is a credential)
        from .job_manager import JobManager
        self.cluster_file = os.path.join(self.session_dir, "cluster.json")
        cf = {"unix_addr": addr, "tcp_host": self._tcp_host,
              "tcp_port": self.tcp_port, "authkey": self._authkey.hex(),
              "store_path": self.store_path, "spill_dir": self.spill.dir,
              "session_dir": self.session_dir, "pid": os.getpid()}
        fd = os.open(self.cluster_file,
                     os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(cf, f)
        from .config import cfg as _cfg
        from .gcs_store import GcsStore, start_snapshot_loop
        from .pubsub import Publisher
        self.pubsub = Publisher()
        # durable metadata (redis_store_client.h analog): internal KV +
        # restorable head-state snapshots
        self.kv = GcsStore(os.path.join(self.session_dir, "gcs.sqlite"))
        self._snapshot_stop = None
        if _cfg.gcs_snapshot_period_s > 0:
            self._snapshot_stop = start_snapshot_loop(
                self, _cfg.gcs_snapshot_period_s)
        # OOM protection (memory_monitor.h:52 analog); runs only when the
        # refresh period is non-zero
        from .memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(self).start()
        self.jobs = JobManager(self.session_dir, self.cluster_file)
        self.jobs.on_status = lambda job_id, status: self.pubsub.publish(
            "jobs", {"job_id": job_id, "status": status})
        self._driver_seq = 0

        # worker stdout/stderr -> the driver console (reference:
        # log_to_driver / the log monitor tailing worker files)
        if log_to_driver:
            threading.Thread(target=self._log_tail_loop, daemon=True,
                             name="rtpu-logtail").start()

        # agent liveness: heartbeats guard against HUNG agents (conn EOF
        # already covers dead processes) — gcs_health_check_manager.h:45
        threading.Thread(target=self._health_check_loop, daemon=True,
                         name="rtpu-healthcheck").start()
        threading.Thread(target=self._pipeline_rebalance_loop, daemon=True,
                         name="rtpu-rebalance").start()
        threading.Thread(target=self._stall_watchdog_loop, daemon=True,
                         name="rtpu-stall-watchdog").start()
        # metrics plane (ray_tpu/obs): TSDB scraper + SLO engine. Rides
        # the merged user-metric store — no new wire frames; remote
        # drivers query it over metrics_history/slo_report/obs_signals
        # in _RPC_METHODS
        self.obs = None
        if cfg.tsdb_enable:
            from ..obs.scraper import MetricsScraper
            self.obs = MetricsScraper(self).start()

        # cross-node data plane: serve this node's store to pullers
        # (object_manager.h:119 Push/Pull analog; object_transfer.py)
        from .object_transfer import ObjectDataServer
        self.data_server = ObjectDataServer(
            self.store, self.spill,
            host=("0.0.0.0" if enable_remote_nodes else "127.0.0.1"))
        if enable_remote_nodes:
            self.head_node.data_addr = (
                f"{host_ip()}:{self.data_server.address.rsplit(':', 1)[1]}")
        else:
            self.head_node.data_addr = self.data_server.address

        # prestart the worker pool so first tasks don't pay process cold-start
        # (reference: worker_pool.h:283 PrestartWorkers / idle pool)
        with self.lock:
            n_prestart = min(int(resources.get("CPU", 1)),
                             cfg.worker_prestart)
            for _ in range(n_prestart):
                self._spawn_worker_locked(self.head_node)

    # ------------------------------------------------------------------ #
    # connection plumbing
    # ------------------------------------------------------------------ #

    def _log_tail_loop(self):
        """Follow head-pool worker logs, echoing new output with a
        (worker) prefix (reference: the log monitor pushing worker
        stdout/stderr to the driver). shutdown() runs one final scan so
        late prints aren't dropped."""
        self._logtail_state = ({}, {})  # offsets, partial-line carries
        while not self._shutdown:
            time.sleep(0.5)
            self._log_tail_scan()

    def _log_tail_scan(self):
        import glob
        offsets, carries = self._logtail_state
        for path in glob.glob(os.path.join(self.session_dir,
                                           "worker-*.log")):
            try:
                size = os.path.getsize(path)
                seen = offsets.get(path, 0)
                if size <= seen:
                    continue
                with open(path, "rb") as f:
                    f.seek(seen)
                    chunk = f.read(size - seen)
                offsets[path] = size
                # emit only COMPLETE lines: carry the trailing partial so
                # split lines / bisected UTF-8 chars are never printed
                data = carries.get(path, b"") + chunk
                head, nl, tail = data.rpartition(b"\n")
                carries[path] = tail
                if not nl:
                    continue
                wid = os.path.basename(path)[len("worker-"):-len(".log")]
                for line in head.decode(errors="replace").splitlines():
                    if line.strip():
                        print(f"({wid}) {line}", flush=True)
            except OSError:
                continue

    def _health_check_loop(self):
        from .config import cfg
        period = cfg.health_check_period_ms / 1000.0
        timeout = cfg.health_check_timeout_s
        if period <= 0:
            return
        while not self._shutdown:
            time.sleep(period)
            now = time.monotonic()
            with self.lock:
                stale = [n for n in self.nodes.values()
                         if n.agent is not None and n.alive
                         and getattr(n, "last_heartbeat", None) is not None
                         and now - n.last_heartbeat > timeout]
            self._reap_idle_workers()
            for n in stale:
                # declare the node dead DIRECTLY: closing the conn would
                # not wake the agent loop's blocked read (Linux read()
                # survives a concurrent close), so run the removal here —
                # the loop's eventual EOF cleanup double-calls remove_node,
                # which no-ops on a dead node
                with self.lock:
                    for wid in list(n.workers):
                        w = self.workers.get(wid)
                        if w is not None and isinstance(w.proc,
                                                        _RemoteProc):
                            w.proc.mark_exited(-1)
                try:
                    self.remove_node(n.node_id)
                except Exception:
                    pass  # agent-loop EOF path already removed it
                try:
                    n.agent.conn.close()
                except Exception:
                    pass  # already closed

    def _pipeline_rebalance_loop(self):
        """Periodic work-stealing fallback (own timer — NOT coupled to the
        health-check flag): the done->idle steal trigger misses the case
        where the last other-worker done fires before a pipeline gets
        stuck behind a slow task, or fires inside the 50ms slow gate —
        with no further events, nothing would ever steal the straggler."""
        from .config import cfg
        if cfg.worker_pipeline_depth <= 0:
            return
        while not self._shutdown:
            time.sleep(0.1)
            try:
                with self.lock:
                    if any(w.state == "idle"
                           for w in self.workers.values()):
                        self._rebalance_pipelines_locked()
            except Exception:
                pass  # never let bookkeeping kill the timer

    def _reap_idle_workers(self):
        """Idle workers beyond the prestart floor exit after
        worker_idle_timeout_s (worker_pool.h idle-eviction analog);
        runtime-env-dedicated workers reap the same way."""
        from .config import cfg
        timeout = cfg.worker_idle_timeout_s
        if timeout <= 0:
            return
        now = time.monotonic()
        with self.lock:
            head_id = self.head_node.node_id
            floor = min(int(self.head_node.resources_total.get("CPU", 1)),
                        cfg.worker_prestart)
            # head-pool scope only: agent nodes manage their own workers
            head_workers = [w for w in self.workers.values()
                            if w.node_id == head_id]
            idle = [w for w in head_workers
                    if w.state == "idle" and w.conn is not None
                    and now - getattr(w, "idle_since", now) > timeout]
            n_idle = sum(1 for w in head_workers if w.state == "idle")
            victims = idle[:max(0, n_idle - floor)]
            for w in victims:
                w.send({"t": "exit"})
                self._on_worker_death_locked_prep(w)

    def _accept_loop(self, listener):
        while not self._shutdown:
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                return
            threading.Thread(target=self._recv_loop, args=(conn,),
                             daemon=True, name="rtpu-recv").start()

    @property
    def head_address(self) -> str:
        """TCP address a node agent dials
        (`ray_tpu.core.node_agent --head <this>`). With the default
        loopback bind this is only dialable from this host; pass
        init(enable_remote_nodes=True) for other hosts."""
        if self._tcp_host != "0.0.0.0":
            return f"{self._tcp_host}:{self.tcp_port}"
        return f"{host_ip()}:{self.tcp_port}"

    def _recv_loop(self, conn: Connection):
        wid = None
        try:
            msg = conn.recv()
            if msg.get("t") in ("register", "register_node",
                                "register_driver"):
                who = {"register": "worker",
                       "register_node": "node agent",
                       "register_driver": "driver client"}[msg["t"]]
                try:
                    check_peer_version(msg.get("pv"), who)
                except ProtocolMismatchError as e:
                    # structured refusal: agents/drivers raise it to the
                    # user from their registration-reply check
                    try:
                        conn.send({"t": "rejected", "error": str(e)})
                    except Exception:
                        pass  # peer hung up before reading the refusal
                    conn.close()
                    return
            if msg.get("t") == "register_node":
                self._agent_loop(conn, msg)
                return
            if msg.get("t") == "register_driver":
                # a driver client (reference analog: ray.init(address=...)
                # attaching a driver core worker to a running cluster /
                # the util/client proxy role). It speaks the full worker
                # protocol but never executes tasks: it lives outside every
                # node's worker pool so the scheduler cannot pick it.
                with self.lock:
                    self._driver_seq += 1
                    wid = f"driver-{self._driver_seq:04d}"
                    w = WorkerInfo(wid, self.head_node.node_id,
                                   _ExternalProc(int(msg.get("pid", 0))),
                                   tpu=False)
                    w.state = "driver"
                    w.conn = conn
                    self.workers[wid] = w
                with w.send_lock:
                    # session_dir + resumed_from let a reconnecting driver
                    # verify this head is ITS cluster (same session, or a
                    # restart resumed from its session) before attaching —
                    # auto-resolve must never hijack onto an unrelated
                    # local cluster (client.py _reconnect)
                    conn.send({"t": "registered_driver", "wid": wid,
                               "store_path": self.store_path,
                               "spill_dir": self.spill.dir,
                               "job_id": self.job_id.hex(),
                               "session_dir": self.session_dir,
                               "resumed_from": getattr(
                                   self, "resumed_from", None),
                               "pv": PROTOCOL_VERSION})
                while True:
                    m = conn.recv()
                    try:
                        self._handle_msg(wid, m)
                    except Exception:
                        traceback.print_exc()
            if msg.get("t") != "register":
                conn.close()
                return
            wid = msg["wid"]
            with self.lock:
                w = self.workers.get(wid)
                if w is None or w.state == "dead":
                    conn.close()
                    return
                w.conn = conn
                pending_spec = getattr(w, "pending_spec", None)
                pending_actor = getattr(w, "pending_actor", None)
                if pending_spec is not None:
                    w.pending_spec = None
                    self._dispatch_locked(w, pending_spec)
                elif pending_actor is not None:
                    w.pending_actor = None
                    self._dispatch_actor_locked(w, pending_actor)
                elif w.state == "starting":
                    w.state = "idle"
                self._schedule_locked()
            while True:
                msg = conn.recv()
                try:
                    self._handle_msg(wid, msg)
                except Exception:
                    # a bad application-level request must not tear down a
                    # healthy worker's control connection
                    traceback.print_exc()
        except (EOFError, OSError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            if wid is not None:
                self._on_worker_death(wid)

    def _sched_pump_loop(self):
        """Admits queued driver submits (one lock hold + one scheduling
        pass per accumulated batch — see _drain_submit_q) and runs the
        scheduling passes that deferred burst submissions request; a
        burst's per-worker dispatches coalesce into one frame each."""
        while True:
            self._sched_evt.wait()
            self._sched_evt.clear()
            if self._shutdown:
                return
            try:
                self._drain_submit_q()
                with self.lock:
                    self._schedule_locked()
            except Exception:
                if self._shutdown:
                    return
                traceback.print_exc()

    def _handle_batch(self, wid: str, msgs: list):
        """A client batch frame: one scheduler-lock acquisition serves
        every contained message (in order), and all the scheduling passes
        they request collapse into ONE at the end — whose per-worker task
        dispatches ride one batched frame each (_send_buf). A bad message
        must not poison the rest, same contract as the recv loop."""
        flight.evt(flight.BATCH_RECV, len(msgs))
        with self.lock:
            opened = self._send_buf is None
            if opened:
                self._send_buf = {}
            self._defer_sched += 1
            try:
                for m in msgs:
                    try:
                        self._handle_msg(wid, m)
                    except Exception:
                        traceback.print_exc()
            finally:
                self._defer_sched -= 1
                try:
                    if self._sched_wanted and not self._defer_sched:
                        self._sched_wanted = False
                        self._schedule_locked()  # rides the open send buf
                finally:
                    # restore + flush even if the pass raises: leaking an
                    # open _send_buf would silently black-hole every
                    # future worker dispatch
                    if opened:
                        buf, self._send_buf = self._send_buf, None
                        self._flush_wsend_buf(buf)

    def _handle_msg(self, wid: str, msg: dict):
        t = msg["t"]
        if t == "batch":
            self._handle_batch(wid, msg["msgs"])
        elif t == "done":
            if "span" in msg:
                self.record_trace_span(msg["span"])
            self._on_task_done(wid, msg)
        elif t == "trace_span":
            self.record_trace_span(msg["span"])
        elif t == "flight_ring":
            # A worker's answer to flight_pull. The monotonic-clock
            # offset is estimated through the WALL clock as a bridge:
            # the snapshot samples (mono, wall) together, we sample our
            # own pair at receipt, and offset = (their mono - their
            # wall) - (our mono - our wall). Unlike the request/reply
            # midpoint this is immune to transport latency (an 8ms
            # queueing delay on a loaded box would otherwise skew the
            # midpoint by 4ms and reorder same-host seal->wake edges);
            # it is exact whenever wall clocks agree — always on one
            # host, NTP-close across hosts. Sub-millisecond residue is
            # clamped to zero so shared-clock processes stitch exactly.
            rec = self._flight_pulls.get(msg["nonce"])
            if rec is not None:
                snap = msg["snap"]
                mono, wall = time.monotonic_ns(), time.time_ns()
                off = ((snap.get("mono_ns", 0) - snap.get("wall_ns", 0))
                       - (mono - wall))
                if abs(off) < 1_000_000:
                    off = 0
                snap["offset_ns"] = off
                rec["snap"] = snap
                self._flight_evt.set()
        elif t == "stack_reply":
            # a worker's/driver's answer to stack_dump (stall doctor);
            # wait-beacon durations are already relative in the snapshot,
            # so no clock stitching is needed here
            rec = self._stack_pulls.get(msg["nonce"])
            if rec is not None:
                rec["snap"] = msg["snap"]
                self._stack_evt.set()
        elif t == "actor_ready":
            self._on_actor_ready(wid, msg)
        elif t == "submit":
            with self.lock:
                # v2 protocol: the submit itself registers the submitter's
                # interest in every return (the client sends no per-task
                # ref_add — half the client writes on a burst). Interest
                # lands BEFORE the task can run, same guarantee as before.
                for oid in msg["spec"].return_ids:
                    self._ref_add_locked(oid, wid, False)
                self._submit_locked(msg["spec"])
        elif t == "func_def":
            with self.lock:
                self.func_registry.setdefault(msg["fid"], msg["blob"])
        elif t == "renv_def":
            with self.lock:
                self.renv_registry.setdefault(msg["hash"], msg["blob"])
        elif t == "put":
            with self.lock:
                e = self.directory[msg["oid"]] = DirEntry(READY)
                w = self.workers.get(wid)
                loc = self._own_store_loc_locked(w)
                if loc is not None:
                    e.add_location(loc)
        elif t == "object_copied":
            # a puller holds a copy now (object_transfer): free fanout and
            # locate() must know (reference: object-directory location add)
            with self.lock:
                e = self.directory.get(ObjectID(msg["oid"]))
                w = self.workers.get(wid)
                loc = self._own_store_loc_locked(w)
                if e is not None and loc is not None:
                    e.add_location(loc)
        elif t == "put_spilled":
            with self.lock:
                oid = ObjectID(msg["oid"])
                e = self.directory.get(oid)
                if e is None:
                    e = self.directory[oid] = DirEntry(SPILLED)
                else:
                    e.state = SPILLED  # keep lineage for later recovery
                loc = self._own_store_loc_locked(self.workers.get(wid))
                if loc is not None:
                    e.add_location(loc)  # spill lives on that node's disk
        elif t == "contained":
            with self.lock:
                self._register_contained_locked(
                    ObjectID(msg["oid"]),
                    [ObjectID(b) for b in msg["inner"]])
        elif t == "ref_add":
            with self.lock:
                self._ref_add_locked(ObjectID(msg["oid"]), wid,
                                     msg.get("transfer", False))
        elif t == "ref_drop":
            with self.lock:
                self._ref_drop_locked(ObjectID(msg["oid"]), wid)
        elif t == "ref_drops":
            # batched 1->0 drops from a client's drop thread: one lock
            # acquire + one message for a burst of dying refs
            with self.lock:
                for ob in msg["oids"]:
                    self._ref_drop_locked(ObjectID(ob), wid)
        elif t == "ref_xfer":
            with self.lock:
                oid = ObjectID(msg["oid"])
                self.xfer_pins[oid] = self.xfer_pins.get(oid, 0) + 1
        elif t == "create_actor":
            with self.lock:
                self._create_actor_locked(msg["spec"])
        elif t == "actor_call":
            with self.lock:
                # v2: actor_call implies submitter interest (see "submit");
                # route directly rather than via submit_actor_task_spec so
                # no head-side ObjectRefs are minted just to be GC'd
                for oid in msg["spec"].return_ids:
                    self._ref_add_locked(oid, wid, False)
                self._submit_actor_task_locked(msg["spec"])
        elif t == "kill_actor":
            self.kill_actor(ActorID(msg["actor_id"]), msg.get("no_restart", True))
        elif t == "ensure":
            with self.lock:
                for ob in msg["oids"]:
                    self._ensure_available_locked(ObjectID(ob))
                self._schedule_locked()
        elif t == "user_metrics":
            self.merge_user_metrics(msg["rows"])
        elif t == "blocked":
            with self.lock:
                w = self.workers.get(wid)
                # zero-resource tasks hold nothing but must STILL mark
                # blocked: the flag is what excludes this worker from
                # pipelining and what triggers the queue steal — without
                # it a zero-cpu task waiting on work queued behind itself
                # deadlocks (release/reacquire are no-ops on {} holdings)
                if w and not w.blocked:
                    w.blocked = True
                    # a blocked task may be waiting on work queued behind
                    # it — steal the pipeline back before releasing
                    self._steal_queued_locked(w)
                    self._release_to_node(w)
                    self._schedule_locked()
        elif t == "unblocked":
            with self.lock:
                w = self.workers.get(wid)
                if w and w.blocked:
                    w.blocked = False
                    self._reacquire_from_node(w)
        elif t == "cancel":
            self.cancel(ObjectRef(ObjectID(msg["oid"])),
                        force=msg.get("force", False))
        elif t == "device_fetch":
            # device-object payload request (experimental/device_objects):
            # route to the owner process; serving may serialize a large
            # array, so keep it off this recv loop
            self._rpc_pool.submit(self.device_fetch, msg["owner"],
                                  msg["key"], msg["reply_oid"], wid)
        elif t == "device_payload":
            # owner's answer to a device_fetch: deliver to the requester
            self._deliver_payload(msg.get("requester", "driver"),
                                  msg["reply_oid"], msg["payload"])
        elif t == "rpc":
            # Handled off-thread: rpcs like pg_wait block, and this recv loop
            # must keep draining the worker's other messages. A shared pool
            # replaces the former thread-per-rpc spawn (hot-path cost).
            self._rpc_pool.submit(self._handle_worker_rpc, msg, wid)
        elif t == "dir_update":
            # shared-directory merge (core/directory.py): cheap dict ops,
            # handled inline; publishes are owner-stamped with the sending
            # connection so _on_worker_death can sweep them
            self.dirs.merge(msg["d"], msg.get("put"), msg.get("drop"),
                            owner=wid)
        elif t == "dir_query":
            # answered INLINE on this recv thread (not the rpc pool): a
            # pure dict read under the directory's own short lock, and
            # admission-time prefix lookups sit on the serve hot path
            try:
                payload = ("ok", self.dirs.lookup(msg["d"],
                                                  msg.get("keys")))
            except Exception as e:  # noqa: BLE001 — reply with any failure
                payload = ("err", e)
            self._reply_rpc(wid, ObjectID(msg["reply_oid"]), payload)
        elif t == "rpc_abandon":
            # Worker timed out waiting for a reply. Mark abandoned FIRST,
            # then reclaim if already written — this order closes the race
            # with the rpc thread's put-then-check (one side always sees the
            # other's write).
            oid = ObjectID(msg["reply_oid"])
            with self.lock:
                self._abandoned_rpcs.add(oid)
            if self.store.contains(oid):
                with self.lock:
                    self._abandoned_rpcs.discard(oid)
                self.store.delete(oid)

    def _agent_loop(self, conn: Connection, msg: dict):
        """Serve one node agent for its lifetime (reference analog: the
        node-membership half of GcsNodeManager, gcs_node_manager.h:49 —
        register on connect, dead on disconnect)."""
        agent = _AgentHandle(conn)
        node = NodeInfo(NodeID.from_random(), msg["resources"],
                        msg.get("labels"), name=msg.get("name", "agent"))
        node.agent = agent
        node.data_addr = msg.get("data_addr")
        node.own_store = bool(msg.get("own_store"))
        # reply BEFORE the node becomes schedulable: otherwise a pending
        # task could push a spawn_worker ahead of this reply and the agent's
        # registration recv would read the wrong message. The agent already
        # holds the authkey (it authenticated with it) — never echo it.
        agent.send({"t": "registered", "node_id": node.node_id.hex(),
                    "store_path": self.store_path,
                    "spill_dir": self.spill.dir,
                    "tcp_port": self.tcp_port, "pv": PROTOCOL_VERSION})
        with self.lock:
            self.nodes[node.node_id] = node
            self._retry_pending_pgs_locked()
            self._schedule_locked()
        self.pubsub.publish("nodes", {"node_id": node.node_id.hex(),
                                      "event": "added", "name": node.name})
        node.last_heartbeat = time.monotonic()
        try:
            while True:
                m = conn.recv()
                t = m.get("t")
                if t == "heartbeat":
                    node.last_heartbeat = time.monotonic()
                elif t == "worker_spawned":
                    with self.lock:
                        w = self.workers.get(m["wid"])
                        if w is not None and isinstance(w.proc, _RemoteProc):
                            w.proc.pid = m["pid"]
                elif t == "worker_exit":
                    with self.lock:
                        w = self.workers.get(m["wid"])
                        if w is not None and isinstance(w.proc,
                                                        _RemoteProc):
                            w.proc.mark_exited(m.get("rc"))
                    self._on_worker_death(m["wid"])
                elif t == "deregister":
                    break
        except (EOFError, OSError):
            pass
        finally:
            try:
                conn.close()
            except Exception:
                pass  # already closed
            # complete every orphaned remote proc first so remove_node's
            # per-worker proc.wait() returns immediately instead of timing
            # out sequentially
            with self.lock:
                for wid in list(node.workers):
                    w = self.workers.get(wid)
                    if w is not None and isinstance(w.proc, _RemoteProc):
                        w.proc.mark_exited(-1)
            try:
                self.remove_node(node.node_id)
            except Exception:
                pass  # double remove_node is a benign no-op

    # Worker→head request/reply: the reply value is written into the shared
    # store at a worker-chosen oid (reference analog: the CoreWorkerService /
    # GCS RPCs workers issue for name resolution and cluster state,
    # gcs_client/accessor.h — here the shm store doubles as the reply channel).
    _RPC_METHODS = ("get_actor_by_name", "cluster_resources",
                    "available_resources", "node_table", "pg_wait",
                    "create_placement_group_rpc", "remove_placement_group_rpc",
                    "timeline", "flight_timeline", "flight_stats",
                    "stack_report", "hang_report",
                    "state_list", "state_summary",
                    "memory_summary", "autoscaler_status",
                    "user_metrics_dump", "pubsub_poll",
                    "metrics_history", "metrics_names", "slo_report",
                    "obs_signals", "cache_report",
                    "kv_put", "kv_get", "kv_del", "kv_keys", "locate",
                    "locate_many", "request_resources_rpc",
                    "job_submit", "job_list", "job_status", "job_logs",
                    "job_stop")

    def locate(self, oid_bytes: bytes) -> list[str]:
        """Data-server addresses of nodes holding the object (ownership
        object directory analog, ownership_object_directory.h). The head's
        own store/spill is always checked — errors and driver puts live
        there."""
        oid = ObjectID(oid_bytes)
        out = []
        with self.lock:
            e = self.directory.get(oid)
            locs = set(e.locations or ()) if e is not None else set()
            if self.store.contains(oid) or self.spill.contains(oid):
                locs.add(self.head_node.node_id.hex())
            for n in self.nodes.values():
                if n.alive and n.node_id.hex() in locs and n.data_addr:
                    out.append(n.data_addr)
        return out

    def request_resources_rpc(self, bundles: list[dict]) -> None:
        """Replace the standing programmatic demand floor
        (autoscaler/sdk.py request_resources from a remote driver)."""
        with self.lock:
            self.resource_requests = [dict(b) for b in bundles]

    def locate_many(self, oids: list[bytes]) -> list[bool]:
        """Settled-ness (a result exists anywhere — any store, spill, or
        live holder node — or the task terminally FAILED) for a batch of
        objects in ONE round-trip: the saturated max_pending_calls prune
        asks about every pending result at once (actor.py
        _admit_pending) instead of one locate RPC per ref. FAILED counts
        as settled — an errored call is not in flight (runtime.wait's
        'errors count as ready' rule). Store/spill probes (shm lookup +
        file stat each) run OUTSIDE the head lock, same reasoning as
        state.memory_summary."""
        undecided: list[tuple[int, ObjectID]] = []
        out = [False] * len(oids)
        with self.lock:
            alive = {n.node_id.hex() for n in self.nodes.values()
                     if n.alive}
            for i, ob in enumerate(oids):
                oid = ObjectID(ob)
                e = self.directory.get(oid)
                if e is not None and e.state == FAILED:
                    out[i] = True
                    continue
                locs = set(e.locations or ()) if e is not None else set()
                if locs & alive:
                    out[i] = True
                else:
                    undecided.append((i, oid))
        for i, oid in undecided:
            out[i] = self.store.contains(oid) or self.spill.contains(oid)
        return out

    # internal KV (gcs_kv_manager.h / ray.experimental.internal_kv analog);
    # user namespace is prefixed so snapshots can't be clobbered
    def kv_put(self, key: str, value: bytes) -> None:
        self.kv.put("user", key, value)

    def kv_get(self, key: str):
        return self.kv.get("user", key)

    def kv_del(self, key: str) -> bool:
        return self.kv.delete("user", key)

    def kv_keys(self) -> list[str]:
        return self.kv.keys("user")

    def _own_store_loc_locked(self, w) -> str | None:
        """Node hex for location tracking — ONLY own-store nodes:
        shared-store copies live in the head store the directory already
        checks directly, and recording them would make eviction look like
        a live remote copy (blocking lineage reconstruction)."""
        if w is None:
            return None
        n = self.nodes.get(w.node_id)
        if n is not None and n.own_store:
            return n.node_id.hex()
        return None

    def _deliver_payload(self, requester: str, reply_oid: bytes,
                         payload) -> None:
        """Hand an out-of-band reply to a requester: the head store for
        the driver and shared-store workers, the control conn for
        own-store workers (who cannot see the head store)."""
        if requester != "driver":
            with self.lock:
                w = self.workers.get(requester)
                n = self.nodes.get(w.node_id) if w is not None else None
            if n is not None and n.own_store:
                if w.send({"t": "rpc_reply", "reply_oid": reply_oid,
                           "payload": payload}):
                    return
        try:
            self.store.put(ObjectID(reply_oid), payload)
        except Exception:
            pass  # store full/closing: requester times out

    def device_fetch(self, owner: str, key: str, reply_oid: bytes,
                     requester: str = "driver") -> None:
        """Route a device-object fetch to its owner process
        (experimental/device_objects.py; RDT transfer-request analog).
        The payload travels owner -> head -> requester over the control
        conns, so it works across per-node stores."""
        if owner == "driver":
            from ..experimental.device_objects import _fetch_payload
            self._deliver_payload(requester, reply_oid, _fetch_payload(key))
            return
        with self.lock:
            w = self.workers.get(owner)
        if w is None or w.state == "dead" or not w.send(
                {"t": "device_get", "key": key, "reply_oid": reply_oid,
                 "requester": requester}):
            self._deliver_payload(requester, reply_oid,
                                  ("err", f"device-object owner {owner} "
                                          f"is gone"))

    def state_list(self, kind, limit=1000, filters=None):
        """State-API rows for workers/driver clients (util/state/api.py)."""
        from .. import state as state_api
        fn = getattr(state_api, f"list_{kind}", None)
        if fn is None:
            raise ValueError(f"unknown state kind {kind!r}")
        import inspect
        params = inspect.signature(fn).parameters
        kwargs = {}
        if "limit" in params:
            kwargs["limit"] = limit
        if "filters" in params and filters:
            kwargs["filters"] = filters
        return fn(**kwargs)

    def state_summary(self):
        from .. import state as state_api
        return state_api.summary()

    def memory_summary(self, limit: int = 1000):
        from .. import state as state_api
        return state_api.memory_summary(limit)

    def autoscaler_status(self):
        from .. import state as state_api
        return state_api.autoscaler_status()

    def pubsub_poll(self, channel, cursor=0, timeout_s=20.0):
        # runs on the rpc pool (long-poll parks a pool thread, like pg_wait)
        return self.pubsub.poll(channel, cursor, timeout_s)

    def _reply_via_conn(self, wid: str | None) -> bool:
        """Workers on own-store nodes can't see the head store; their RPC
        replies ride the control connection instead."""
        if wid is None:
            return False
        with self.lock:
            w = self.workers.get(wid)
            if w is None:
                return False
            n = self.nodes.get(w.node_id)
            return n is not None and n.own_store

    def _reply_rpc(self, wid: str | None, oid: ObjectID, payload) -> None:
        """Deliver one rpc-style reply: over the control connection for
        own-store peers, into the shared store otherwise (with the
        abandon-race reclaim). Shared by the rpc pool and the inline
        dir_query handler."""
        if self._reply_via_conn(wid):
            with self.lock:
                w = self.workers.get(wid)
            if w is not None:
                # outside the lock: w.send pickles + writes the pipe
                # under its own per-worker send_lock
                w.send({"t": "rpc_reply", "reply_oid": oid.binary(),
                        "payload": payload})
            return
        self.store.put(oid, payload)
        # No directory entry: the worker polls the store directly and deletes
        # the reply once read. If the worker already gave up, reclaim now.
        with self.lock:
            abandoned = oid in self._abandoned_rpcs
            self._abandoned_rpcs.discard(oid)
            if not abandoned and wid is not None:
                pend = self._rpc_reply_pins.setdefault(wid, set())
                # lazy prune: replies the peer already consumed (and
                # deleted) fall out here, keeping the set at the number
                # of genuinely in-flight replies
                pend.difference_update(
                    [o for o in pend if not self.store.contains(o)])
                pend.add(oid)
        if abandoned:
            self.store.delete(oid)

    def _handle_worker_rpc(self, msg: dict, wid: str | None = None):
        oid = ObjectID(msg["reply_oid"])
        try:
            m = msg["m"]
            if m not in self._RPC_METHODS:
                raise ValueError(f"unknown rpc {m!r}")
            result = getattr(self, m)(*msg.get("args", ()))
            self._reply_rpc(wid, oid, ("ok", result))
        except BaseException as e:  # noqa: BLE001 — reply with any failure
            try:
                self._reply_rpc(wid, oid, ("err", e))
            except BaseException:  # unpicklable exception/result
                self._reply_rpc(wid, oid, ("err", RuntimeError(
                    f"rpc {msg.get('m')} failed with unpicklable error: "
                    f"{type(e).__name__}: {e!r}")))

    # job-table RPCs (gcs_job_manager.h:52 / job_manager.py:60 analog)
    def job_submit(self, entrypoint, env=None, working_dir_zip=None,
                   metadata=None, job_id=None):
        return self.jobs.submit(entrypoint, env, working_dir_zip,
                                metadata, job_id)

    def job_list(self):
        return self.jobs.list()

    def job_status(self, job_id):
        return self.jobs.status(job_id)

    def job_logs(self, job_id, tail_bytes=1 << 20, offset=None):
        return self.jobs.logs(job_id, tail_bytes, offset)

    def job_stop(self, job_id):
        return self.jobs.stop(job_id)

    def create_placement_group_rpc(self, bundles, strategy, name="",
                                   same_label=None, bundle_selectors=None):
        pg = self.create_placement_group(
            bundles, strategy, name,
            same_label=same_label, bundle_selectors=bundle_selectors)
        return (pg.pg_id, [dict(b.resources) for b in pg.bundles])

    def remove_placement_group_rpc(self, pg_id):
        self.remove_placement_group(pg_id)
        return None

    def pg_wait(self, pg_id, timeout: float = 30.0) -> bool:
        with self.lock:
            pg = self.pgs.get(pg_id)
        if pg is None:
            raise ValueError(f"no placement group {pg_id}")
        # removal sets ready_event to wake waiters; only 'created' is ready
        ok = pg.ready_event.wait(timeout=timeout)
        return ok and pg.state == "created"

    # ------------------------------------------------------------------ #
    # worker pool (reference: raylet/worker_pool.h:283)
    # ------------------------------------------------------------------ #

    def _spawn_worker_locked(self, node: NodeInfo, tpu: bool = False) -> WorkerInfo:
        self._worker_seq += 1
        wid = f"w{self._worker_seq:05d}"
        if node.agent is not None:
            # agent-backed node: the agent forks the worker on its host and
            # reports pid/exit back over its control connection
            w = WorkerInfo(wid, node.node_id,
                           _RemoteProc(node.agent, wid), tpu)
            w.pending_spec = None
            w.pending_actor = None
            self.workers[wid] = w
            node.workers.add(wid)
            node.agent.send({
                "t": "spawn_worker", "wid": wid, "tpu": tpu,
                "node_id": node.node_id.hex()})
            return w
        env = build_worker_env(
            store_path=self.store_path, head_addr=self.listener_addr,
            head_family="AF_UNIX", authkey_hex=self._authkey.hex(),
            wid=wid, node_id_hex=node.node_id.hex(), tpu=tpu,
            spill_dir=self.spill.dir)
        log = open(os.path.join(self.session_dir, f"worker-{wid}.log"), "wb")
        # fork under the runtime lock is deliberate: wid allocation and
        # the workers-table insert must be atomic with the scheduling
        # pass that decided to spawn (dropping the lock here would let a
        # concurrent pass double-assign the bundle). The local-process
        # path only runs on the head node — agent-backed nodes (the
        # scale path) take the non-blocking send branch above.
        proc = subprocess.Popen(  # graftlint: disable=GL012,GL013
            [sys.executable, "-m", "ray_tpu.core.worker"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            start_new_session=True)
        w = WorkerInfo(wid, node.node_id, proc, tpu)
        w.pending_spec = None
        w.pending_actor = None
        self.workers[wid] = w
        node.workers.add(wid)
        # watchdog: a worker that dies before (or without) connecting would
        # otherwise never trigger the recv-loop EOF path
        threading.Thread(target=self._watch_proc, args=(w,),
                         daemon=True, name=f"rtpu-watch-{wid}").start()
        return w

    def _watch_proc(self, w: WorkerInfo):
        try:
            w.proc.wait()
        except Exception:
            pass  # reaped elsewhere; death path runs below
        self._on_worker_death(w.wid)

    def _returns_complete_locked(self, spec) -> bool:
        """All of a task's returns already produced (sealed in shm OR
        spilled to disk, and not failed) — the call completed even if its
        done message never arrived."""
        if not spec.return_ids:
            return False
        for oid in spec.return_ids:
            e = self.directory.get(oid)
            if e is not None and e.state == FAILED:
                return False
            if e is not None and e.state == SPILLED:
                continue
            if not self.store.contains(oid):
                return False
        return True

    def _on_worker_death(self, wid: str):
        with self.lock:
            if self._shutdown:
                # shutdown() already tears every worker down; running the
                # death path now would race the closing object store
                return
            w = self.workers.get(wid)
            if w is None or w.state == "dead":
                return
            w.state = "dead"
            # reclaim store state the dead process can no longer release:
            # unsealed creates (it died mid-put) and leaked read pins
            try:
                self.store.reclaim_pid(w.proc.pid)
            except Exception:
                pass  # store closing; pins die with it
            # zero the dead process's per-proc gauge series (host:pid
            # label, llm/telemetry.py): gauges are last-write-wins with
            # no owner left to update them, so a killed replica's last
            # kv_utilization/occupancy would pin /metrics forever. A
            # same-pid collision from another host self-heals on that
            # process's next ~2s flush tick.
            try:
                suffix = f":{w.proc.pid}"
                for rec in self.user_metrics.values():
                    if rec.get("kind") != "gauge":
                        continue
                    for key, val in rec["series"].items():
                        if val and any(k == "proc"
                                       and str(v).endswith(suffix)
                                       for k, v in key):
                            rec["series"][key] = 0.0
            except Exception:
                pass  # gauge cleanup must never block reaping
            # and its refcount interest (it will never send ref_drop)
            for oid in [o for o, s in self.interest.items() if wid in s]:
                self._ref_drop_locked(oid, wid)
            # store-path rpc replies it will never read (a peer killed
            # between sending an rpc/dir_query and consuming the reply)
            for oid in self._rpc_reply_pins.pop(wid, ()):
                try:
                    if self.store.contains(oid):
                        self.store.delete(oid)
                except Exception:
                    pass  # store closing; the reply dies with it
            node = self.nodes.get(w.node_id)
            if node:
                node.workers.discard(wid)
            if not w.blocked:
                self._release_to_node(w)
            # pipelined-but-not-started tasks just go back to pending
            if w.queued:
                for s, _n in w.queued:
                    self.pending.append(s)
                w.queued.clear()
            # running normal task?
            spec = w.current
            if spec is not None and not spec.is_actor_task:
                if self._returns_complete_locked(spec):
                    # results all sealed: the task completed, only its done
                    # message lost the race with the death — don't clobber
                    self.counters["tasks_finished"] += 1
                    self._record_task_locked(spec, "FINISHED",
                                             finished_at=time.time())
                    for oid in spec.return_ids:
                        e = self.directory.get(oid)
                        if e is not None and e.state == PENDING:
                            e.state = READY
                        self._maybe_free_locked(oid)
                    self._drop_task_dep_interest_locked(spec)
                else:
                    self._handle_failed_task_locked(
                        spec, exc.WorkerCrashedError(
                            f"worker {wid} died while running {spec.name}"))
            # actor hosted here?
            if w.actor_id is not None:
                self._on_actor_worker_death_locked(w.actor_id, wid)
            self._schedule_locked()
            self.cv.notify_all()
        # outside self.lock (own short lock): a dead publisher's shared-
        # directory hints are swept so stale entries die with the worker
        # instead of lingering until every reader validates them
        try:
            self.dirs.sweep_owner(wid)
        except Exception:
            pass  # hint cleanup must never block reaping
        try:
            w.proc.wait(timeout=1)
        except Exception:
            pass  # slow exit; the OS reaps the zombie

    def _release_to_node(self, w: WorkerInfo):
        node = self.nodes.get(w.node_id)
        if node and node.alive and w.holding:
            for k, v in w.holding.items():
                node.resources_avail[k] = node.resources_avail.get(k, 0) + v
        if w.holding_bundle:
            pg_id, idx, res = w.holding_bundle
            pg = self.pgs.get(pg_id)
            if pg and pg.state == "created":
                b = pg.bundles[idx]
                for k, v in res.items():
                    b.avail[k] = b.avail.get(k, 0) + v

    def _reacquire_from_node(self, w: WorkerInfo):
        node = self.nodes.get(w.node_id)
        if node and node.alive and w.holding:
            for k, v in w.holding.items():
                node.resources_avail[k] = node.resources_avail.get(k, 0) - v
        if w.holding_bundle:
            pg_id, idx, res = w.holding_bundle
            pg = self.pgs.get(pg_id)
            if pg and pg.state == "created":
                b = pg.bundles[idx]
                for k, v in res.items():
                    b.avail[k] = b.avail.get(k, 0) - v

    # ------------------------------------------------------------------ #
    # object directory + lineage (reference: reference_count.h:73,
    # object_recovery_manager.h:43)
    # ------------------------------------------------------------------ #

    def put(self, value: Any, pin: bool = True) -> ObjectRef:
        oid = ObjectID.from_random()
        ref = self.put_at(oid, value)
        if pin:
            with self.lock:
                e = self.directory.get(oid)
                if e is not None and e.state == READY and \
                        oid not in self._pinned:
                    # store-level pin so LRU eviction never drops a live
                    # ray.put (released when the refcount frees the object)
                    if self.store.get_raw(oid, timeout_ms=0) is not None:
                        self._pinned.add(oid)
        return ref

    def expect(self, oid: ObjectID) -> None:
        """No-op: deferred oids need no pre-registration in the shared-store
        runtimes (get() already blocks). LocalModeRuntime overrides."""

    def put_at(self, oid: ObjectID, value: Any,
               is_exception: bool = False) -> ObjectRef:
        """Write `value` under a pre-allocated id (deferred-resolution refs).
        Objects the shm store can't hold spill to disk. Refs pickled inside
        `value` become containment edges so they outlive one transfer."""
        from .ref import capture_serialized_refs
        with capture_serialized_refs() as inner_ids:
            spilled = self.store.put_or_spill(oid, value, is_exception,
                                              self.spill)
        state = SPILLED if spilled else READY
        with self.lock:
            self.directory[oid] = DirEntry(state)
            if inner_ids:
                self._register_contained_locked(oid, inner_ids)
        return ObjectRef(oid)

    def _register_contained_locked(self, outer: ObjectID,
                                   inner_ids: list[ObjectID]):
        holder = f"obj:{outer.hex()}"
        self.contained.setdefault(outer, []).extend(inner_ids)
        for inner in inner_ids:
            self.interest.setdefault(inner, set()).add(holder)

    # -- refcounting (reference: reference_count.h:73) ---------------------

    def ref_created(self, oid: ObjectID, from_transfer: bool):
        if not from_transfer:
            # submit fast path: refs of a still-queued spec count under
            # the (cheap, uncontended) presumed lock; the pump migrates
            # the count and registers interest when it admits the spec
            with self._presumed_lock:
                c = self._presumed.get(oid)
                if c is not None:
                    self._presumed[oid] = c + 1
                    return
        with self.lock:
            c = self._local_refs.get(oid, 0)
            self._local_refs[oid] = c + 1
            if c == 0 or from_transfer:
                self._ref_add_locked(oid, "driver", from_transfer)

    def ref_deleted(self, oid: ObjectID):
        # __del__ context: must not mutate interest/directory synchronously
        # (a GC pass can fire inside code iterating those dicts on this
        # very thread); enqueue and let the drop thread do the bookkeeping
        self._drop_q.put(oid)

    def _drop_loop(self):
        while True:
            oid = self._drop_q.get()
            if oid is None:
                return
            try:
                # presumed drops settle under the presumed lock ALONE
                # (never nested inside self.lock here — the pump nests the
                # other way around); a ref created before the pump admits
                # its spec and dropped after is attributed here by oid,
                # which can transiently mis-attribute when the same oid
                # also has transfer-created refs — worst case a leaked
                # interest entry, never a premature free
                handled = False
                with self._presumed_lock:
                    c = self._presumed.get(oid)
                    if c is not None:
                        handled = True
                        if c <= 1:
                            self._presumed.pop(oid, None)
                            # every local ref died before the pump saw
                            # the spec: interest must never be registered
                            self._dropped_early.add(oid)
                        else:
                            self._presumed[oid] = c - 1
                if handled:
                    continue
                with self.lock:
                    c = self._local_refs.get(oid, 0) - 1
                    if c <= 0:
                        self._local_refs.pop(oid, None)
                        self._ref_drop_locked(oid, "driver")
                    else:
                        self._local_refs[oid] = c
            except Exception:
                traceback.print_exc()

    def ref_serialized(self, oid: ObjectID):
        with self.lock:
            self.xfer_pins[oid] = self.xfer_pins.get(oid, 0) + 1

    def _ref_add_locked(self, oid: ObjectID, holder: str,
                        from_transfer: bool):
        self.interest.setdefault(oid, set()).add(holder)
        if oid in self._ready_ok and not self.store.contains(oid):
            # an ALIVE actor's ready oid gains an observer: seal the
            # "ok" payload now (nothing is stored up front — see
            # _ready_ok) so ray.get(h.__ray_ready__()) resolves; this
            # ref's refcount frees it, and a later re-observation
            # re-materializes
            try:
                self.store.put(oid, True)
            except Exception:
                pass  # store full: get() falls back to ensure/locate
            else:
                if oid not in self.directory:
                    self.directory[oid] = DirEntry(READY)
        # not popped: the entry persists (one small dict slot per dead
        # actor) so every FUTURE ref — including one deserialized after
        # the first observer's error object was freed — re-materializes
        brief = self._ready_failed.get(oid)
        if brief is not None and not self.store.contains(oid):
            # a dead actor's payload-less ready oid gains its first
            # observer: materialize the death error now so get() raises
            # it instead of spinning on a missing object; this ref's
            # refcount frees it like any task result. (Scoped to ready
            # oids via the registry — a generic FAILED entry may hold
            # its real, differently-typed error on a remote store.)
            self._store_error(oid, exc.ActorDiedError(brief))
            if oid not in self.directory:
                # the original entry may have been freed by an earlier
                # ready ref's drop; without one, _maybe_free could
                # never reclaim the error object we just stored
                self.directory[oid] = DirEntry(FAILED)
        if from_transfer:
            # clamp at 0: deserializations of refs embedded in STORED
            # objects carry no pin (containment edges protect those), and
            # a pin must never be cancelled by an unrelated deserialize
            n = self.xfer_pins.get(oid, 0) - 1
            if n <= 0:
                self.xfer_pins.pop(oid, None)
            else:
                self.xfer_pins[oid] = n

    def _ref_drop_locked(self, oid: ObjectID, holder: str):
        s = self.interest.get(oid)
        if s is not None:
            s.discard(holder)
            if not s:
                self.interest.pop(oid, None)
        self._maybe_free_locked(oid)

    def _maybe_free_locked(self, oid: ObjectID):
        """Free payload + metadata once the object is unreachable: no
        process holds a ref, no serialized copy is in flight, and no task
        is about to produce it."""
        if oid in self.interest or self.xfer_pins.get(oid, 0) > 0:
            return
        e = self.directory.get(oid)
        if e is None or e.state == PENDING:
            return
        e_locs = e.locations
        self.directory.pop(oid, None)
        # copies on own-store nodes are freed by their agents (the head
        # can't reach those stores); reference: FreeObjects fanout
        if e_locs:
            for n in self.nodes.values():
                if (n.agent is not None and n.own_store
                        and n.node_id.hex() in e_locs):
                    n.agent.send({"t": "free_objects",
                                  "oids": [oid.binary()]})
        if oid in self._pinned:
            self._pinned.discard(oid)
            try:
                self.store.release(oid)
            except Exception:
                pass  # store closing; the pin dies with it
        try:
            self.store.delete(oid)
        except Exception:
            pass  # already evicted
        self.spill.delete(oid)
        self.xfer_pins.pop(oid, None)
        # the freed outer no longer keeps its inners alive
        holder = f"obj:{oid.hex()}"
        for inner in self.contained.pop(oid, []):
            self._ref_drop_locked(inner, holder)

    def _store_error(self, oid: ObjectID, err: BaseException):
        try:
            self.store.delete(oid)
            self.store.put(oid, err, is_exception=True)
        except Exception:
            pass  # store full/closing; directory marks FAILED

    def _ensure_available_locked(self, oid: ObjectID):
        """If `oid` was evicted, restore it from spill or resubmit its
        producing task (lineage)."""
        e = self.directory.get(oid)
        if e is not None and e.state == SPILLED:
            # spilled objects are served from disk: the head reads the file
            # directly and workers fall back to the shared spill directory
            # (restoring into the store here would do multi-GB IO under the
            # runtime lock and lose spill-awareness on later eviction)
            return
        if e is None or e.state != READY or self.store.contains(oid):
            return
        if e.locations:
            # a live copy on another node satisfies consumers via the
            # transfer service — reconstruction would DOUBLE-RUN the
            # producer (wrong for side-effecting tasks)
            alive = {n.node_id.hex() for n in self.nodes.values()
                     if n.alive}
            live_copies = e.locations & alive
            if live_copies:
                return
            e.locations = None  # every holder died: fall through to lineage
        if e.lineage is None:
            self._store_error(oid, exc.ObjectLostError(
                f"object {oid} was evicted and has no lineage "
                "(ray_tpu.put objects are not reconstructable)"))
            e.state = FAILED
            self._sweep_failed_deps_locked()
            return
        e.state = PENDING
        spec = e.lineage
        # all sibling returns become pending again
        for rid in spec.return_ids:
            ent = self.directory.get(rid)
            if ent is not None:
                ent.state = PENDING
        self.pending.append(spec)

    # ------------------------------------------------------------------ #
    # task submission + scheduling (reference: cluster_task_manager.h:72,
    # hybrid_scheduling_policy.h:50, local_task_manager.h:60)
    # ------------------------------------------------------------------ #

    def register_renv(self, h: str, blob: bytes):
        with self.lock:
            self.renv_registry.setdefault(h, blob)

    def register_function(self, fid: str, blob: bytes):
        with self.lock:
            self.func_registry.setdefault(fid, blob)

    def _queue_submit(self, kind: str, spec: TaskSpec) -> list[ObjectRef]:
        """Driver submit fast path: mark the return oids presumed (their
        ObjectRefs count under the presumed lock, not the runtime lock),
        queue the spec, and wake the pump. Interest lands when the pump
        admits the spec — BEFORE the task can run, the same guarantee
        the v2 submit message gives remote clients."""
        with self._presumed_lock:
            for o in spec.return_ids:
                self._presumed.setdefault(o, 0)
        refs = [ObjectRef(o) for o in spec.return_ids]
        self._submit_q.append((kind, spec))
        self._sched_evt.set()
        return refs

    def _drain_submit_q(self):
        """Admit every queued driver spec: one lock acquisition and one
        deferred scheduling pass per batch (same shape as _handle_batch
        for remote clients). Single drainer at a time so specs admit in
        queue order (actor-call ordering depends on it)."""
        with self._submitq_drain_lock:
            while self._submit_q:
                batch = []
                # bounded batches: the first specs of a burst dispatch
                # after a short admission pass (workers start while the
                # rest of the burst admits), and done-processing recv
                # threads never stall behind one long lock hold
                while self._submit_q and len(batch) < 128:
                    try:
                        batch.append(self._submit_q.popleft())
                    except IndexError:
                        break
                if not batch:
                    return
                with self.lock:
                    opened = self._send_buf is None
                    if opened:
                        self._send_buf = {}
                    self._defer_sched += 1
                    try:
                        for kind, spec in batch:
                            try:
                                self._admit_driver_spec_locked(kind, spec)
                            except Exception:
                                traceback.print_exc()
                    finally:
                        self._defer_sched -= 1
                        try:
                            if self._sched_wanted and not self._defer_sched:
                                self._sched_wanted = False
                                self._schedule_locked()
                        finally:
                            if opened:
                                buf, self._send_buf = self._send_buf, None
                                self._flush_wsend_buf(buf)

    def _admit_driver_spec_locked(self, kind: str, spec: TaskSpec):
        # migrate presumed ref counts into the lock-guarded table and
        # register the driver's interest — exactly what _handle_msg
        # "submit" does for a remote client's return oids
        with self._presumed_lock:
            settled = []
            for oid in spec.return_ids:
                cnt = self._presumed.pop(oid, None)
                early = oid in self._dropped_early
                self._dropped_early.discard(oid)
                settled.append((oid, cnt, early))
        for oid, cnt, early in settled:
            if early:
                continue  # every ref died pre-admission: no interest
            if cnt:
                self._local_refs[oid] = self._local_refs.get(oid, 0) + cnt
            self._ref_add_locked(oid, "driver", False)
        if kind == "actor":
            self._submit_actor_task_locked(spec)
        else:
            self._submit_locked(spec)

    def submit_task(self, spec: TaskSpec) -> list[ObjectRef]:
        if self._submitq_on and not self._shutdown:
            return self._queue_submit("task", spec)
        with self.lock:
            # interest BEFORE the task can run: a fast task finishing
            # between submit and ref construction must not see an
            # unreferenced result and free it
            refs = [ObjectRef(o) for o in spec.return_ids]
            bw = self._burst_window
            if bw > 0.0:
                now = time.monotonic()
                burst = now - self._last_submit_ts < bw
                self._last_submit_ts = now
                if burst:
                    # burst submission (in-process driver): defer the
                    # scheduling pass to the pump so one pass — and one
                    # batched pipe frame per worker — serves the whole
                    # burst. An isolated submit (interval >= the window,
                    # i.e. anything with a round-trip in between) still
                    # schedules inline with zero added latency.
                    self._defer_sched += 1
                    try:
                        self._submit_locked(spec)
                    finally:
                        self._defer_sched -= 1
                    if self._sched_wanted and not self._defer_sched:
                        self._sched_wanted = False
                        self._sched_evt.set()
                    return refs
            self._submit_locked(spec)
        return refs

    def _record_task_locked(self, spec, state: str, **extra):
        # every transition hits the flight ring, even ones whose state-
        # API record was FIFO-evicted — the recorder is the always-on
        # view of task flow, the records dict is the bounded query view
        flight.evt(flight.TASK_STATE, flight.lo48(spec.task_id),
                   flight.TASK_STATES.get(state, -1))
        rec = self.task_records.get(spec.task_id)
        if rec is None:
            if state != "PENDING":
                # record was FIFO-evicted: don't resurrect it with a bogus
                # submitted_at — honest absence beats wrong timestamps
                return
            rec = {"task_id": spec.task_id.hex(), "name": spec.name,
                   "state": state, "is_actor_task": spec.is_actor_task,
                   "submitted_at": time.time()}
            self.task_records[spec.task_id] = rec
            while len(self.task_records) > self.task_records_max:
                self.task_records.popitem(last=False)
        rec["state"] = state
        if state in ("RUNNING", "RETRYING") and rec.get("stuck"):
            # a fresh attempt starts clean: without this, a retried task
            # is falsely listed stuck the moment it re-enters RUNNING
            # (stale flag + stale stack from the previous attempt), and
            # a retry that genuinely wedges later could never be
            # re-flagged with a fresh stack
            for k in ("stuck", "stuck_at", "threshold_s", "ewma_s",
                      "stack"):
                rec.pop(k, None)
        rec.update(extra)
        if self._event_file is not None:
            try:
                self._event_file.write(json.dumps(
                    {"ts": time.time(), "task_id": rec["task_id"],
                     "name": rec["name"], "state": state, **{
                         k: v for k, v in extra.items()
                         if isinstance(v, (int, float, str))}}) + "\n")
            except (OSError, ValueError):
                self._event_file = None  # disk gone: stop exporting

    def _submit_locked(self, spec: TaskSpec):
        self.counters["tasks_submitted"] += 1
        self._record_task_locked(spec, "PENDING")
        for oid in spec.return_ids:
            self.directory[oid] = DirEntry(PENDING, lineage=spec)
        # the task holds interest in its args until it terminally completes
        # (covers re-deserialization on retries; the submitter may drop its
        # refs right after submit)
        holder = f"task:{spec.task_id.hex()}"
        for d in spec.dep_oids:
            self.interest.setdefault(d, set()).add(holder)
        if spec.is_actor_task:
            self._route_actor_task_locked(spec)
        elif spec.dep_oids and self._deps_state_locked(spec) == "failed":
            # dep already failed at submit: fail fast — a blocked bucket
            # head would otherwise hide this task from the next pass
            self._handle_failed_task_locked(
                spec, self._collect_dep_error_locked(spec), retryable=False)
        else:
            self.pending.append(spec)
            self._schedule_locked()

    def _feasible(self, node: NodeInfo, res: dict[str, float]) -> bool:
        return node.alive and all(
            node.resources_total.get(k, 0) >= v for k, v in res.items())

    def _has_avail(self, node: NodeInfo, res: dict[str, float]) -> bool:
        return node.alive and all(
            node.resources_avail.get(k, 0) >= v - 1e-9 for k, v in res.items())

    @staticmethod
    def _labels_ok(node: NodeInfo, spec) -> bool:
        sel = getattr(spec, "label_selector", None)
        if not sel:
            return True
        return all(node.labels.get(k) == v for k, v in sel.items())

    def _pick_node_locked(self, spec) -> Optional[NodeInfo]:
        res = spec.resources
        if spec.pg_id is not None:
            pg = self.pgs.get(spec.pg_id)
            if pg is None or pg.state != "created":
                return None
            idxs = ([spec.pg_bundle_index] if spec.pg_bundle_index >= 0
                    else range(len(pg.bundles)))
            for i in idxs:
                b = pg.bundles[i]
                node = self.nodes.get(b.node_id)
                if node is None or not node.alive \
                        or not self._labels_ok(node, spec):
                    continue
                if all(b.avail.get(k, 0) >= v - 1e-9 for k, v in res.items()):
                    return node
            return None
        if spec.node_affinity is not None:
            node = self.nodes.get(NodeID(spec.node_affinity))
            if node and self._has_avail(node, res) \
                    and self._labels_ok(node, spec):
                return node
            if spec.node_affinity_soft:
                pass  # fall through to normal policy
            else:
                return None
        alive = [n for n in self.nodes.values()
                 if n.alive and self._labels_ok(n, spec)]
        if not alive:
            return None
        if spec.scheduling_strategy == "SPREAD":
            order = alive[self._spread_rr % len(alive):] + \
                alive[:self._spread_rr % len(alive)]
            for n in order:
                if self._has_avail(n, res):
                    self._spread_rr += 1
                    return n
            return None
        # hybrid: pack onto head/local until 50% utilized, then least-utilized
        head = self.head_node
        from .config import cfg as _cfg
        if self._labels_ok(head, spec) and self._has_avail(head, res) and \
                head.utilization() < _cfg.scheduler_spread_threshold:
            return head
        best, best_u = None, 2.0
        for n in alive:
            if self._has_avail(n, res) and n.utilization() < best_u:
                best, best_u = n, n.utilization()
        return best

    def _deps_state_locked(self, spec) -> str:
        """-> 'ready' | 'wait' | 'failed'."""
        for d in spec.dep_oids:
            e = self.directory.get(d)
            if e is not None and e.state == FAILED:
                return "failed"
            if e is not None and e.state == SPILLED:
                # satisfiable from disk: workers fall back to the shared
                # spill directory when the store misses
                continue
            if not self.store.contains(d):
                if e is not None and e.state == READY and e.locations and \
                        any(n.alive and n.node_id.hex() in e.locations
                            for n in self.nodes.values()):
                    # a live copy on another node; the executing worker
                    # pulls it via the transfer service (every worker can
                    # fetch — see worker._try_fetch)
                    continue
                if e is not None and e.state == READY:
                    self._ensure_available_locked(d)  # evicted → reconstruct
                return "wait"
        return "ready"

    def _schedule_locked(self):
        """Drain what's dispatchable. Per-shape bucket queues make this
        O(buckets + dispatched + dep-waiters) per pass: once a bucket's
        head can't place, the rest of that bucket can't either (identical
        placement signature, and capacity only shrinks as the pass
        dispatches), so the bucket is skipped whole. Dep-waiting tasks are
        set aside per pass so a blocked head never hides a ready task
        behind it. All control messages to one worker during the pass
        coalesce into ONE pipe write (a burst refilling a 4-deep pipeline
        costs one syscall, not four)."""
        if self._shutdown:
            return
        if self._defer_sched:
            # inside a batch frame / deferred submit: one pass at the end
            # serves every request made during it
            self._sched_wanted = True
            return
        if self._send_buf is None:
            self._send_buf = {}
            try:
                self._schedule_pass_locked()
            finally:
                buf, self._send_buf = self._send_buf, None
                self._flush_wsend_buf(buf)
            return
        self._schedule_pass_locked()

    def _flush_wsend_buf(self, buf: dict) -> None:
        """Ship the per-worker message batches accumulated by _wsend —
        one pipe write per worker per pass/batch."""
        dead = []
        for w, msgs in buf.items():
            msg = (msgs[0] if len(msgs) == 1
                   else {"t": "batch", "msgs": msgs})
            if not w.send(msg):
                dead.append(w.wid)
        for wid in dead:
            self._on_worker_death(wid)

    def _wsend(self, w: WorkerInfo, msg) -> bool:
        """Send to a worker, coalescing into the current scheduling
        pass's per-worker batch when one is open."""
        buf = self._send_buf
        if buf is not None:
            buf.setdefault(w, []).append(msg)
            return True  # delivery failures surface at flush
        return w.send(msg)

    def _dispatch_possible_locked(self) -> bool:
        """Cheap saturation check before walking every bucket: can ANY
        pending plain task possibly dispatch this pass? True when a
        zero-resource shape is pending (always placeable), a PLACEMENT
        GROUP task is pending (bundles hold their own reserved capacity,
        invisible in node.resources_avail — gating on node availability
        would deadlock a PG that reserved a whole node), a node has any
        free resource, or a busy worker has an open pipeline slot.
        O(nodes + workers) instead of a full pass with per-task dep
        checks — what a burst of submits pays per task once the pool is
        saturated. Conservative by construction: a true here only means
        the full pass runs (possibly finding nothing). Accepted
        semantics: reconstruction of an evicted dep kicks at the next
        capacity-freeing event rather than instantly — while saturated
        the regenerating task could not run anyway (failed-dep
        propagation is unaffected: submit fail-fast plus the
        failure-event sweep run outside the pass)."""
        from .config import cfg as _cfg
        for key in self.pending.buckets:
            if not key[0] or key[1] is not None:
                return True
        for n in self.nodes.values():
            if n.alive and any(v > 1e-9 for v in n.resources_avail.values()):
                return True
        depth = _cfg.worker_pipeline_depth
        if depth > 0:
            for w in self.workers.values():
                if (w.state == "busy" and not w.blocked
                        and w.conn is not None and w.actor_id is None
                        and len(w.queued) < depth):
                    return True
        return False

    def _schedule_pass_locked(self):
        if self.pending.buckets and not self._dispatch_possible_locked():
            return
        flight.evt(flight.SCHED_BEGIN)
        try:
            self._schedule_pass_body_locked()
        finally:
            flight.evt(flight.SCHED_END)

    def _schedule_pass_body_locked(self):
        for key in list(self.pending.buckets):
            dq = self.pending.buckets.get(key)
            if not dq:
                continue
            dep_wait: list = []
            while dq:
                spec = dq.popleft()
                deps = self._deps_state_locked(spec)
                if deps == "failed":
                    err = self._collect_dep_error_locked(spec)
                    self._handle_failed_task_locked(spec, err,
                                                    retryable=False)
                    continue
                if deps == "wait":
                    dep_wait.append(spec)
                    continue
                node = self._pick_node_locked(spec)
                w = None if node is None else \
                    self._acquire_worker_locked(node, spec)
                if w is None:
                    if self._pipeline_dispatch_locked(spec):
                        continue
                    # same signature ⇒ the rest of the bucket can't place
                    # either this pass; stop (tasks behind the head are
                    # NOT rescanned — failed-dependency propagation is
                    # event-driven via _sweep_failed_deps_locked, so a
                    # blocked head can't hide a doomed task)
                    dq.appendleft(spec)
                    break
                self._dispatch_locked(w, spec)
            # the failure sweep (run from _handle_failed_task_locked above)
            # may have emptied-and-removed THIS bucket mid-pass: only touch
            # the dict entry if it is still our deque, and re-route
            # dep-waiters through append() otherwise so they land in a
            # live bucket instead of an orphaned one
            if dep_wait:
                if self.pending.buckets.get(key) is dq:
                    dq.extend(dep_wait)
                else:
                    for s in dep_wait:
                        self.pending.append(s)
            if not dq and self.pending.buckets.get(key) is dq:
                del self.pending.buckets[key]

    def _sweep_failed_deps_locked(self):
        """Fail every pending task whose dependency just failed. Called on
        failure EVENTS (object marked FAILED), not per scheduling pass —
        keeping the hot path O(buckets) while failures still propagate
        promptly past placement-blocked bucket heads. Iterates to a
        fixpoint (a failed task's returns can doom further dependents);
        the guard flattens the recursion through
        _handle_failed_task_locked."""
        if self._sweeping_failed_deps:
            return
        self._sweeping_failed_deps = True
        try:
            while True:
                doomed = [
                    spec for spec in self.pending
                    if spec.dep_oids
                    and self._deps_state_locked(spec) == "failed"]
                if not doomed:
                    return
                for spec in doomed:
                    try:
                        self.pending.remove(spec)
                    except ValueError:
                        continue
                    err = self._collect_dep_error_locked(spec)
                    self._handle_failed_task_locked(spec, err,
                                                    retryable=False)
        finally:
            self._sweeping_failed_deps = False

    def _acquire_worker_locked(self, node: NodeInfo, spec) -> Optional[WorkerInfo]:
        from .runtime_env import env_hash as _env_hash
        want_env = _env_hash(getattr(spec, "runtime_env", None))
        for wid in node.workers:
            w = self.workers[wid]
            if w.state == "idle" and w.conn is not None and w.tpu == (
                    spec.resources.get("TPU", 0) > 0) and \
                    w.env_hash == want_env:
                self._mark_busy(w, node, spec)
                return w
        # blocked workers don't count against the cap: their CPU is
        # released and the task that blocked them may be waiting on
        # exactly the child task this spawn would run (reference: the
        # worker pool starts a replacement when a worker blocks in
        # ray.get, so nested task trees can't wedge the pool)
        live = sum(1 for wid in node.workers
                   if self.workers[wid].state != "dead"
                   and not self.workers[wid].blocked)
        if live >= node.max_workers:
            # pool full of idle workers dedicated to OTHER runtime envs?
            # reap one so this env can make progress (reference: the worker
            # pool kills idle dedicated workers under starvation)
            victim = next(
                (self.workers[wid] for wid in node.workers
                 if self.workers[wid].state == "idle"
                 and self.workers[wid].env_hash != want_env), None)
            if victim is None:
                return None
            victim.send({"t": "exit"})
            self._on_worker_death_locked_prep(victim)
            live -= 1
        if live < node.max_workers:
            w = self._spawn_worker_locked(
                node, tpu=spec.resources.get("TPU", 0) > 0)
            # not yet connected; dispatch happens when it registers
            self._mark_busy(w, node, spec, dispatch_later=True)
            return w
        return None

    def _on_worker_death_locked_prep(self, w: WorkerInfo):
        """Mark an intentionally-reaped worker dead under the lock (the
        recv-loop EOF will find state=='dead' and no-op)."""
        w.state = "dead"
        for oid in [o for o, s in self.interest.items() if w.wid in s]:
            self._ref_drop_locked(oid, w.wid)
        node = self.nodes.get(w.node_id)
        if node:
            node.workers.discard(w.wid)

    def _mark_busy(self, w: WorkerInfo, node: NodeInfo, spec,
                   dispatch_later: bool = False):
        w.state = "busy" if not dispatch_later else w.state
        res = spec.resources
        if spec.pg_id is not None:
            pg = self.pgs[spec.pg_id]
            idxs = ([spec.pg_bundle_index] if spec.pg_bundle_index >= 0
                    else range(len(pg.bundles)))
            for i in idxs:
                b = pg.bundles[i]
                if b.node_id == node.node_id and all(
                        b.avail.get(k, 0) >= v - 1e-9 for k, v in res.items()):
                    for k, v in res.items():
                        b.avail[k] -= v
                    w.holding_bundle = (spec.pg_id, i, dict(res))
                    break
        else:
            for k, v in res.items():
                node.resources_avail[k] = node.resources_avail.get(k, 0) - v
            w.holding = dict(res)

    def _dispatch_locked(self, w: WorkerInfo, spec):
        w.current = spec
        if w.conn is None:
            # newly spawned; stash the task — dispatched on register
            w.state = "starting"
            w.pending_spec = spec
            return
        w.state = "busy"
        w.current_started = time.monotonic()
        if spec.runtime_env and w.env_hash is None:
            self._ship_renv_locked(w, spec.runtime_env)
        self._ship_function_locked(w, spec.func_id)
        self._record_task_locked(spec, "RUNNING", worker=w.wid,
                                 node=w.node_id.hex(),
                                 started_at=time.time())
        self.events.append({"name": spec.name, "cat": "task", "ph": "B",
                            "pid": w.wid, "ts": time.time() * 1e6,
                            "tid": spec.task_id.hex()[:8]})
        if not self._wsend(w, {"t": "task", "spec": spec}):
            self._on_worker_death(w.wid)

    def _pipeline_dispatch_locked(self, spec) -> bool:
        """Queue a same-shape plain task behind a busy worker's current
        task (reference analog: worker-lease reuse on the direct task
        transport — the done->dispatch round-trip leaves the worker's
        critical path because the next task message is already in its
        pipe). The queued task reuses the running task's resource lease,
        so nothing extra is charged; eligibility is strict: identical
        resource shape, same runtime env, no placement constraints."""
        from .config import cfg as _cfg
        depth = _cfg.worker_pipeline_depth
        if depth <= 0 or spec.pg_id is not None \
                or spec.node_affinity is not None \
                or spec.scheduling_strategy == "SPREAD":
            return False
        env_hash = (spec.runtime_env or {}).get("hash")
        best = None
        for w in self.workers.values():
            if (w.state == "busy" and not w.blocked and w.conn is not None
                    and w.actor_id is None and w.current is not None
                    and not w.current.is_actor_task
                    and len(w.queued) < depth
                    and w.current.resources == spec.resources
                    and w.env_hash == env_hash
                    and self._labels_ok(self.nodes[w.node_id], spec)
                    and (best is None or len(w.queued) < len(best.queued))):
                best = w
        if best is None:
            return False
        self._ship_function_locked(best, spec.func_id)
        nonce = f"{best.wid}:{best.send_seq}"
        best.send_seq += 1
        # only reachable from inside a scheduling pass, so _wsend always
        # buffers here: a pipe failure surfaces at the pass flush, which
        # requeues best.queued via _on_worker_death
        self._wsend(best, {"t": "task", "spec": spec, "n": nonce})
        best.queued.append((spec, nonce))
        return True

    def _promote_queued_locked(self, w: WorkerInfo):
        """The previous task's done message means the worker is already
        executing the head of its queue: transfer the lease head-side."""
        nxt, _nonce = w.queued.popleft()
        w.current = nxt
        w.state = "busy"
        w.current_started = time.monotonic()
        self._record_task_locked(nxt, "RUNNING", worker=w.wid,
                                 node=w.node_id.hex(),
                                 started_at=time.time())
        self.events.append({"name": nxt.name, "cat": "task", "ph": "B",
                            "pid": w.wid, "ts": time.time() * 1e6,
                            "tid": nxt.task_id.hex()[:8]})

    def _steal_queued_locked(self, w: WorkerInfo):
        """Pull pipelined tasks back from a worker (it blocked or is
        wanted for other work): the worker is told to skip them and the
        specs re-enter the pending queues. Prevents the deadlock where a
        blocked task waits on a result only its own queued successor
        would produce."""
        if not w.queued:
            return
        stolen = list(w.queued)
        w.queued.clear()
        w.send({"t": "steal", "nonces": [n for _, n in stolen]})
        for s, _ in stolen:
            self.pending.append(s)

    def merge_user_metrics(self, rows: list) -> None:
        """Fold user-metric deltas from any process into the head store
        (util/metrics.py; counters/histogram buckets SUM, gauges
        last-write-wins)."""
        with self.lock:
            store = self.user_metrics
            for kind, name, desc, key, value, add in rows:
                rec = store.setdefault(
                    name, {"kind": kind, "desc": desc, "series": {}})
                if add:
                    rec["series"][key] = rec["series"].get(key, 0.0) + value
                else:
                    rec["series"][key] = value

    def user_metrics_dump(self) -> dict:
        """RPC: the merged user-metric store (remote drivers render their
        own Prometheus text from it)."""
        with self.lock:
            return {n: {"kind": r["kind"], "desc": r["desc"],
                        "series": dict(r["series"])}
                    for n, r in self.user_metrics.items()}

    # -- metrics plane (ray_tpu/obs): TSDB history + SLOs + signals ---- #

    def _obs(self):
        if self.obs is None:
            raise RuntimeError(
                "metrics TSDB disabled (cfg.tsdb_enable=0); history/SLO "
                "queries need the head scraper")
        return self.obs

    def metrics_history(self, name: str, tags=None, window_s=None,
                        quantiles=None, group_by=None) -> dict:
        """RPC: range-query the head TSDB. With ``quantiles``, also fold
        the matching histogram bucket series into windowed quantile
        values (state.metrics_history / cli top / dashboard). With
        ``group_by`` (label names), additionally return per-group
        rate/quantile aggregates under "groups" — one round-trip serves
        a whole `cli top` column instead of one RPC per deployment."""
        obs = self._obs()
        tags = dict(tags) if tags else None
        out = {
            "name": name,
            "kind": obs.tsdb.kind_of(name),
            "series": obs.tsdb.query(name, tags, window_s),
            "scrape_s": obs.tsdb.scrape_s,
        }
        qs = tuple(float(q) for q in quantiles) if quantiles else None
        if qs:
            out["quantiles"] = dict(zip(
                (str(q) for q in qs),
                obs.tsdb.histogram_quantiles(name, tags, window_s, qs)))
        if out["kind"] == "counter":
            out["rate_per_s"] = obs.tsdb.rate(name, tags, window_s)
        if group_by:
            gb = tuple(group_by)
            keys: list[dict] = []
            for s in out["series"]:
                key = dict(s["key"])
                # only labels the series actually carries: a "" filler
                # could never subset-match back into the TSDB
                gk = {k: key[k] for k in gb if k in key}
                if gk not in keys:
                    keys.append(gk)
            rows = []
            for gk in keys:
                # group aggregates honor the caller's tags filter too
                qtags = {**tags, **gk} if tags else (gk or None)
                row: dict = {"key": gk}
                if qs:
                    row["quantiles"] = dict(zip(
                        (str(q) for q in qs),
                        obs.tsdb.histogram_quantiles(
                            name, qtags, window_s, qs)))
                if out["kind"] == "counter":
                    row["rate_per_s"] = obs.tsdb.rate(name, qtags,
                                                      window_s)
                rows.append(row)
            out["groups"] = rows
        return out

    def metrics_names(self) -> list[str]:
        return self._obs().tsdb.names()

    def slo_report(self) -> dict:
        """RPC: the SLO engine's latest evaluation + TSDB health."""
        obs = self._obs()
        rep = dict(obs.engine.report())
        rep["tsdb"] = obs.stats()
        return rep

    def obs_signals(self, app: str, deployment: str) -> dict:
        """RPC: the autoscaler's composed scale-out signals for one
        deployment (serve controller, once per scrape period)."""
        from ..obs.scraper import autoscale_signals
        obs = self._obs()
        return autoscale_signals(obs.tsdb, obs.engine, app, deployment)

    def cache_report(self, top_k: int = 10) -> dict:
        """RPC: the cluster-wide prefix-cache heat map (cache heat
        plane). Folds three independent sources — the replicas'
        ``heat:*`` directory summaries (per-replica pools + hot
        chains), the merged metric store's ``rtpu_llm_prefix_cache_*``
        aggregates, and the per-chain ``rtpu_llm_prefix_chain_*``
        gauges — so it works whether or not the TSDB scraper is on
        (trend is attached only when it is). When the tiered KV-cache
        ran anywhere, a ``spill`` section carries the fleet's
        ``rtpu_llm_prefix_spill_*`` lifecycle totals and residency,
        and each replica row counts its directory's ``spill:``
        store-backed entries."""
        now = time.time()
        top_k = max(int(top_k), 1)
        # -- per-replica heat summaries from the shared directories ---- #
        replicas: list[dict] = []
        dir_sizes = self.dirs.stats()["directories"]
        for name in sorted(dir_sizes):
            if not name.startswith("serve:prefix:"):
                continue
            heats = self.dirs.lookup_prefix(name, "heat:")
            # spill: entries (tiered KV-cache, llm/tiering.py) share
            # the directory but are store-backed pages, not live ones
            n_spill = len(self.dirs.lookup_prefix(name, "spill:"))
            for _k, v in sorted(heats.items()):
                row = dict(v)
                ts = row.pop("ts", None)
                row["age_s"] = round(now - ts, 1) if ts else None
                row["directory_pages"] = \
                    dir_sizes[name] - len(heats) - n_spill
                row["directory_spilled"] = n_spill
                replicas.append(row)
        # -- fleet totals from the merged counter store ---------------- #
        def _total(metric: str) -> float:
            rec = self.user_metrics.get(metric)
            return sum(rec["series"].values()) if rec else 0.0
        with self.lock:
            totals = {k: _total(f"rtpu_llm_prefix_cache_{k}_total")
                      for k in ("hits", "misses", "evictions",
                                "tokens_saved", "imported_pages",
                                "exported_pages")}
            seen = totals["hits"] + totals["misses"]
            totals["hit_rate"] = round(totals["hits"] / seen, 4) \
                if seen else 0.0
            spill_totals = {
                k: _total(f"rtpu_llm_prefix_spill_{k}_total")
                for k in ("pages", "bytes", "demotions", "promotions",
                          "expired", "drops")}
            spill_totals["resident_pages"] = _total(
                "rtpu_llm_prefix_spill_resident_pages")
            spill_totals["resident_bytes"] = _total(
                "rtpu_llm_prefix_spill_resident_bytes")
            # -- cluster chain fold: sum per-chain gauges across procs - #
            chains: dict[str, dict] = {}
            for metric, field, fold in (
                    ("rtpu_llm_prefix_chain_hits", "hits", "sum"),
                    ("rtpu_llm_prefix_chain_tokens_saved",
                     "tokens_saved", "sum"),
                    ("rtpu_llm_prefix_chain_resident_pages",
                     "resident_pages", "sum"),
                    ("rtpu_llm_prefix_chain_last_hit_age_s",
                     "last_hit_age_s", "min")):
                rec = self.user_metrics.get(metric)
                for key, val in (rec["series"] if rec else {}).items():
                    labels = dict(key)
                    chain = labels.get("chain", "")
                    row = chains.setdefault(
                        chain, {"chain": chain, "replicas": 0})
                    if fold == "sum":
                        row[field] = row.get(field, 0) + val
                    else:
                        row[field] = min(row.get(field, val), val)
                    if metric.endswith("_hits"):
                        row["replicas"] += 1
        chain_rows = sorted(chains.values(),
                            key=lambda r: -r.get("hits", 0))[:top_k]
        # -- per-tenant warmth + pool rollup from replica summaries ---- #
        tenants: dict[str, dict] = {}
        pages = {"free": 0, "cached": 0, "total": 0,
                 "reclaimable_bytes": 0,
                 "spilled": 0, "spilled_bytes": 0}
        for rep in replicas:
            pool = rep.get("pool") or {}
            pages["free"] += pool.get("free_pages", 0)
            pages["cached"] += pool.get("cached_pages", 0)
            pages["total"] += pool.get("total_pages", 0)
            pages["reclaimable_bytes"] += pool.get("reclaimable_bytes", 0)
            pages["spilled"] += pool.get("spilled_pages", 0)
            pages["spilled_bytes"] += pool.get("spilled_bytes", 0)
            for c in rep.get("chains") or ():
                t = tenants.setdefault(
                    c.get("tenant", ""), {"hits": 0, "tokens_saved": 0,
                                          "resident_bytes": 0})
                t["hits"] += c.get("hits", 0)
                t["tokens_saved"] += c.get("tokens_saved", 0)
                t["resident_bytes"] += c.get("resident_bytes", 0)
        out = {"generated_at": now, "totals": totals,
               "chains": chain_rows, "replicas": replicas,
               "pages": pages, "tenants": tenants}
        if any(spill_totals.values()) or pages["spilled"]:
            out["spill"] = spill_totals
        # -- recent trend, only when the TSDB scraper is running ------- #
        if self.obs is not None:
            try:
                hr = self.obs.tsdb.rate(
                    "rtpu_llm_prefix_cache_hits_total", None, 300.0)
                mr = self.obs.tsdb.rate(
                    "rtpu_llm_prefix_cache_misses_total", None, 300.0)
                out["trend"] = {
                    "window_s": 300.0,
                    "hits_per_s": round(hr, 3),
                    "misses_per_s": round(mr, 3),
                    "hit_rate": round(hr / (hr + mr), 4)
                    if hr + mr else None,
                }
            except Exception:
                pass  # trend is garnish; the report stands without it
        return out

    def _rebalance_pipelines_locked(self):
        """A worker just went idle with nothing pending: if another worker
        has pipelined tasks stuck behind a slower one, steal that queue
        back so the idle capacity absorbs it (work stealing keeps deep
        pipelines safe under skewed task durations — even a single queued
        straggler moves, else it waits out the whole task ahead of it)."""
        if self.pending:
            return  # the scheduler will feed the idle worker anyway
        # only steal from behind a task that is demonstrably SLOW: during
        # a fast-draining burst workers dip idle between submissions, and
        # stealing then just churns messages (tasks would finish sooner
        # where they are)
        now = time.monotonic()
        victim = None
        for w in self.workers.values():
            if len(w.queued) >= 1 \
                    and now - getattr(w, "current_started", 0.0) > 0.05 \
                    and (victim is None
                         or len(w.queued) > len(victim.queued)):
                victim = w
        if victim is not None:
            self._steal_queued_locked(victim)
            self._schedule_locked()

    def _ship_renv_locked(self, w: WorkerInfo, renv_spec: dict):
        """Dedicate `w` to this runtime env: ship the env spec + its blobs
        once; the worker applies them process-wide before the task runs
        (messages are ordered on the connection)."""
        hashes = list(renv_spec.get("py_modules", []))
        if renv_spec.get("working_dir"):
            hashes.append(renv_spec["working_dir"])
        blobs = {h: self.renv_registry[h] for h in hashes
                 if h in self.renv_registry}
        missing = [h for h in hashes if h not in blobs]
        if missing:
            # blob lost (e.g. head restarted): fail loudly at dispatch
            self._wsend(w, {"t": "renv", "spec": renv_spec,
                            "blobs": blobs, "missing": missing})
        else:
            self._wsend(w, {"t": "renv", "spec": renv_spec,
                            "blobs": blobs})
        w.env_hash = renv_spec["hash"]

    def _ship_function_locked(self, w: WorkerInfo, fid: str):
        if fid and fid not in w.funcs:
            blob = self.func_registry.get(fid)
            if blob is not None:
                self._wsend(w, {"t": "func", "fid": fid, "blob": blob})
                w.funcs.add(fid)

    def _collect_dep_error_locked(self, spec) -> BaseException:
        for d in spec.dep_oids:
            e = self.directory.get(d)
            if e is not None and e.state == FAILED:
                try:
                    return self.store.get(d, timeout_ms=0)
                except StoreTimeout:
                    pass
                except BaseException as caught:  # the stored exception
                    return caught
        return exc.RayError(f"dependency of {spec.name} failed")

    def _handle_failed_task_locked(self, spec, err: BaseException,
                                   retryable: bool = True):
        if retryable and spec.retries_left > 0:
            from .config import cfg as _cfg
            spec.retries_left -= 1
            self.counters["tasks_retried"] += 1
            self._record_task_locked(spec, "RETRYING", error=repr(err))
            delay = _cfg.task_retry_delay_ms / 1000.0
            if delay > 0 and not spec.is_actor_task:
                # backoff off-lock; resubmission re-enters under it
                def _later(s=spec):
                    time.sleep(delay)
                    with self.lock:
                        if not self._shutdown:
                            self.pending.append(s)
                            self._schedule_locked()
                threading.Thread(target=_later, daemon=True).start()
            elif spec.is_actor_task:
                self._route_actor_task_locked(spec)
            else:
                self.pending.append(spec)
            return
        self.counters["tasks_failed"] += 1
        self._record_task_locked(spec, "FAILED", finished_at=time.time(),
                                 error=repr(err))
        for oid in spec.return_ids:
            self._store_error(oid, err)
            e = self.directory.get(oid)
            if e is not None:
                e.state = FAILED
                e.error_brief = repr(err)
            self._maybe_free_locked(oid)
        self._drop_task_dep_interest_locked(spec)
        self._sweep_failed_deps_locked()   # cascade to pending dependents
        self.cv.notify_all()

    def _drop_task_dep_interest_locked(self, spec):
        holder = f"task:{spec.task_id.hex()}"
        for d in spec.dep_oids:
            self._ref_drop_locked(d, holder)

    def _on_task_done(self, wid: str, msg: dict):
        with self.lock:
            w = self.workers.get(wid)
            if w is None:
                return
            task_id = msg["task_id"]
            spec = None
            if w.actor_id is not None:
                # actor method completion: resources stay held by the actor
                a = self.actors.get(w.actor_id)
                if a is not None:
                    spec = a.running.pop(task_id, None)
            else:
                spec = w.current
                if spec is not None and spec.task_id != task_id:
                    # stale done: a pipelined dispatch was stolen AFTER the
                    # worker had already started it (the steal lost the
                    # race with the predecessor's in-flight done). The
                    # worker is now executing `spec`; its real done is
                    # still coming — record nothing, release nothing.
                    self.events.append(
                        {"name": msg.get("name", "task"), "cat": "task",
                         "ph": "E", "pid": wid, "ts": time.time() * 1e6,
                         "tid": task_id.hex()[:8]})
                    self.cv.notify_all()
                    return
                w.current = None
                if w.queued and not w.blocked:
                    # lease transfers to the already-sent next task; the
                    # worker is executing it as this message is handled
                    self._promote_queued_locked(w)
                else:
                    if w.blocked:
                        w.blocked = False
                    else:
                        self._release_to_node(w)
                    w.holding = {}
                    w.holding_bundle = None
                    w.state = "idle"
                    w.idle_since = time.monotonic()
                    self._rebalance_pipelines_locked()
            self.events.append({"name": msg.get("name", "task"), "cat": "task",
                                "ph": "E", "pid": wid, "ts": time.time() * 1e6,
                                "tid": task_id.hex()[:8]})
            if spec is not None and spec.task_id == task_id:
                if msg["ok"]:
                    self.counters["tasks_finished"] += 1
                    # per-task-name runtime EWMA: the stuck-task
                    # watchdog's notion of "typical" (bounded dict —
                    # oldest name evicted, matching task_records FIFO)
                    dur = msg.get("dur")
                    if isinstance(dur, (int, float)):
                        prev = self._task_ewma.get(spec.name)
                        self._task_ewma[spec.name] = (
                            dur if prev is None
                            else 0.8 * prev + 0.2 * dur)
                        if len(self._task_ewma) > 4096:
                            self._task_ewma.pop(
                                next(iter(self._task_ewma)))
                    self._record_task_locked(spec, "FINISHED",
                                             finished_at=time.time(),
                                             duration_s=msg.get("dur"))
                    loc = self._own_store_loc_locked(w)
                    for oid in spec.return_ids:
                        e = self.directory.get(oid)
                        if e is not None and e.state == PENDING:
                            # (a SPILLED return must stay SPILLED)
                            e.state = READY
                        if e is not None and loc is not None:
                            e.add_location(loc)
                        # a consumer may have dropped its ref while we were
                        # still PENDING; re-check now that we're final
                        self._maybe_free_locked(oid)
                    # dynamic-generator items: deterministic ids + the
                    # producing spec as lineage, so they reconstruct like
                    # regular returns
                    for ob in msg.get("dynamic_items", ()):  # bytes
                        ioid = ObjectID(ob)
                        ie = self.directory.get(ioid)
                        if ie is None:
                            ie = self.directory[ioid] = DirEntry(READY)
                        ie.lineage = spec
                        if loc is not None:
                            ie.add_location(loc)
                        self._maybe_free_locked(ioid)
                    self._drop_task_dep_interest_locked(spec)
                elif msg.get("retryable"):
                    self._handle_failed_task_locked(
                        spec, exc.RayError(msg.get("err", "")), retryable=True)
                else:
                    self.counters["tasks_failed"] += 1
                    self._record_task_locked(spec, "FAILED",
                                             finished_at=time.time(),
                                             error=msg.get("err"))
                    for oid in spec.return_ids:
                        e = self.directory.get(oid)
                        if e is not None:
                            e.state = FAILED
                            e.error_brief = msg.get("err")
                        self._maybe_free_locked(oid)
                    self._drop_task_dep_interest_locked(spec)
                    self._sweep_failed_deps_locked()
            self._schedule_locked()
            self.cv.notify_all()

    # ------------------------------------------------------------------ #
    # actors (reference: gcs_actor_manager.h:352, gcs_actor_scheduler.h:150,
    # transport/actor_task_submitter.h:49)
    # ------------------------------------------------------------------ #

    def create_actor(self, spec: ActorSpec) -> None:
        with self.lock:
            self._create_actor_locked(spec)

    def _create_actor_locked(self, spec: ActorSpec):
        if spec.named:
            if spec.named in self.named_actors:
                raise ValueError(f"actor name {spec.named!r} already taken")
        self.counters["actors_created"] += 1
        a = ActorInfo(spec)
        if spec.named:
            self.named_actors[spec.named] = spec.actor_id
        self.actors[spec.actor_id] = a
        if spec.ready_oid is not None:
            self.directory[spec.ready_oid] = DirEntry(PENDING)
        self._schedule_actor_locked(a)

    def _schedule_actor_locked(self, a: ActorInfo):
        spec = a.spec
        fake = TaskSpec(  # reuse node-picking with a synthetic spec
            task_id=TaskID.from_random(), func_id="", name=spec.name,
            args_blob=b"", dep_oids=[], return_ids=[],
            resources=spec.resources, pg_id=spec.pg_id,
            pg_bundle_index=spec.pg_bundle_index,
            node_affinity=spec.node_affinity,
            node_affinity_soft=spec.node_affinity_soft,
            label_selector=spec.label_selector)
        node = self._pick_node_locked(fake)
        if node is None:
            # retry async until resources appear
            threading.Thread(target=self._retry_actor_schedule,
                             args=(a,), daemon=True).start()
            return
        w = self._spawn_worker_locked(
            node, tpu=spec.resources.get("TPU", 0) > 0)
        w.actor_id = spec.actor_id
        a.wid = w.wid
        self._mark_busy(w, node, fake)
        w.state = "starting"
        w.pending_actor = a

    def _retry_actor_schedule(self, a: ActorInfo,
                              timeout: float | None = None):
        from .config import cfg as _cfg
        if timeout is None:
            timeout = _cfg.pg_retry_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            time.sleep(0.05)
            with self.lock:
                if self._shutdown or a.state == "dead":
                    return
                fake = TaskSpec(
                    task_id=TaskID.from_random(), func_id="", name=a.spec.name,
                    args_blob=b"", dep_oids=[], return_ids=[],
                    resources=a.spec.resources, pg_id=a.spec.pg_id,
                    pg_bundle_index=a.spec.pg_bundle_index,
                    node_affinity=a.spec.node_affinity,
                    node_affinity_soft=a.spec.node_affinity_soft,
                    label_selector=a.spec.label_selector)
                if self._pick_node_locked(fake) is not None:
                    self._schedule_actor_locked(a)
                    return
        with self.lock:
            self._fail_actor_locked(a, exc.ActorDiedError(
                f"actor {a.spec.name} could not be scheduled in {timeout}s "
                f"(infeasible or saturated resources: {a.spec.resources})"))

    def _dispatch_actor_locked(self, w: WorkerInfo, a: ActorInfo):
        if a.state == "dead":
            return
        if a.spec.runtime_env and w.env_hash is None:
            self._ship_renv_locked(w, a.spec.runtime_env)
        cls_blob = self.func_registry.get(a.spec.class_id)
        # _wsend keeps ordering with any pass-buffered func/renv ships for
        # this worker (everything lands in the same batch envelope)
        self._wsend(w, {"t": "func", "fid": a.spec.class_id,
                        "blob": cls_blob})
        w.funcs.add(a.spec.class_id)
        self._wsend(w, {"t": "actor_create", "spec": a.spec})
        w.state = "actor"

    def _on_actor_ready(self, wid: str, msg: dict):
        with self.lock:
            a = self.actors.get(msg["actor_id"])
            if a is None:
                return
            if msg["ok"]:
                a.state = "alive"
                self.pubsub.publish("actors", {
                    "actor_id": a.spec.actor_id.hex(), "state": "alive",
                    "name": a.spec.name})
                if a.spec.ready_oid is not None:
                    ro = a.spec.ready_oid
                    e = self.directory.get(ro)
                    if e is not None:
                        e.state = READY
                    self._ready_ok.add(ro)
                    if ro in self.interest and \
                            not self.store.contains(ro):
                        # a __ray_ready__ waiter parked BEFORE init
                        # finished: seal its payload now (later
                        # observers materialize at ref-add)
                        try:
                            self.store.put(ro, True)
                        except Exception:
                            pass  # store full: waiter falls back to
                            # the ensure/locate path
                while a.queue:
                    self._route_actor_task_locked(a.queue.popleft())
            else:
                self._fail_actor_locked(a, exc.ActorDiedError(
                    f"actor {a.spec.name} __init__ failed: {msg.get('err')}"),
                    creation_failed=True)
            self.cv.notify_all()

    def submit_actor_task_spec(self, spec: TaskSpec) -> list[ObjectRef]:
        if self._submitq_on and not self._shutdown:
            return self._queue_submit("actor", spec)
        with self.lock:
            refs = [ObjectRef(o) for o in spec.return_ids]  # interest first
            self._submit_actor_task_locked(spec)
        return refs

    def _submit_actor_task_locked(self, spec: TaskSpec) -> None:
        self.counters["tasks_submitted"] += 1
        self._record_task_locked(spec, "PENDING")
        for oid in spec.return_ids:
            self.directory[oid] = DirEntry(PENDING, lineage=None)
        holder = f"task:{spec.task_id.hex()}"
        for d in spec.dep_oids:
            self.interest.setdefault(d, set()).add(holder)
        self._route_actor_task_locked(spec)

    def _route_actor_task_locked(self, spec: TaskSpec):
        a = self.actors.get(spec.actor_id)
        if a is None or a.state == "dead":
            cause = a.death_cause if a else "actor not found"
            self._handle_failed_task_locked(
                spec, exc.ActorDiedError(
                    f"actor task {spec.name} failed: {cause}"),
                retryable=False)
            return
        if a.state != "alive":
            a.queue.append(spec)
            return
        w = self.workers.get(a.wid)
        if w is None or w.state == "dead":
            a.queue.append(spec)
            return
        self._ship_function_locked(w, spec.func_id)
        a.running[spec.task_id] = spec
        self._record_task_locked(spec, "RUNNING", worker=w.wid,
                                 node=w.node_id.hex(),
                                 started_at=time.time())
        # _wsend: must share the batch with the func ship above when a
        # scheduling pass is open (send failure then surfaces at flush)
        if not self._wsend(w, {"t": "actor_task", "spec": spec}):
            self._on_worker_death(w.wid)

    def _on_actor_worker_death_locked(self, actor_id: ActorID, wid: str):
        a = self.actors.get(actor_id)
        if a is None or a.state == "dead":
            return
        cause = f"actor worker {wid} died"
        # decide per-task: retry only when max_task_retries allows
        running = list(a.running.values())
        a.running.clear()
        can_restart = a.restarts_left != 0
        for spec in running:
            # ray.get returns at object-seal; the done message may still be
            # in flight when a kill lands. A call whose returns are ALL
            # sealed DID complete — failing it would overwrite results a
            # consumer already holds refs to.
            if self._returns_complete_locked(spec):
                self.counters["tasks_finished"] += 1
                self._record_task_locked(spec, "FINISHED",
                                         finished_at=time.time())
                for oid in spec.return_ids:
                    e = self.directory.get(oid)
                    if e is not None and e.state == PENDING:
                        e.state = READY
                    self._maybe_free_locked(oid)
                self._drop_task_dep_interest_locked(spec)
                continue
            if can_restart and a.spec.max_task_retries != 0 and \
                    spec.retries_left > 0:
                spec.retries_left -= 1
                a.queue.appendleft(spec)
            else:
                self._handle_failed_task_locked(
                    spec, exc.ActorDiedError(
                        f"{spec.name}: {cause}"), retryable=False)
        if can_restart:
            if a.restarts_left > 0:
                a.restarts_left -= 1
            a.state = "restarting"
            a.wid = None
            self.pubsub.publish("actors", {
                "actor_id": a.spec.actor_id.hex(), "state": "restarting",
                "name": a.spec.name})
            self._schedule_actor_locked(a)
        else:
            self._fail_actor_locked(a, exc.ActorDiedError(
                f"actor {a.spec.name} died ({cause}) and has no restarts left"))

    def _fail_actor_locked(self, a: ActorInfo, err: BaseException,
                           creation_failed: bool = False):
        a.state = "dead"
        a.death_cause = str(err)
        self.pubsub.publish("actors", {
            "actor_id": a.spec.actor_id.hex(), "state": "dead",
            "name": a.spec.name, "cause": a.death_cause})
        if a.spec.named and self.named_actors.get(a.spec.named) == a.spec.actor_id:
            del self.named_actors[a.spec.named]
        if a.spec.ready_oid is not None:
            ro = a.spec.ready_oid
            self._ready_ok.discard(ro)
            e = self.directory.get(ro)
            if ro in self.interest or self.xfer_pins.get(ro, 0) > 0:
                # a live __ray_ready__ ref reads the real error; its
                # refcount frees the object like any task result. The
                # registry entry stays regardless: once the holder drops
                # and the object is freed, a LATER ref (a ready ref
                # deserialized from an old pickled handle) still needs
                # the error re-materialized
                self._store_error(ro, err)
                if e is not None:
                    e.state = FAILED
                self._ready_failed[ro] = str(err)[:200]
            else:
                # nobody holds a ready ref: a stored error would leak
                # one store object per dead actor forever (ready oids
                # never enter refcounting). Seal-less FAILED keeps a
                # still-present entry loud for dependency scans; the
                # registry lets a late __ray_ready__ ref materialize
                # the real error at ref-add time — including when the
                # entry was already freed by an earlier ready ref's
                # drop (e is None here).
                if e is not None:
                    e.state = FAILED
                    e.error_brief = str(err)[:200]
                self._ready_failed[ro] = str(err)[:200]
            self._sweep_failed_deps_locked()
        for spec in list(a.queue) + list(a.running.values()):
            self._handle_failed_task_locked(spec, err, retryable=False)
        a.queue.clear()
        a.running.clear()
        self.cv.notify_all()

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        with self.lock:
            a = self.actors.get(actor_id)
            if a is None:
                return
            if no_restart:
                a.restarts_left = 0
            wid = a.wid
            w = self.workers.get(wid) if wid else None
            if w is None and no_restart and a.state in ("pending",
                                                        "restarting"):
                # no worker to kill yet — mark dead so the retry threads
                # stop and queued tasks fail instead of resurrecting it
                self._fail_actor_locked(a, exc.ActorDiedError(
                    f"actor {a.spec.name} was killed before being scheduled"))
                return
        if w is not None and w.proc is not None:
            try:
                w.proc.kill()
            except Exception:
                pass  # already dead
        # death is observed by the recv loop EOF → _on_worker_death

    def get_actor_by_name(self, name: str):
        with self.lock:
            aid = self.named_actors.get(name)
            if aid is None:
                raise ValueError(f"no actor named {name!r}")
            return self.actors[aid].spec

    # ------------------------------------------------------------------ #
    # placement groups (reference: gcs_placement_group_mgr.h:232,
    # policy/bundle_scheduling_policy.h:31)
    # ------------------------------------------------------------------ #

    def create_placement_group(self, bundles: list[dict[str, float]],
                               strategy: str, name: str = "",
                               pg_id: PlacementGroupID | None = None,
                               same_label: str | None = None,
                               bundle_selectors: list[dict | None] | None = None,
                               ) -> PlacementGroupState:
        # pg_id is supplied on session restore so actor specs that
        # reference the old group stay valid (gcs_store.restore)
        pg = PlacementGroupState(pg_id or PlacementGroupID.from_random(),
                                 bundles, strategy, name,
                                 same_label=same_label,
                                 bundle_selectors=bundle_selectors)
        with self.lock:
            self.pgs[pg.pg_id] = pg
            self._try_reserve_pg_locked(pg)
        if pg.state != "created":
            threading.Thread(target=self._retry_pg, args=(pg,),
                             daemon=True).start()
        return pg

    def _try_reserve_pg_locked(self, pg: PlacementGroupState) -> bool:
        alive = [n for n in self.nodes.values() if n.alive]
        if pg.same_label:
            # gang-to-one-label-group (whole-slice) placement: only nodes
            # carrying the label compete, and all bundles must land inside
            # one label value's node group (one ICI domain).
            groups: dict[str, list[NodeInfo]] = {}
            for n in alive:
                val = n.labels.get(pg.same_label)
                if val is not None:
                    groups.setdefault(val, []).append(n)
            plan = None
            # prefer the busiest feasible group so idle slices stay whole
            # for future gangs (pack-onto-used, SURVEY §2.4)
            for val in sorted(
                    groups,
                    key=lambda v: -max(n.utilization() for n in groups[v])):
                plan = self._plan_pg_locked(groups[val], pg)
                if plan is not None:
                    break
        else:
            plan = self._plan_pg_locked(alive, pg)
        if plan is None:
            return False
        # commit
        for b, n in plan:
            b.node_id = n.node_id
            b.avail = dict(b.resources)
            for k, v in b.resources.items():
                n.resources_avail[k] = n.resources_avail.get(k, 0) - v
        pg.state = "created"
        pg.ready_event.set()
        return True

    def _plan_pg_locked(self, nodes: list[NodeInfo], pg: PlacementGroupState,
                        ) -> Optional[list[tuple[BundleState, NodeInfo]]]:
        """Bundle→node assignment over `nodes` per pg.strategy, or None if
        infeasible. Does not mutate node state."""
        plan: list[tuple[BundleState, NodeInfo]] = []
        avail = {n.node_id: dict(n.resources_avail) for n in nodes}
        selectors = pg.bundle_selectors

        def eligible(n: NodeInfo, bi: int) -> bool:
            sel = selectors[bi] if bi < len(selectors) else None
            return sel is None or all(
                n.labels.get(k) == v for k, v in sel.items())

        def fits(nid, res):
            return all(avail[nid].get(k, 0) >= v - 1e-9 for k, v in res.items())

        def take(nid, res):
            for k, v in res.items():
                avail[nid][k] = avail[nid].get(k, 0) - v

        strategy = pg.strategy
        if strategy in ("PACK", "STRICT_PACK"):
            # try to fit all bundles on one node (requirement for STRICT_PACK)
            packed = False
            for n in sorted(nodes, key=lambda n: n.utilization()):
                trial = dict(avail[n.node_id])
                ok = True
                for b in pg.bundles:
                    if eligible(n, b.index) and all(
                            trial.get(k, 0) >= v - 1e-9
                            for k, v in b.resources.items()):
                        for k, v in b.resources.items():
                            trial[k] = trial.get(k, 0) - v
                    else:
                        ok = False
                        break
                if ok:
                    for b in pg.bundles:
                        plan.append((b, n))
                        take(n.node_id, b.resources)
                    packed = True
                    break
            if not packed:
                if strategy == "STRICT_PACK":
                    return None
                # soft PACK: greedy spill
                for b in pg.bundles:
                    tgt = next((n for n in nodes
                                if eligible(n, b.index)
                                and fits(n.node_id, b.resources)), None)
                    if tgt is None:
                        return None
                    plan.append((b, tgt))
                    take(tgt.node_id, b.resources)
        else:  # SPREAD / STRICT_SPREAD
            used_nodes: set[NodeID] = set()
            for b in pg.bundles:
                cands = [n for n in nodes
                         if eligible(n, b.index)
                         and fits(n.node_id, b.resources)]
                fresh = [n for n in cands if n.node_id not in used_nodes]
                if strategy == "STRICT_SPREAD":
                    cands = fresh
                elif fresh:
                    cands = fresh
                if not cands:
                    return None
                tgt = min(cands, key=lambda n: n.utilization())
                plan.append((b, tgt))
                take(tgt.node_id, b.resources)
                used_nodes.add(tgt.node_id)
        return plan

    def _retry_pending_pgs_locked(self) -> None:
        """Re-attempt every pending PG. Called when a node registers: the
        _retry_pg polling thread gives up after pg_retry_timeout_s, but a
        cloud TPU slice can take minutes to boot — registration must be
        able to place gangs that outlived the poller."""
        for pg in self.pgs.values():
            if pg.state == "pending":
                self._try_reserve_pg_locked(pg)

    def _retry_pg(self, pg: PlacementGroupState,
                  timeout: float | None = None):
        from .config import cfg as _cfg
        if timeout is None:
            timeout = _cfg.pg_retry_timeout_s
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            time.sleep(0.05)
            with self.lock:
                if self._shutdown or pg.state != "pending":
                    return
                if self._try_reserve_pg_locked(pg):
                    self._schedule_locked()
                    return

    def remove_placement_group(self, pg_id: PlacementGroupID):
        with self.lock:
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state == "removed":
                return
            if pg.state == "created":
                for b in pg.bundles:
                    n = self.nodes.get(b.node_id)
                    if n is not None and n.alive:
                        for k, v in b.resources.items():
                            n.resources_avail[k] = \
                                n.resources_avail.get(k, 0) + v
            pg.state = "removed"
            pg.ready_event.set()  # wake pg_wait-ers; they check state
            self._schedule_locked()

    # ------------------------------------------------------------------ #
    # nodes (cluster fixture support; reference: gcs_node_manager.h:49)
    # ------------------------------------------------------------------ #

    def add_node(self, resources: dict[str, float],
                 labels: dict[str, str] | None = None,
                 name: str = "") -> NodeID:
        node = NodeInfo(NodeID.from_random(), resources, labels, name)
        with self.lock:
            self.nodes[node.node_id] = node
            self._retry_pending_pgs_locked()
            self._schedule_locked()
        self.pubsub.publish("nodes", {"node_id": node.node_id.hex(),
                                      "event": "added", "name": node.name})
        return node.node_id

    def remove_node(self, node_id: NodeID):
        with self.lock:
            node = self.nodes.get(node_id)
            if node is None or not node.alive:
                return
            if node is self.head_node:
                raise ValueError("cannot remove the head node")
            node.alive = False
            wids = list(node.workers)
            # placement bundles on this node are lost → re-reserve elsewhere
            for pg in self.pgs.values():
                if pg.state == "created" and any(
                        b.node_id == node_id for b in pg.bundles):
                    for b in pg.bundles:
                        n = self.nodes.get(b.node_id)
                        if n is not None and n.alive and n.node_id != node_id:
                            for k, v in b.resources.items():
                                n.resources_avail[k] += v
                        b.node_id = None
                    pg.state = "pending"
                    pg.ready_event.clear()
                    threading.Thread(target=self._retry_pg, args=(pg,),
                                     daemon=True).start()
        self.pubsub.publish("nodes", {"node_id": node_id.hex(),
                                      "event": "removed", "name": node.name})
        for wid in wids:
            with self.lock:
                w = self.workers.get(wid)
            if w is not None:
                try:
                    w.proc.kill()
                except Exception:
                    pass  # already dead
                self._on_worker_death(wid)

    # ------------------------------------------------------------------ #
    # get / wait / cancel (driver side)
    # ------------------------------------------------------------------ #

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        if len(ref_list) > 1:
            # bulk fast path: park in chunked wait_sealed calls (GIL
            # released, one futex wait services whichever result seals
            # first) until everything is readable, THEN materialize in
            # order — instead of a blocking store.get per ref, each of
            # which woke the driver on every unrelated seal
            self._wait_all_present([r.id() for r in ref_list], deadline)
        out = []
        for r in ref_list:
            out.append(self._get_one(r.id(), deadline))
        return out[0] if single else out

    def _sealed_is_exception(self, oid: ObjectID) -> bool:
        """Peek a sealed object's frame flags without deserializing."""
        view = self.store.get_raw(oid, timeout_ms=0)
        if view is None:
            return False
        try:
            from .object_store import _FLAG_EXCEPTION
            return bool(view[0] & _FLAG_EXCEPTION)
        finally:
            del view
            self.store.release(oid)

    def _spilled_is_exception(self, oid: ObjectID) -> bool:
        """Peek a spilled frame's flags byte (same wire framing)."""
        try:
            from .object_store import _FLAG_EXCEPTION
            with open(self.spill._path(oid), "rb") as f:
                b = f.read(1)
            return bool(b and b[0] & _FLAG_EXCEPTION)
        except OSError:
            return False

    def _satisfiable_elsewhere_locked(self, oid: ObjectID) -> bool:
        """True when _get_one can resolve `oid` without a LOCAL seal:
        spilled to disk, terminally failed, or a live remote copy that
        the per-ref loop will pull over."""
        e = self.directory.get(oid)
        if e is None:
            return False
        if e.state in (SPILLED, FAILED):
            return True
        if e.state == READY and e.locations:
            alive = {n.node_id.hex() for n in self.nodes.values()
                     if n.alive}
            return bool(e.locations & alive)
        return False

    def _wait_all_present(self, oids, deadline):
        """Block until every oid the ordered materialization loop will
        actually reach is readable (sealed locally, spilled, failed, or
        pullable from a live remote copy). Sequential-get parity: a
        stored task error at index j stops this wait from blocking on
        anything at or past j — an error ahead of a never-completing ref
        must surface now, not after the hang. Returns on deadline expiry
        and leaves the per-ref timeout error to _get_one. The growing
        slice only bounds how often directory states are re-checked and
        evicted READY objects re-ensured; a seal wakes the wait
        immediately regardless."""
        flags = self.store.wait_sealed(oids, len(oids), 0)
        missing = [(i, o) for i, (o, f) in enumerate(zip(oids, flags))
                   if not f]
        err_before = len(oids)
        if missing:
            with self.lock:
                still = []
                for i, o in missing:
                    if not self._satisfiable_elsewhere_locked(o):
                        still.append((i, o))
                        continue
                    e = self.directory.get(o)
                    if e is not None and e.state == FAILED:
                        # terminally failed with NO sealed/spilled frame
                        # (e.g. a lost spill with no lineage): _get_one
                        # raises here — never block past this index
                        err_before = min(err_before, i)
                missing = still
        if not missing:
            return
        # index of the first already-errored ref: only the prefix before
        # it must resolve before _get_one raises it in order. Peeked only
        # now that we know we'd otherwise block, and only up to the last
        # missing index.
        miss_idx = {i for i, _ in missing}
        for i in range(min(err_before, missing[-1][0])):
            if i in miss_idx:
                continue
            present_err = (self._sealed_is_exception(oids[i]) if flags[i]
                           else self._spilled_is_exception(oids[i]))
            if present_err:
                err_before = i
                break
        missing = [(i, o) for i, o in missing if i < err_before]
        slice_ms = 10
        next_ensure = 0.0
        while missing:
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return
                slice_ms = min(slice_ms, max(1, int(remain * 1000)))
            got = self.store.wait_sealed([o for _, o in missing],
                                         len(missing), slice_ms)
            now = time.monotonic()
            do_ensure = now >= next_ensure
            if do_ensure:
                next_ensure = now + 0.2
            still = []
            with self.lock:
                for (i, o), f in zip(missing, got):
                    if f:
                        if self._sealed_is_exception(o):
                            err_before = min(err_before, i)
                        continue
                    if self._satisfiable_elsewhere_locked(o):
                        e = self.directory.get(o)
                        if (e is not None and e.state == FAILED) or \
                                self._spilled_is_exception(o):
                            err_before = min(err_before, i)
                        continue
                    if do_ensure:
                        # evicted READY objects need lineage re-exec,
                        # same as get() (object_recovery_manager.h:43)
                        self._ensure_available_locked(o)
                    still.append((i, o))
                if do_ensure and still:
                    self._schedule_locked()
            missing = [(i, o) for i, o in still if i < err_before]
            slice_ms = min(slice_ms * 2, 200)

    def _mux_nudge(self, oid: ObjectID):
        """Completion-mux recovery hook (core/completion.py): an awaited
        oid stayed unsealed past the nudge window — re-ensure it (lineage
        re-execution of evicted objects) and, when a live remote copy
        exists, pull it off-thread so the mux never blocks on transfer
        IO."""
        pull = False
        with self.lock:
            e = self.directory.get(oid)
            if e is None or e.state == PENDING:
                # producer still running (the common case for a slow
                # awaited task): nothing to recover, and a scheduling
                # pass per nudge would just contend with the hot path
                return
            self._ensure_available_locked(oid)
            e = self.directory.get(oid)
            if e is not None and e.state == PENDING:
                # the re-ensure requeued its lineage: run one pass so
                # the reconstruction actually dispatches
                self._schedule_locked()
            elif e is not None and e.state == READY and e.locations:
                pull = True
        if pull:
            self._rpc_pool.submit(self._fetch_remote, oid)

    def _recover_lost_spill(self, oid: ObjectID) -> None:
        """A SPILLED object's file is gone and no live node holds a copy:
        flip to the lineage path (reconstructable) or FAILED (loud)."""
        with self.lock:
            e = self.directory.get(oid)
            if e is None or e.state != SPILLED:
                return
            alive = {n.node_id.hex() for n in self.nodes.values()
                     if n.alive}
            if (e.locations or set()) & alive or self.spill.contains(oid):
                return  # a holder is still up; keep pulling
            if e.lineage is not None:
                e.state = READY      # reuse the evicted-object recovery
                e.locations = None
                self._ensure_available_locked(oid)
                self._schedule_locked()
            else:
                self._store_error(oid, exc.ObjectLostError(
                    f"object {oid} was spilled on a node that died and "
                    f"has no lineage to reconstruct from"))
                e.state = FAILED
                self._sweep_failed_deps_locked()

    def _fetch_remote(self, oid: ObjectID) -> bool:
        """Pull an object produced on an own-store node into the head's
        store (object_transfer.py); False when no remote copy exists."""
        with self.lock:
            e = self.directory.get(oid)
            locs = set(e.locations or ()) if e is not None else set()
            locs.discard(self.head_node.node_id.hex())
            addrs = [n.data_addr for n in self.nodes.values()
                     if n.alive and n.own_store and n.data_addr
                     and n.node_id.hex() in locs]
        from .object_transfer import fetch_resilient
        try:
            return fetch_resilient(addrs, oid, self.store, self.spill)
        except OSError:
            return False

    def _get_one(self, oid: ObjectID, deadline: float | None):
        while True:
            slice_ms = 200
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise exc.GetTimeoutError(
                        f"ray_tpu.get timed out waiting for {oid}")
                slice_ms = max(1, min(slice_ms, int(remain * 1000)))
            try:
                value = self.store.get(oid, timeout_ms=slice_ms)
            except StoreTimeout:
                with self.lock:
                    e = self.directory.get(oid)
                    spilled = e is not None and e.state == SPILLED
                if spilled:
                    # objects bigger than the store never leave disk
                    try:
                        return self.spill.load(oid)
                    except FileNotFoundError:
                        # spilled on an own-store NODE: pull it over; if
                        # every holder died, reconstruct via lineage or
                        # fail loudly — never spin silently
                        if not self._fetch_remote(oid):
                            self._recover_lost_spill(oid)
                        continue
                    except exc.RayTaskError as e:
                        raise e.as_instanceof_cause() from e
                if self._fetch_remote(oid):
                    continue  # pulled into the local store; next get hits
                with self.lock:
                    self._ensure_available_locked(oid)
                    self._schedule_locked()
                continue
            except exc.RayTaskError as e:
                raise e.as_instanceof_cause() from e
            return value

    def wait(self, refs, num_returns=1, timeout: float | None = None,
             fetch_local=True):
        # event-driven: one multi-oid futex wait (store.wait_sealed)
        # services whichever result seals first — a completion wakes this
        # waiter immediately instead of on the next 5ms poll boundary.
        # The growing slice only bounds how often directory states
        # (FAILED/SPILLED never seal in shm) are re-checked and evicted
        # READY objects re-ensured.
        ref_list = list(refs)
        if num_returns > len(ref_list):
            raise ValueError("num_returns exceeds number of refs")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(ref_list)
        slice_ms = 0          # first round is a non-blocking scan
        next_ensure = 0.0
        while len(ready) < num_returns and pending:
            if deadline is not None and slice_ms:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                slice_ms = min(slice_ms, max(1, int(remain * 1000)))
            flags = self.store.wait_sealed(
                [r.id() for r in pending],
                num_returns - len(ready), slice_ms)
            now = time.monotonic()
            do_ensure = now >= next_ensure
            if do_ensure:
                next_ensure = now + 0.2
            still = []
            with self.lock:
                for r, f in zip(pending, flags):
                    if f:
                        ready.append(r)
                        continue
                    e = self.directory.get(r.id())
                    if e is not None and e.state in (FAILED, SPILLED):
                        # errors count as ready; spilled objects are
                        # readable from disk
                        ready.append(r)
                        continue
                    if do_ensure:
                        # evicted-but-READY objects need lineage re-exec,
                        # same as get() (object_recovery_manager.h:43)
                        self._ensure_available_locked(r.id())
                    still.append(r)
                if do_ensure and still:
                    self._schedule_locked()
            pending = still
            if deadline is not None and time.monotonic() >= deadline:
                break
            slice_ms = min(max(slice_ms * 2, 2), 50)  # backoff fallback
        # reference contract: at most num_returns refs in ready; extra
        # already-ready refs stay in the remaining list
        return ready[:num_returns], ready[num_returns:] + pending

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True):
        # queued driver submits are invisible to the scans below: admit
        # them first so a cancel-right-after-submit finds its task
        self._drain_submit_q()
        with self.lock:
            # pending?
            for spec in list(self.pending):
                if ref.id() in spec.return_ids:
                    self.pending.remove(spec)
                    self._handle_failed_task_locked(
                        spec, exc.TaskCancelledError(
                            f"task {spec.name} was cancelled"),
                        retryable=False)
                    return
            # running?
            for w in self.workers.values():
                spec = w.current
                if spec is not None and ref.id() in spec.return_ids:
                    spec.retries_left = 0
                    if force:
                        try:
                            w.proc.kill()
                        except Exception:
                            pass  # already dead
                    else:
                        w.send({"t": "cancel", "task_id": spec.task_id})
                    return
                # pipelined behind a running task: steal it back and fail
                for item in list(w.queued):
                    s, nonce = item
                    if ref.id() in s.return_ids:
                        w.queued.remove(item)
                        w.send({"t": "steal", "nonces": [nonce]})
                        self._handle_failed_task_locked(
                            s, exc.TaskCancelledError(
                                f"task {s.name} was cancelled"),
                            retryable=False)
                        return

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #

    def cluster_resources(self) -> dict[str, float]:
        with self.lock:
            out: dict[str, float] = {}
            for n in self.nodes.values():
                if n.alive:
                    for k, v in n.resources_total.items():
                        out[k] = out.get(k, 0) + v
            return out

    def available_resources(self) -> dict[str, float]:
        with self.lock:
            out: dict[str, float] = {}
            for n in self.nodes.values():
                if n.alive:
                    for k, v in n.resources_avail.items():
                        out[k] = out.get(k, 0) + v
            return out

    def node_table(self) -> list[dict]:
        with self.lock:
            return [
                {"NodeID": n.node_id.hex(), "Alive": n.alive,
                 "Resources": dict(n.resources_total),
                 "Available": dict(n.resources_avail),
                 "Labels": dict(n.labels), "NodeName": n.name}
                for n in self.nodes.values()
            ]

    def record_trace_span(self, rec: dict) -> None:
        """A completed trace span (util/tracing.py) enters the timeline as
        a chrome complete event whose args carry the trace/span/parent ids
        — flow-stitchable across processes (reference:
        tracing_helper.py:293 context-in-metadata)."""
        with self.lock:
            self.events.append({
                "name": rec.get("name", "span"), "cat": "trace",
                "ph": "X", "pid": rec.get("task_id", "driver"),
                "ts": rec["start_s"] * 1e6,
                "dur": rec.get("dur_s", 0.0) * 1e6,
                "args": {k: rec[k] for k in
                         ("trace_id", "span_id", "parent_id",
                          "request_id")
                         if rec.get(k) is not None}})

    def timeline(self) -> list[dict]:
        with self.lock:
            return list(self.events)

    # ------------------------------------------------------------------ #
    # flight recorder (core/flight.py) cluster collection
    # ------------------------------------------------------------------ #

    def _pull_from_peers(self, make_msg, pulls: dict,
                         evt: threading.Event, timeout_s: float,
                         wids: Optional[list] = None):
        """Shared nonce-pull machinery behind flight_collect and
        stack_collect: register a nonce per connected worker/driver in
        `pulls` (the dict the matching reply handler fills), send
        ``make_msg(nonce)`` to each, and wait out the deadline on
        `evt`. Returns ({nonce: {"snap"}}, {nonce: wid}) for the peers
        that were actually sent to; late repliers are dropped at
        cleanup. Never waits under the scheduler lock."""
        with self.lock:
            targets = [w for w in self.workers.values()
                       if w.conn is not None and w.state != "dead"
                       and (wids is None or w.wid in wids)]
        mine: dict[bytes, dict] = {}
        names: dict[bytes, str] = {}
        for w in targets:
            nonce = os.urandom(12)
            rec = {"snap": None}
            pulls[nonce] = rec
            mine[nonce] = rec
            names[nonce] = w.wid
            if not w.send(make_msg(nonce)):
                pulls.pop(nonce, None)
                mine.pop(nonce, None)
                names.pop(nonce, None)
        deadline = time.monotonic() + timeout_s
        try:
            while any(r["snap"] is None for r in mine.values()):
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                evt.wait(timeout=min(0.1, remain))
                evt.clear()
        finally:
            for nonce in mine:
                pulls.pop(nonce, None)
        return mine, names

    def flight_collect(self, timeout_s: float = 3.0,
                       stats_only: bool = False) -> list[dict]:
        """Pull every live worker's flight-recorder ring (or just its
        stats) over the control plane, plus this process's own. Each
        remote snapshot carries ``offset_ns`` — its monotonic clock
        minus ours, estimated through the wall-clock bridge (see the
        flight_ring handler) and clamped to 0 for same-host clocks — so
        export_chrome can stitch all tracks onto the head clock.
        Dead/unresponsive workers are skipped at the deadline;
        collection never blocks the scheduler lock."""
        local = flight.snapshot(stats_only) or flight.stats()
        local["offset_ns"] = 0
        snaps = [local]
        pulls, _ = self._pull_from_peers(
            lambda nonce: {"t": "flight_pull", "nonce": nonce,
                           "stats_only": stats_only},
            self._flight_pulls, self._flight_evt, timeout_s)
        snaps.extend(r["snap"] for r in pulls.values()
                     if r["snap"] is not None)
        return snaps

    def flight_stats(self) -> list[dict]:
        """Per-process recorder health (events recorded/dropped, channel
        endpoint counters) for state.summary(). stats_only pulls are
        tiny frames answered straight from each recv loop; the short
        deadline bounds how long a summary poll can stall on one
        backlogged worker (it is skipped, not waited out)."""
        out = []
        for snap in self.flight_collect(timeout_s=0.5, stats_only=True):
            cnt = snap.get("counters", {})
            out.append({
                "proc": snap.get("proc", ""), "pid": snap.get("pid"),
                "recorded": snap.get("recorded", 0),
                "dropped": snap.get("dropped", 0),
                "bad": snap.get("bad", 0),
                "chan_open": cnt.get("chan_open", 0),
                "chan_closed": cnt.get("chan_closed", 0),
            })
        return out

    def flight_timeline(self, since_ns: int = 0) -> dict:
        """Cluster-stitched Chrome-trace/Perfetto object: every
        process's flight ring on one clock, plus the span-tracing
        timeline events merged in (state.timeline(flight=True)).
        Span events are wall-clock stamped — rebase them onto the head
        monotonic microseconds the flight events use, so both layers
        land on one Perfetto timeline."""
        trace = flight.export_chrome(self.flight_collect(),
                                     since_ns=since_ns)
        delta_us = (time.monotonic_ns() / 1000.0
                    - time.time_ns() / 1000.0)
        for ev in self.timeline():
            ev = dict(ev)
            ev["ts"] = float(ev.get("ts", 0.0)) + delta_us
            if ev["ts"] * 1000.0 >= since_ns:
                trace["traceEvents"].append(ev)
        return trace

    # ------------------------------------------------------------------ #
    # stall doctor (core/stacks.py): live stacks, stuck-task watchdog,
    # wait-graph deadlock detection
    # ------------------------------------------------------------------ #

    def stack_collect(self, timeout_s: float = 3.0,
                      wids: Optional[list] = None,
                      include_stacks: bool = True,
                      include_local: bool = True):
        """Pull live thread stacks (+ wait-beacon/task annotations) from
        every connected worker AND driver over the control plane, plus
        this process's own. Replies are built on each peer's recv thread
        (the flight_pull precedent), so a dump succeeds even when the
        target's executor threads are wedged — which is exactly when it
        is needed. Returns (snapshots, unresponsive_wids); dead or
        backlogged peers are skipped at the deadline, never waited out
        under the scheduler lock."""
        snaps = [stacks.capture(include_stacks)] if include_local else []
        pulls, names = self._pull_from_peers(
            lambda nonce: {"t": "stack_dump", "nonce": nonce,
                           "no_stacks": not include_stacks},
            self._stack_pulls, self._stack_evt, timeout_s, wids=wids)
        unresponsive = [names[n] for n, r in pulls.items()
                        if r["snap"] is None]
        for nonce, r in pulls.items():
            if r["snap"] is not None:
                r["snap"]["wid"] = names[nonce]
                snaps.append(r["snap"])
        return snaps, unresponsive

    def _stall_maps_locked(self):
        """Resolution tables for snapshot annotation + the wait-graph
        fold: task lo48 -> its state record, and PENDING-object lo48 ->
        the lo48 of the task whose lineage produces it."""
        task_by48 = {}
        for tid_key, rec in self.task_records.items():
            task_by48[flight.lo48(tid_key)] = rec
        obj_task48 = {}
        obj_hex48 = {}
        for oid, e in self.directory.items():
            if e.state == PENDING:
                obj_hex48[flight.lo48(oid)] = oid.hex()
                if e.lineage is not None:
                    obj_task48[flight.lo48(oid)] = \
                        flight.lo48(e.lineage.task_id)
        return task_by48, obj_task48, obj_hex48

    @staticmethod
    def _fold_producers(snaps: list) -> dict:
        """Channel-base lo48 -> (pid, tid) across every collected
        process's endpoint table."""
        producers = {}
        for s in snaps:
            for b48, tid in (s.get("chan_producers") or {}).items():
                producers[int(b48)] = (s["pid"], int(tid))
        return producers

    def _annotate_snaps(self, snaps: list, maps=None) -> None:
        """Resolve each thread's task48/wait id48 against what the head
        knows: task names, PENDING objects and their producing tasks,
        channel producer endpoints across every collected process.
        `maps` lets hang_report share one _stall_maps_locked build (and
        one lock hold) with cycle detection."""
        if maps is None:
            with self.lock:
                maps = self._stall_maps_locked()
        task_by48, obj_task48, obj_hex48 = maps
        producers = self._fold_producers(snaps)
        proc_of = {}
        for s in snaps:
            proc_of[s["pid"]] = s.get("proc") or f"pid-{s['pid']}"
        for s in snaps:
            for t in s.get("threads", ()):
                t48 = t.get("task48")
                if t48:
                    rec = task_by48.get(t48)
                    if rec is not None:
                        t["task"] = (f"{rec.get('name')} "
                                     f"[{rec.get('task_id', '')[:12]}]")
                w = t.get("wait")
                if not w:
                    continue
                id48 = w.get("id48", 0)
                tgt = producers.get(id48)
                if tgt is not None:
                    w["target"] = (f"channel 0x{id48:012x} (producer: "
                                   f"{proc_of.get(tgt[0], tgt[0])} "
                                   f"thread {tgt[1]})")
                    continue
                prod48 = obj_task48.get(id48)
                if prod48 is not None:
                    rec = task_by48.get(prod48)
                    if rec is not None:
                        w["target"] = (
                            f"object {obj_hex48.get(id48, '')[:12]} <- "
                            f"task {rec.get('name')} "
                            f"({rec.get('state')} on "
                            f"{rec.get('worker', '?')})")
                        continue
                if id48 in obj_hex48:
                    w["target"] = f"object {obj_hex48[id48][:12]}"

    def stack_report(self, timeout_s: float = 3.0,
                     wids: Optional[list] = None,
                     include_stacks: bool = True) -> dict:
        """Cluster-wide annotated live-stack report
        (state.stack_report() / `cli stack` / GET /api/stacks)."""
        snaps, unresponsive = self.stack_collect(
            timeout_s=timeout_s, wids=wids,
            include_stacks=include_stacks)
        self._annotate_snaps(snaps)
        return {"procs": snaps, "unresponsive": unresponsive,
                "collected_at": time.time()}

    def _detect_wait_cycles(self, snaps: list,
                            min_wait_s: float = 1.0,
                            maps=None) -> list[dict]:
        """Fold wait beacons + channel endpoint tables + the object
        directory into a waiter->producer graph and return its cycles.
        Nodes are (pid, tid) threads; each waiting thread has at most
        one outgoing edge (what it waits on resolves to at most one
        producing thread), so cycle detection is one pass over a
        functional graph.

        Only waits parked at least ``min_wait_s`` become edges: the
        snapshots are not simultaneous (each peer captures when its
        recv loop reaches the dump, up to the collection timeout
        apart), so millisecond-transient waits on a healthy
        backpressured pipeline could otherwise pair up into a phantom
        cycle. A real deadlock is sustained by definition and crosses
        any such floor."""
        producers = self._fold_producers(snaps)
        threads = {(s["pid"], t["tid"]): (s, t)
                   for s in snaps for t in s.get("threads", ())}
        task_thread = {t["task48"]: (s["pid"], t["tid"])
                       for s in snaps for t in s.get("threads", ())
                       if t.get("task48")}
        if maps is None:
            with self.lock:
                maps = self._stall_maps_locked()
        _, obj_task48, _ = maps
        edges = {}
        for key, (s, t) in threads.items():
            w = t.get("wait")
            if not w or w.get("for_s", 0.0) < min_wait_s:
                continue
            id48 = w.get("id48", 0)
            tgt = producers.get(id48)
            if tgt is None:
                prod48 = obj_task48.get(id48)
                if prod48 is not None:
                    tgt = task_thread.get(prod48)
            if tgt is not None and tgt in threads and tgt != key:
                edges[key] = tgt
        done: set = set()
        cycles = []
        for start in list(edges):
            if start in done:
                continue
            path, seen_at = [], {}
            node = start
            while node in edges and node not in done \
                    and node not in seen_at:
                seen_at[node] = len(path)
                path.append(node)
                node = edges[node]
            if node in seen_at:
                cyc = path[seen_at[node]:]
                parties = []
                for pid, tid in cyc:
                    s, t = threads[(pid, tid)]
                    w = t.get("wait", {})
                    parties.append({
                        "proc": s.get("proc") or f"pid-{pid}",
                        "pid": pid, "tid": tid,
                        "thread_name": t.get("name"),
                        "task": t.get("task"),
                        "wait_kind": w.get("kind"),
                        "target": w.get("target")
                        or f"0x{w.get('id48', 0):012x}",
                    })
                cycles.append({"parties": parties})
            done.update(path)
        return cycles

    def hang_report(self, timeout_s: float = 3.0,
                    min_wait_s: float = 1.0) -> dict:
        """One-shot hang diagnosis (state.hang_report() / `cli doctor`):
        watchdog-flagged stuck tasks (with their attached worker
        stacks), suspected wait-graph deadlocks naming every party, and
        watchdog health. The annotated stack snapshots the diagnosis
        was computed from ride along as ``procs`` so consumers (`cli
        doctor`) render them without a second cluster-wide pull."""
        snaps, unresponsive = self.stack_collect(timeout_s=timeout_s)
        with self.lock:
            maps = self._stall_maps_locked()
        # one maps build + lock hold serves annotation AND the cycle fold
        self._annotate_snaps(snaps, maps=maps)
        cycles = self._detect_wait_cycles(snaps, min_wait_s=min_wait_s,
                                          maps=maps)
        report = {"procs": snaps, "unresponsive": unresponsive,
                  "collected_at": time.time()}
        now = time.time()
        with self.lock:
            # one DEADLOCK event per incident: a poller (dashboard
            # auto-refresh, a doctor loop) re-observing the same
            # sustained cycle must not inflate the flight ring. A key is
            # forgotten (so a recurrence re-reports) only when a FULL
            # collection no longer shows it — a cycle merely invisible
            # because one party missed the reply deadline must not be
            # re-announced when it reappears.
            keys = [frozenset((p["pid"], p["tid"])
                    for p in cyc["parties"]) for cyc in cycles]
            for key, cyc in zip(keys, cycles):
                if key not in self._seen_cycles:
                    flight.evt(flight.DEADLOCK, len(cyc["parties"]))
            if not unresponsive:
                self._seen_cycles &= set(keys)
            self._seen_cycles |= set(keys)
        with self.lock:
            stuck = []
            for rec in self.task_records.values():
                if rec.get("stuck") and rec.get("state") == "RUNNING":
                    r = dict(rec)
                    r["running_s"] = now - rec.get("started_at", now)
                    stuck.append(r)
            wd = dict(self._watchdog)
        return {"stuck_tasks": stuck, "deadlocks": cycles,
                "watchdog": wd, "procs": report["procs"],
                "unresponsive": report["unresponsive"],
                "collected_at": report["collected_at"]}

    def watchdog_health(self) -> dict:
        with self.lock:
            return dict(self._watchdog)

    def _stall_watchdog_loop(self):
        from .config import cfg
        if not cfg.stall_watchdog:
            return
        period = max(0.1, cfg.stall_watchdog_period_s)
        while not self._shutdown:
            time.sleep(period)
            if self._shutdown:
                return
            try:
                self._stall_watchdog_scan()
            except Exception:
                pass  # diagnosis must never take down the head; the
                # next scan retries with fresh state

    def _stall_watchdog_scan(self):
        """One watchdog pass: flag RUNNING tasks past their per-name
        threshold (EWMA multiple, floored), attach the owning worker's
        live stack to the task record, emit the task_stuck flight event
        and rtpu_core_stuck_tasks metrics. A scan that flags nothing
        does no control-plane traffic at all."""
        from .config import cfg
        from ..util.metrics import Counter, Gauge, cached_metric
        now = time.time()
        floor = cfg.stuck_task_floor_s
        mult = cfg.stuck_task_multiple
        newly = []
        n_stuck = 0
        with self.lock:
            self._watchdog["scans"] += 1
            self._watchdog["last_scan"] = now
            for rec in self.task_records.values():
                if rec.get("state") != "RUNNING":
                    continue
                t0 = rec.get("started_at")
                if t0 is None:
                    continue
                running = now - t0
                ewma = self._task_ewma.get(rec.get("name"))
                thr = max(floor, mult * ewma) if ewma is not None \
                    else floor
                if running < thr:
                    continue
                n_stuck += 1
                if not rec.get("stuck"):
                    rec["stuck"] = True
                    rec["stuck_at"] = now
                    rec["threshold_s"] = thr
                    rec["ewma_s"] = ewma
                    # live record ref kept: the stack attaches to it
                    # below without re-searching under the lock
                    newly.append((rec, running, thr))
            self._watchdog["stuck_running"] = n_stuck
            if newly:
                self._watchdog["flagged_total"] += len(newly)
        cached_metric(Gauge, "rtpu_core_stuck_tasks",
                      "tasks currently RUNNING past their stuck "
                      "threshold").set(float(n_stuck))
        if not newly:
            return
        cached_metric(Counter, "rtpu_core_stuck_tasks_total",
                      "tasks flagged stuck by the stall watchdog"
                      ).inc(float(len(newly)))
        # ONE stack pull per distinct owning worker, not per task: a
        # node wedging a whole batch at once must not serialize N
        # 2s-deadline pulls (stalling further scans exactly when timely
        # diagnosis matters) or spam an unresponsive worker
        by_wid: dict[str, list] = {}
        for rec, running, thr in newly:
            try:
                t48 = flight.lo48(bytes.fromhex(rec.get("task_id", "")))
            except ValueError:
                t48 = 0
            flight.evt(flight.TASK_STUCK, t48,
                       int(max(0.0, running - thr) * 1000))
            wid = rec.get("worker")
            if wid:
                by_wid.setdefault(wid, []).append((rec, t48))
        for wid, recs in by_wid.items():
            snaps, _ = self.stack_collect(timeout_s=2.0, wids=[wid],
                                          include_local=False)
            if not snaps:
                continue
            self._annotate_snaps(snaps)
            threads = snaps[0].get("threads", [])
            busy = [t for t in threads
                    if t.get("task48") or t.get("wait")]
            with self.lock:
                for rec, t48 in recs:
                    if not rec.get("stuck"):
                        # the attempt failed and a retry re-entered
                        # RUNNING while we collected: the fresh attempt
                        # must not inherit the wedged one's stack
                        continue
                    hit = [t for t in threads
                           if t48 and t.get("task48") == t48]
                    rec["stack"] = hit or busy or threads

    # ------------------------------------------------------------------ #
    # shutdown
    # ------------------------------------------------------------------ #

    def shutdown(self):
        global _runtime
        with self.lock:
            if self._shutdown:
                return
            self._shutdown = True
            workers = list(self.workers.values())
        # flush any worker output the tailer hasn't echoed yet
        if getattr(self, "_logtail_state", None) is not None:
            try:
                self._log_tail_scan()
            except Exception:
                pass  # final log echo is best-effort
        # final metric flush BEFORE the snapshot: counter deltas recorded
        # since the last 2s tick merge into user_metrics and persist
        from ..util.metrics import shutdown_flush
        shutdown_flush()
        # durable snapshot FIRST: killing workers below tears actors out
        # of the tables (watch-proc death path), and a successor must see
        # them as they were while alive
        self.memory_monitor.stop()
        if self.obs is not None:
            self.obs.stop()
        if self._snapshot_stop is not None:
            self._snapshot_stop.set()
        try:
            from .gcs_store import snapshot
            snapshot(self)
        except Exception:
            pass  # failed snapshot must not block teardown
        self.jobs.shutdown()
        for w in workers:
            w.send({"t": "exit"})
        for node in list(self.nodes.values()):
            if node.agent is not None:
                node.agent.send({"t": "shutdown"})
        # wake pg_wait blockers so rpc-pool threads exit promptly, then
        # release the pool without joining in-flight handlers
        self._sched_evt.set()  # release the scheduler pump
        for pg in self.pgs.values():
            pg.ready_event.set()
        self._rpc_pool.shutdown(wait=False, cancel_futures=True)
        deadline = time.monotonic() + 1.0
        for w in workers:
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.01, deadline - time.monotonic()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass  # already dead
        for lst in (self.listener, self.tcp_listener):
            try:
                lst.close()
            except Exception:
                pass  # already closed
        # sever control-plane connections so recv threads exit before the
        # store mapping goes away (they may touch the store while handling
        # late messages)
        for w in workers:
            try:
                if w.conn is not None:
                    w.conn.close()
            except Exception:
                pass  # already closed
        try:
            from .usage import write_usage_file
            write_usage_file(self.session_dir)
        except Exception:
            pass  # usage file is best-effort
        try:
            self.kv.close()
        except Exception:
            pass  # sqlite already closed
        self.store.close(unlink=True)
        try:
            os.unlink(self.cluster_file)  # address='auto' must not find us
        except OSError:
            pass
        if _runtime is self:
            _runtime = None


class LocalModeRuntime:
    """`ray_tpu.init(local_mode=True)`: tasks run synchronously in-process.

    Reference analog: python/ray/_private/worker.py LOCAL_MODE. Useful for
    debugging user code with pdb; actors are plain objects, objects live in a
    dict.
    """

    # refcounting is a no-op in local mode (objects live in a plain dict)
    def ref_created(self, oid, from_transfer):
        pass

    def ref_deleted(self, oid):
        pass

    def ref_serialized(self, oid):
        pass

    def __init__(self):
        self.objects: dict[ObjectID, Any] = {}
        self.job_id = JobID.from_random()
        self.func_registry: dict[str, Any] = {}
        self._actors: dict[ActorID, Any] = {}
        self.named_actors: dict[str, ActorID] = {}

    def register_function(self, fid, blob):
        self.func_registry.setdefault(fid, cloudpickle.loads(blob))

    def register_renv(self, h, blob):
        pass  # local mode runs in-process; runtime envs are validated only

    def put(self, value, pin=True):
        oid = ObjectID.from_random()
        self.objects[oid] = ("ok", value)
        return ObjectRef(oid)

    def _resolve_args(self, args_blob):
        args, kwargs = cloudpickle.loads(args_blob)
        args = [self.get(a) if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {k: self.get(v) if isinstance(v, ObjectRef) else v
                  for k, v in kwargs.items()}
        return args, kwargs

    def submit_task(self, spec: TaskSpec):
        fn = self.func_registry[spec.func_id]
        args, kwargs = self._resolve_args(spec.args_blob)
        try:
            res = fn(*args, **kwargs)
            n = len(spec.return_ids)
            if getattr(spec, "dynamic_returns", False):
                vals = [[self.put(item) for item in res]]
            else:
                vals = (list(res) if n > 1 else [res])
            for oid, v in zip(spec.return_ids, vals):
                self.objects[oid] = ("ok", v)
        except BaseException as e:  # noqa: BLE001
            err = exc.RayTaskError(spec.name, e)
            for oid in spec.return_ids:
                self.objects[oid] = ("err", err)
        return [ObjectRef(o) for o in spec.return_ids]

    def create_actor(self, spec: ActorSpec):
        cls = self.func_registry[spec.class_id]
        args, kwargs = self._resolve_args(spec.args_blob)
        inst = cls(*args, **kwargs)
        self._actors[spec.actor_id] = inst
        if spec.named:
            self.named_actors[spec.named] = spec.actor_id
        if spec.ready_oid is not None:
            self.objects[spec.ready_oid] = ("ok", None)

    def submit_actor_task_spec(self, spec: TaskSpec):
        inst = self._actors.get(spec.actor_id)
        if inst is None:
            err = exc.ActorDiedError(f"actor for {spec.name} is dead")
            for oid in spec.return_ids:
                self.objects[oid] = ("err", err)
            return [ObjectRef(o) for o in spec.return_ids]
        args, kwargs = self._resolve_args(spec.args_blob)
        try:
            res = getattr(inst, spec.method_name)(*args, **kwargs)
            n = len(spec.return_ids)
            vals = (list(res) if n > 1 else [res])
            for oid, v in zip(spec.return_ids, vals):
                self.objects[oid] = ("ok", v)
        except BaseException as e:  # noqa: BLE001
            err = exc.RayTaskError(spec.name, e)
            for oid in spec.return_ids:
                self.objects[oid] = ("err", err)
        return [ObjectRef(o) for o in spec.return_ids]

    def get(self, refs, timeout=None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        out = []
        for r in ref_list:
            # deferred refs (pg.ready() — pre-registered via expect()) are
            # resolved by a waiter thread; anything else is synchronous in
            # local mode, so an unknown oid is an immediate error
            deadline = None if timeout is None else time.monotonic() + timeout
            while self.objects.get(r.id(), (None,))[0] == "pending":
                if deadline is not None and time.monotonic() > deadline:
                    raise exc.GetTimeoutError(f"timed out on {r.id()}")
                time.sleep(0.001)
            if r.id() not in self.objects:
                raise exc.ObjectLostError(
                    f"object {r.id()} does not exist in local mode")
            st, v = self.objects[r.id()]
            if st == "err":
                raise v.as_instanceof_cause() if isinstance(
                    v, exc.RayTaskError) else v
            out.append(v)
        return out[0] if single else out

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        ref_list = list(refs)
        return ref_list[:num_returns], ref_list[num_returns:]

    def kill_actor(self, actor_id, no_restart=True):
        self._actors.pop(actor_id, None)

    def get_actor_by_name(self, name):
        aid = self.named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        spec = ActorSpec(actor_id=aid, class_id="", name=name, args_blob=b"",
                         dep_oids=[], resources={})
        return spec

    def cancel(self, ref, force=False, recursive=True):
        pass

    def cluster_resources(self):
        return {"CPU": float(os.cpu_count() or 1)}

    def available_resources(self):
        return self.cluster_resources()

    def node_table(self):
        return [{"NodeID": "local", "Alive": True,
                 "Resources": self.cluster_resources(),
                 "Available": self.cluster_resources(), "Labels": {},
                 "NodeName": "local"}]

    def timeline(self):
        return []

    def create_placement_group(self, bundles, strategy, name="",
                               same_label=None, bundle_selectors=None):
        pg = PlacementGroupState(PlacementGroupID.from_random(), bundles,
                                 strategy, name, same_label=same_label,
                                 bundle_selectors=bundle_selectors)
        pg.state = "created"
        pg.ready_event.set()
        return pg

    def remove_placement_group(self, pg_id):
        pass

    def pg_wait(self, pg_id, timeout: float = 30.0) -> bool:
        return True  # local-mode PGs are always immediately "reserved"

    def expect(self, oid):
        """Register an oid a background waiter will put_at shortly, so get()
        blocks on it instead of failing fast on an unknown oid."""
        self.objects.setdefault(oid, ("pending", None))

    def put_at(self, oid, value, is_exception: bool = False):
        self.objects[oid] = ("err" if is_exception else "ok", value)
        return ObjectRef(oid)

    def shutdown(self):
        global _runtime
        if _runtime is self:
            _runtime = None
