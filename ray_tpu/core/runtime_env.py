"""Per-task/actor runtime environments.

Reference parity: _private/runtime_env/ — the plugin framework
(plugin.py), working_dir/py_modules packaging (working_dir.py: zip to the
GCS KV store, extracted per node by the runtime-env agent,
agent/runtime_env_agent.py:164), and the dedicated-worker matching of
raylet's worker pool (worker_pool.h: workers are keyed by runtime-env
hash and never shared across envs).

TPU-first reductions, by design:
  - blobs travel over the control plane into a head-side registry (the
    function-registry mechanism) and are shipped to a worker once, at its
    first task with that env — same role as the reference's KV-store
    upload + per-node agent download, without a separate agent daemon;
  - workers are *dedicated*: a worker that applied env E only ever runs
    tasks with env E (matching the reference's pool semantics), so
    env_vars / cwd / sys.path can be applied process-wide;
  - ``pip`` / ``conda`` / ``container`` are rejected up front: this image
    has no package network and one interpreter (environment constraint) —
    a clear error beats a silent no-op.

Supported keys: ``env_vars`` (dict str→str), ``working_dir`` (local dir
path, zipped at submission), ``py_modules`` (list of local dirs/files put
on sys.path), ``config`` (ignored passthrough for API compat).
"""
from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile

_UNSUPPORTED = ("pip", "conda", "uv", "container", "image_uri",
                "java_jars", "nsight")
_SUPPORTED = ("env_vars", "working_dir", "py_modules", "config")

# driver-side cache: fingerprint of (relpath, mtime_ns, size) per file ->
# (content_hash, zip_bytes). Keying on content metadata (not just the
# path) means editing a file and resubmitting ships the NEW code — the
# fingerprint walk is cheap, the zip isn't.
_pack_cache: dict[str, tuple[str, bytes]] = {}


def _fingerprint(path: str) -> str:
    entries = []
    if os.path.isfile(path):
        st = os.stat(path)
        entries.append((os.path.basename(path), st.st_mtime_ns, st.st_size))
    else:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in
                             ("__pycache__", ".git", ".venv"))
            for fn in sorted(files):
                full = os.path.join(root, fn)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((os.path.relpath(full, path),
                                st.st_mtime_ns, st.st_size))
    return hashlib.sha256(repr((path, entries)).encode()).hexdigest()


def validate(renv: dict) -> None:
    for k in renv:
        if k in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env[{k!r}] is not supported on this runtime: the "
                f"TPU image is hermetic (no package network); bake deps "
                f"into the image or vendor them via py_modules")
        if k not in _SUPPORTED:
            raise ValueError(f"unknown runtime_env key {k!r}; supported: "
                             f"{_SUPPORTED}")
    ev = renv.get("env_vars", {})
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise TypeError("runtime_env['env_vars'] must be dict[str, str]")


def _zip_path(path: str) -> bytes:
    """Deterministic zip of a dir or single file (stable hash for caching)."""
    buf = io.BytesIO()
    path = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isfile(path):
            zi = zipfile.ZipInfo(os.path.basename(path))
            zi.compress_type = zipfile.ZIP_DEFLATED
            with open(path, "rb") as f:
                z.writestr(zi, f.read())
        else:
            entries = []
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in
                                 ("__pycache__", ".git", ".venv"))
                for fn in sorted(files):
                    full = os.path.join(root, fn)
                    entries.append((os.path.relpath(full, path), full))
            for rel, full in sorted(entries):
                zi = zipfile.ZipInfo(rel)  # fixed date -> deterministic
                zi.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as f:
                    z.writestr(zi, f.read())
    return buf.getvalue()


def _pack(path: str) -> tuple[str, bytes]:
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path {path!r} does not exist")
    path = os.path.abspath(path)
    key = _fingerprint(path)
    cached = _pack_cache.get(key)
    if cached is None:
        blob = _zip_path(path)
        h = hashlib.sha256(blob).hexdigest()[:16]
        if len(_pack_cache) > 64:  # bound memory across many env versions
            _pack_cache.clear()
        cached = _pack_cache[key] = (h, blob)
    return cached


def prepare(renv: dict, register_blob) -> dict:
    """Driver-side: validate, zip local paths, register blobs with the head
    via ``register_blob(hash, bytes)``. Returns the wire-form env spec
    (hashes instead of paths) with a deterministic overall ``hash``."""
    validate(renv)
    spec: dict = {}
    if renv.get("env_vars"):
        spec["env_vars"] = dict(renv["env_vars"])
    if renv.get("working_dir"):
        h, blob = _pack(renv["working_dir"])
        register_blob(h, blob)
        spec["working_dir"] = h
    if renv.get("py_modules"):
        hashes = []
        for p in renv["py_modules"]:
            h, blob = _pack(p)
            register_blob(h, blob)
            hashes.append(h)
        spec["py_modules"] = hashes
    if not spec:
        return {}
    import json
    # sort_keys canonicalizes nested dicts too — env_vars insertion order
    # must not fork dedicated-worker pools
    digest = hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()
    spec["hash"] = digest[:16]
    return spec


def env_hash(spec: dict | None) -> str | None:
    return spec.get("hash") if spec else None


def apply_in_worker(spec: dict, blobs: dict[str, bytes],
                    base_dir: str) -> None:
    """Worker-side: materialize the env in THIS process (the worker is
    dedicated to it). env_vars -> os.environ; working_dir -> extract,
    chdir, sys.path[0]; py_modules -> extract, sys.path."""
    for k, v in spec.get("env_vars", {}).items():
        os.environ[k] = v
    for h in spec.get("py_modules", []):
        d = _extract(blobs[h], os.path.join(base_dir, h))
        if d not in sys.path:
            sys.path.insert(0, d)
    wd = spec.get("working_dir")
    if wd is not None:
        d = _extract(blobs[wd], os.path.join(base_dir, wd))
        os.chdir(d)
        if d not in sys.path:
            sys.path.insert(0, d)


def _extract(blob: bytes, dest: str) -> str:
    dest = os.path.abspath(dest)
    if not os.path.isdir(dest):
        tmp = dest + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            for name in z.namelist():  # zip-slip guard
                target = os.path.abspath(os.path.join(tmp, name))
                if not target.startswith(tmp + os.sep) and target != tmp:
                    raise ValueError(f"zip entry escapes dest: {name!r}")
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)  # atomic: concurrent workers race safely
        except OSError:
            if not os.path.isdir(dest):
                raise
    return dest
