"""Per-task/actor runtime environments.

Reference parity: _private/runtime_env/ — the plugin framework
(plugin.py), working_dir/py_modules packaging (working_dir.py: zip to the
GCS KV store, extracted per node by the runtime-env agent,
agent/runtime_env_agent.py:164), and the dedicated-worker matching of
raylet's worker pool (worker_pool.h: workers are keyed by runtime-env
hash and never shared across envs).

TPU-first reductions, by design:
  - blobs travel over the control plane into a head-side registry (the
    function-registry mechanism) and are shipped to a worker once, at its
    first task with that env — same role as the reference's KV-store
    upload + per-node agent download, without a separate agent daemon;
  - workers are *dedicated*: a worker that applied env E only ever runs
    tasks with env E (matching the reference's pool semantics), so
    env_vars / cwd / sys.path can be applied process-wide;
  - ``pip`` / ``uv`` create a node-shared VENV per package list
    (``--system-site-packages``, reference: _private/runtime_env/pip.py,
    uv.py) and the dedicated worker puts its site-packages first on
    sys.path. The venv shares the worker's interpreter — package
    isolation, not interpreter swap, exactly the reference pip plugin's
    model (conda is the interpreter-swapping one);
  - ``conda`` / ``container`` are rejected up front: one interpreter and
    no container runtime in this image — a clear error beats a silent
    no-op.

Supported keys: ``env_vars`` (dict str→str), ``working_dir`` (local dir
path, zipped at submission), ``py_modules`` (list of local dirs/files put
on sys.path), ``pip`` / ``uv`` (list of requirement strings, or
{"packages": [...], "pip_install_options": [...]}), ``config`` (ignored
passthrough for API compat).
"""
from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile

_UNSUPPORTED = ("conda", "container", "image_uri", "java_jars", "nsight")
_SUPPORTED = ("env_vars", "working_dir", "py_modules", "pip", "uv",
              "config")

# driver-side cache: fingerprint of (relpath, mtime_ns, size) per file ->
# (content_hash, zip_bytes). Keying on content metadata (not just the
# path) means editing a file and resubmitting ships the NEW code — the
# fingerprint walk is cheap, the zip isn't.
_pack_cache: dict[str, tuple[str, bytes]] = {}


def _fingerprint(path: str) -> str:
    entries = []
    if os.path.isfile(path):
        st = os.stat(path)
        entries.append((os.path.basename(path), st.st_mtime_ns, st.st_size))
    else:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in
                             ("__pycache__", ".git", ".venv"))
            for fn in sorted(files):
                full = os.path.join(root, fn)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((os.path.relpath(full, path),
                                st.st_mtime_ns, st.st_size))
    return hashlib.sha256(repr((path, entries)).encode()).hexdigest()


def validate(renv: dict) -> None:
    for k in renv:
        if k in _UNSUPPORTED:
            raise ValueError(
                f"runtime_env[{k!r}] is not supported on this runtime "
                f"(one interpreter, no container runtime); use pip/uv "
                f"envs, bake deps into the image, or vendor them via "
                f"py_modules")
        if k not in _SUPPORTED:
            raise ValueError(f"unknown runtime_env key {k!r}; supported: "
                             f"{_SUPPORTED}")
    ev = renv.get("env_vars", {})
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise TypeError("runtime_env['env_vars'] must be dict[str, str]")
    if "pip" in renv and "uv" in renv:
        raise ValueError(
            "runtime_env cannot carry both 'pip' and 'uv' (one package "
            "provider per env; the reference rejects this too)")
    for key in ("pip", "uv"):
        if key in renv:
            _normalize_pip(renv[key], key)   # raises on bad shapes


def _normalize_pip(value, key: str) -> dict:
    """list[str] | {"packages": [...], "pip_install_options": [...]} ->
    {"packages": [...], "options": [...]}."""
    if isinstance(value, (list, tuple)):
        pkgs, opts = list(value), []
    elif isinstance(value, dict):
        pkgs = list(value.get("packages", []))
        opts = list(value.get("pip_install_options", []))
        unknown = set(value) - {"packages", "pip_install_options"}
        if unknown:
            raise ValueError(
                f"runtime_env[{key!r}] unknown fields {sorted(unknown)}")
    else:
        raise TypeError(
            f"runtime_env[{key!r}] must be a list of requirements or "
            f'{{"packages": [...], "pip_install_options": [...]}}')
    if not pkgs:
        raise ValueError(f"runtime_env[{key!r}] needs at least one package")
    if not all(isinstance(p, str) for p in pkgs + opts):
        raise TypeError(f"runtime_env[{key!r}] entries must be strings")
    return {"packages": pkgs, "options": opts}


def _zip_path(path: str) -> bytes:
    """Deterministic zip of a dir or single file (stable hash for caching)."""
    buf = io.BytesIO()
    path = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        if os.path.isfile(path):
            zi = zipfile.ZipInfo(os.path.basename(path))
            zi.compress_type = zipfile.ZIP_DEFLATED
            with open(path, "rb") as f:
                z.writestr(zi, f.read())
        else:
            entries = []
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs if d not in
                                 ("__pycache__", ".git", ".venv"))
                for fn in sorted(files):
                    full = os.path.join(root, fn)
                    entries.append((os.path.relpath(full, path), full))
            for rel, full in sorted(entries):
                zi = zipfile.ZipInfo(rel)  # fixed date -> deterministic
                zi.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as f:
                    z.writestr(zi, f.read())
    return buf.getvalue()


def _pack(path: str) -> tuple[str, bytes]:
    if not os.path.exists(path):
        raise FileNotFoundError(f"runtime_env path {path!r} does not exist")
    path = os.path.abspath(path)
    key = _fingerprint(path)
    cached = _pack_cache.get(key)
    if cached is None:
        blob = _zip_path(path)
        h = hashlib.sha256(blob).hexdigest()[:16]
        if len(_pack_cache) > 64:  # bound memory across many env versions
            _pack_cache.clear()
        cached = _pack_cache[key] = (h, blob)
    return cached


def prepare(renv: dict, register_blob) -> dict:
    """Driver-side: validate, zip local paths, register blobs with the head
    via ``register_blob(hash, bytes)``. Returns the wire-form env spec
    (hashes instead of paths) with a deterministic overall ``hash``."""
    validate(renv)
    spec: dict = {}
    if renv.get("env_vars"):
        spec["env_vars"] = dict(renv["env_vars"])
    if renv.get("working_dir"):
        h, blob = _pack(renv["working_dir"])
        register_blob(h, blob)
        spec["working_dir"] = h
    if renv.get("py_modules"):
        hashes = []
        for p in renv["py_modules"]:
            h, blob = _pack(p)
            register_blob(h, blob)
            hashes.append(h)
        spec["py_modules"] = hashes
    for key in ("pip", "uv"):
        if renv.get(key):
            spec["pip"] = _normalize_pip(renv[key], key)
            break   # uv is the same venv backend with a different frontend
    if not spec:
        return {}
    import json
    # sort_keys canonicalizes nested dicts too — env_vars insertion order
    # must not fork dedicated-worker pools
    digest = hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()
    spec["hash"] = digest[:16]
    return spec


def env_hash(spec: dict | None) -> str | None:
    return spec.get("hash") if spec else None


def apply_in_worker(spec: dict, blobs: dict[str, bytes],
                    base_dir: str) -> None:
    """Worker-side: materialize the env in THIS process (the worker is
    dedicated to it). env_vars -> os.environ; working_dir -> extract,
    chdir, sys.path[0]; py_modules -> extract, sys.path."""
    for k, v in spec.get("env_vars", {}).items():
        os.environ[k] = v
    for h in spec.get("py_modules", []):
        d = _extract(blobs[h], os.path.join(base_dir, h))
        if d not in sys.path:
            sys.path.insert(0, d)
    wd = spec.get("working_dir")
    if wd is not None:
        d = _extract(blobs[wd], os.path.join(base_dir, wd))
        os.chdir(d)
        if d not in sys.path:
            sys.path.insert(0, d)
    if spec.get("pip"):
        _activate_venv(_ensure_venv(spec["pip"], base_dir))


def _ensure_venv(pip_spec: dict, base_dir: str,
                 timeout_s: float = 300.0) -> str:
    """Create (once per node, race-guarded) the venv for this package
    list; returns its directory. --system-site-packages keeps the image's
    baked deps visible, matching the reference pip plugin's default."""
    import json
    import subprocess
    import time

    key = hashlib.sha256(
        json.dumps(pip_spec, sort_keys=True).encode()).hexdigest()[:16]
    venv_dir = os.path.join(base_dir, f"venv-{key}")
    done = os.path.join(venv_dir, ".rtpu_done")
    if os.path.exists(done):
        return venv_dir
    os.makedirs(base_dir, exist_ok=True)
    lock = venv_dir + ".lock"
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            os.makedirs(lock)
            with open(os.path.join(lock, "pid"), "w") as f:
                f.write(str(os.getpid()))
            break   # we own creation
        except FileExistsError:
            if os.path.exists(done):
                return venv_dir   # another worker finished it
            # stale lock? a worker killed mid-install (OOM, SIGKILL)
            # leaves the lock with no finally — reclaim when its pid is
            # gone so one crash can't wedge the env node-wide
            try:
                with open(os.path.join(lock, "pid")) as f:
                    owner = int(f.read().strip() or 0)
            except (OSError, ValueError):
                owner = None   # racing its creation: give it a beat
            if owner:
                try:
                    os.kill(owner, 0)
                except ProcessLookupError:
                    import shutil
                    shutil.rmtree(venv_dir, ignore_errors=True)
                    shutil.rmtree(lock, ignore_errors=True)
                    continue   # retake the lock
                except PermissionError:
                    pass       # alive under another uid: keep waiting
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"venv {venv_dir} creation stuck behind {lock}")
            time.sleep(0.2)
    try:
        if os.path.exists(done):
            return venv_dir
        r = subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             venv_dir],
            capture_output=True, text=True, timeout=timeout_s)
        if r.returncode:
            raise RuntimeError(f"venv creation failed:\n{r.stderr[-2000:]}")
        cmd = [os.path.join(venv_dir, "bin", "python"), "-m", "pip",
               "install", *pip_spec.get("options", []),
               *pip_spec["packages"]]
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s)
        if r.returncode:
            raise RuntimeError(
                f"pip install failed ({' '.join(cmd)}):\n"
                f"{r.stderr[-2000:]}")
        with open(done, "w") as f:
            f.write("ok")
        return venv_dir
    finally:
        import shutil
        shutil.rmtree(lock, ignore_errors=True)


def _activate_venv(venv_dir: str) -> None:
    """Put the venv's site-packages FIRST on sys.path and its bin on PATH.
    Same interpreter (venv shares this CPython): package isolation, not
    interpreter swap — the reference pip plugin's model."""
    import glob
    sps = glob.glob(os.path.join(venv_dir, "lib", "python*",
                                 "site-packages"))
    if not sps:
        raise RuntimeError(f"no site-packages under {venv_dir}")
    if sps[0] not in sys.path:
        sys.path.insert(0, sps[0])
    os.environ["VIRTUAL_ENV"] = venv_dir
    os.environ["PATH"] = (os.path.join(venv_dir, "bin") + os.pathsep
                          + os.environ.get("PATH", ""))


def _extract(blob: bytes, dest: str) -> str:
    dest = os.path.abspath(dest)
    if not os.path.isdir(dest):
        tmp = dest + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            for name in z.namelist():  # zip-slip guard
                target = os.path.abspath(os.path.join(tmp, name))
                if not target.startswith(tmp + os.sep) and target != tmp:
                    raise ValueError(f"zip entry escapes dest: {name!r}")
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)  # atomic: concurrent workers race safely
        except OSError:
            if not os.path.isdir(dest):
                raise
    return dest
