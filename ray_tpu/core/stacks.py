"""Live stack capture + wait beacons — the "what is this process doing
RIGHT NOW" half of the observability stack (the stall doctor).

The flight recorder (core/flight.py) answers "what happened"; nothing in
it answers "why is this process hung *at this moment*" — the question
behind the repo's recurring failure class: workers parked forever in
channel waits on a dead peer, shutdown joining a wedged executor thread,
a rollout runner starved of credits. Reference parity: ``ray stack``
(py-spy over every worker) and the dashboard's hung-task views backed by
the GCS task-event store; TorchTitan makes the same case for production
training stacks — hang diagnosis must be built in and always-on.

Three pieces live here:

- **Capture** (:func:`capture`): every thread of THIS process via
  ``sys._current_frames()``, annotated with what the runtime knows —
  the task the thread is executing, the object/channel it is parked on
  (wait beacons), thread names — serialized as plain dicts so the head
  can pull them over the control plane (protocol-v6 ``stack_dump`` /
  ``stack_reply`` frames, answered from the per-connection recv threads
  exactly like ``flight_pull``, so a dump succeeds even when the
  target's executor threads are wedged).

- **Wait beacons**: each thread owns ONE preallocated 10-slot list
  (``[kind, id48, n, since_ns, task48]`` plus the continuation slots
  documented at the layout constants below) registered in a module
  table. The wait hot paths (``os_wait_sealed`` / ``os_chan_get`` call
  sites in object_store.py, the ack waits in dag/channel.py) write the
  slots before parking and zero ``kind`` after — no allocation, no
  locks, no strings on the hot path (same budget discipline as
  ``flight.evt``). A beacon turns an opaque native futex wait into
  "parked 3.2s on channel 0x8a1f… slot" in a stack report.

- **Channel endpoint tables**: producers note themselves per channel
  base (one dict store per write/ack — and at RingWriter/RingReader
  construction, so a never-written deadlocked channel still resolves).
  The head folds beacons + these tables + its object directory into a
  waiter→producer wait graph and runs cycle detection
  (Runtime.hang_report) — a constructed two-channel wait cycle names
  both parties instead of hanging silently.

Surfaced as ``state.stack_report()`` / ``state.hang_report()``,
``python -m ray_tpu.cli stack [--all]`` and ``cli doctor``, dashboard
``GET /api/stacks``.
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Any, Optional

from . import flight

# --------------------------------------------------------------------- #
# wait beacons
# --------------------------------------------------------------------- #

# beacon kinds (slot 0); 0 = not waiting
WAIT_NONE = 0
WAIT_OBJ = 1    # os_wait_sealed over object ids (get/wait bulk paths)
WAIT_GET = 2    # blocking os_get on one object id
WAIT_CHAN = 3   # os_chan_get on a channel data slot
WAIT_ACK = 4    # credit/ack wait (channel ring backpressure)

KIND_NAMES = {WAIT_OBJ: "object_wait", WAIT_GET: "object_get",
              WAIT_CHAN: "channel_recv", WAIT_ACK: "channel_credit"}

# beacon slots: [kind, id48, n, since_ns, task48,
#                prev_kind, prev_id48, prev_since_ns, cleared_at_ns,
#                prev_tag]
# Slots 5-9 make `since` survive SLICED waits: the blocking call sites
# park in bounded native slices (200-500ms) and re-arm the beacon per
# slice — re-arming the SAME (kind, id48, tag) within _REARM_GAP_NS of
# the last clear is one logical wait, so it keeps the original since.
# Without this, "parked for_s" caps at one slice length and the
# deadlock detector's sustained-wait gate can never trigger. The `tag`
# disambiguates waits that share a lo48 (channel slot ids share their
# base's first 6 bytes across seqs — a healthy consumer advancing
# seq-by-seq must read as a NEW wait each message, not one ever-growing
# park, or the sustained-wait gate would see phantom deadlocks in
# saturated pipelines).
_B_KIND, _B_ID, _B_N, _B_SINCE, _B_TASK, \
    _B_PKIND, _B_PID, _B_PSINCE, _B_CLEARED, _B_PTAG = range(10)

#: max gap between clear and re-arm still counted as the same logical
#: wait (slices re-arm within microseconds; real re-waits on the same
#: channel base after USING the value take far longer than this)
_REARM_GAP_NS = 50_000_000

_tls = threading.local()
_reg_lock = threading.Lock()
#: tid -> that thread's beacon list (read by capture; written only by
#: the owning thread — slot stores are atomic under the GIL)
_beacons: dict[int, list] = {}

#: channel-base lo48 -> tid of the thread that produces into it (write
#: sites overwrite; endpoint constructors seed an initial guess so a
#: never-written channel still resolves). Acks count as production: the
#: CONSUMER seals acks, so a producer parked in an ack wait resolves to
#: the consumer thread through this same table.
_chan_producers: dict[int, int] = {}
_CHAN_TABLE_MAX = 4096


def beacon() -> list:
    """This thread's beacon (created + registered on first use; every
    later call is one thread-local attribute read)."""
    b = getattr(_tls, "b", None)
    if b is None:
        b = [0, 0, 0, 0, 0, 0, 0, 0, 0, 0]
        _tls.b = b
        with _reg_lock:
            _beacons[threading.get_ident()] = b
    return b


def wait_tag(id_bytes: bytes) -> int:
    """Continuation tag for a waited id: bytes 10:16 — for channel slot
    ids (base[:12] + uint32 seq) this covers the seq, so consecutive
    messages on one channel read as distinct logical waits."""
    return int.from_bytes(id_bytes[10:16], "little")


def set_wait(b: list, kind: int, id48: int, n: int = 1,
             tag: int = 0) -> None:
    """Arm the beacon before parking. Callers pass the list from
    beacon() so the hot path pays no repeated lookup. Re-arming the
    same (kind, id48, tag) right after a clear continues the previous
    logical wait (sliced native parks keep one honest since)."""
    now = time.monotonic_ns()
    if b[_B_PKIND] == kind and b[_B_PID] == id48 and \
            b[_B_PTAG] == tag and now - b[_B_CLEARED] < _REARM_GAP_NS:
        since = b[_B_PSINCE]
    else:
        since = now
        b[_B_PKIND] = kind
        b[_B_PID] = id48
        b[_B_PTAG] = tag
        b[_B_PSINCE] = since
    b[_B_ID] = id48
    b[_B_N] = n
    b[_B_SINCE] = since
    b[_B_KIND] = kind


def clear_wait(b: list) -> None:
    b[_B_CLEARED] = time.monotonic_ns()
    b[_B_KIND] = 0


def set_task(task48: int) -> None:
    """Executor threads mark the task they are running (worker.py task /
    actor-call paths); 0 clears. Rides the same beacon list."""
    beacon()[_B_TASK] = task48


def note_producer(base48: int) -> None:
    """Record this thread as the producer of channel `base48` (called
    per write/ack — one dict store — and at endpoint construction)."""
    if len(_chan_producers) >= _CHAN_TABLE_MAX and \
            base48 not in _chan_producers:
        # bounded: drop the oldest registration (dict preserves insertion
        # order); long-lived processes cycling many channels stay flat.
        # Eviction is rare (>=4096 live bases), so it may take the lock
        # and tolerate a concurrent writer racing the iterator — the
        # common path above stays a single GIL-atomic dict store.
        with _reg_lock:
            try:
                while len(_chan_producers) >= _CHAN_TABLE_MAX:
                    _chan_producers.pop(next(iter(_chan_producers)), None)
            except (StopIteration, RuntimeError):
                pass  # lost the race with a concurrent store; table is
                # near the cap either way, never wrong
    _chan_producers[base48] = threading.get_ident()


# --------------------------------------------------------------------- #
# capture
# --------------------------------------------------------------------- #

def capture(include_stacks: bool = True) -> dict:
    """Snapshot every thread of this process: stack frames (outermost
    first), thread name, the task it is executing, and the wait beacon
    if it is parked in an instrumented wait. Plain dicts/lists only —
    the snapshot crosses the control plane pickled."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    now = time.monotonic_ns()
    with _reg_lock:
        # prune beacons of threads that no longer exist (bounded growth
        # for pools that cycle threads), then snapshot the live ones
        for tid in list(_beacons):
            if tid not in frames:
                del _beacons[tid]
        beacons = {tid: list(b) for tid, b in _beacons.items()}
    threads = []
    for tid, frame in frames.items():
        th: dict[str, Any] = {"tid": tid, "name": names.get(tid, "")}
        b = beacons.get(tid)
        if b is not None:
            if b[_B_KIND]:
                th["wait"] = {
                    "kind": KIND_NAMES.get(b[_B_KIND], str(b[_B_KIND])),
                    "id48": b[_B_ID], "n": b[_B_N],
                    "for_s": max(0.0, (now - b[_B_SINCE]) / 1e9),
                }
            if b[_B_TASK]:
                th["task48"] = b[_B_TASK]
        if include_stacks:
            th["stack"] = [
                (fs.filename, fs.lineno, fs.name, fs.line or "")
                for fs in traceback.extract_stack(frame)]
        threads.append(th)
    threads.sort(key=lambda t: t["tid"])
    return {
        "pid": os.getpid(),
        "proc": flight.proc_name(),
        "mono_ns": now,
        "wall_ns": time.time_ns(),
        "threads": threads,
        "chan_producers": dict(_chan_producers),
    }


def dump_reply(msg: dict) -> dict:
    """The ``stack_reply`` answer to a ``stack_dump`` frame — the one
    place the protocol-v6 reply payload is built (worker recv loop and
    driver conn loop both send exactly this)."""
    return {"t": "stack_reply", "nonce": msg["nonce"],
            "snap": capture(include_stacks=not msg.get("no_stacks",
                                                       False))}


# --------------------------------------------------------------------- #
# formatting (cli stack / cli doctor)
# --------------------------------------------------------------------- #

def _interesting(th: dict) -> bool:
    """A thread worth showing by default: executing a task, parked in an
    instrumented wait, or the main thread."""
    return bool(th.get("wait") or th.get("task48")
                or th.get("name") == "MainThread")


def format_thread(th: dict, indent: str = "  ") -> str:
    head = f"{indent}thread {th['tid']}"
    if th.get("name"):
        head += f" [{th['name']}]"
    if th.get("task"):
        head += f"  task={th['task']}"
    elif th.get("task48"):
        head += f"  task48=0x{th['task48']:012x}"
    w = th.get("wait")
    if w:
        target = w.get("target") or f"0x{w['id48']:012x}"
        head += (f"  << parked {w['for_s']:.1f}s in {w['kind']} "
                 f"on {target}" + (f" (+{w['n'] - 1} more)"
                                   if w.get("n", 1) > 1 else ""))
    lines = [head]
    for fname, lineno, func, code in th.get("stack", ()):
        lines.append(f"{indent}  {fname}:{lineno} in {func}")
        if code:
            lines.append(f"{indent}    {code}")
    return "\n".join(lines)


def format_report(report: dict, show_all: bool = False) -> str:
    """Human-readable cluster stack report (Runtime.stack_report()
    shape). ``show_all`` includes idle/bookkeeping threads; the default
    shows threads executing a task, parked in an instrumented wait, or
    main threads."""
    out = []
    for snap in report.get("procs", []):
        shown = [t for t in snap.get("threads", ())
                 if show_all or _interesting(t)]
        hidden = len(snap.get("threads", ())) - len(shown)
        out.append(f"=== {snap.get('proc') or '?'} "
                   f"(pid {snap.get('pid')}) — {len(snap.get('threads', ()))}"
                   f" threads ===")
        for th in shown:
            out.append(format_thread(th))
        if hidden:
            out.append(f"  ... {hidden} idle threads hidden "
                       f"(--all shows them)")
        out.append("")
    missing = report.get("unresponsive", ())
    if missing:
        out.append("UNRESPONSIVE (no stack reply before the deadline): "
                   + ", ".join(missing))
    return "\n".join(out)


def format_hangs(hangs: dict) -> str:
    """Human-readable hang report (Runtime.hang_report() shape)."""
    out = []
    stuck = hangs.get("stuck_tasks", ())
    if stuck:
        out.append(f"STUCK TASKS ({len(stuck)}):")
        for rec in stuck:
            line = (f"  {rec.get('name')} [{rec.get('task_id', '')[:12]}] "
                    f"on {rec.get('worker')} — running "
                    f"{rec.get('running_s', 0.0):.1f}s "
                    f"(threshold {rec.get('threshold_s', 0.0):.1f}s")
            if rec.get("ewma_s") is not None:
                line += f", typical {rec['ewma_s']:.2f}s"
            out.append(line + ")")
            for th in rec.get("stack", ()):
                out.append(format_thread(th, indent="    "))
    else:
        out.append("no stuck tasks")
    cycles = hangs.get("deadlocks", ())
    if cycles:
        out.append(f"SUSPECTED DEADLOCKS ({len(cycles)}):")
        for cyc in cycles:
            out.append("  cycle:")
            for node in cyc.get("parties", ()):
                out.append(f"    {node.get('proc')} thread "
                           f"{node.get('tid')}"
                           + (f" [{node['thread_name']}]"
                              if node.get("thread_name") else "")
                           + (f" task={node['task']}"
                              if node.get("task") else "")
                           + f" waits {node.get('wait_kind')} on "
                           + f"{node.get('target')}")
    else:
        out.append("no wait-graph cycles")
    wd = hangs.get("watchdog")
    if wd:
        out.append(f"watchdog: {'enabled' if wd.get('enabled') else 'OFF'}"
                   f", {wd.get('scans', 0)} scans, "
                   f"{wd.get('stuck_running', 0)} currently stuck, "
                   f"{wd.get('flagged_total', 0)} flagged total")
    return "\n".join(out)
