"""Task and actor specifications exchanged between driver and workers.

Reference parity: TaskSpecification (src/ray/common/task/task_spec.h, built
from common.proto TaskSpec). We use plain dataclasses over the pickle-based
connection transport instead of protobuf — the head process and workers share
a Python version, and the hot path (arg payloads) bypasses these structs via
the shared-memory store.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from .ids import ActorID, ObjectID, PlacementGroupID, TaskID


@dataclasses.dataclass
class TaskSpec:
    task_id: TaskID
    func_id: str                       # registry key (hash of pickled fn)
    name: str                          # human-readable, for errors/events
    args_blob: bytes                   # cloudpickle((args, kwargs)), refs by-ref
    dep_oids: list[ObjectID]           # top-level ObjectRef args to resolve
    return_ids: list[ObjectID]
    resources: dict[str, float]
    retries_left: int = 0
    retry_exceptions: bool = False
    # actor-task fields
    actor_id: Optional[ActorID] = None
    method_name: Optional[str] = None
    seq_no: int = 0                    # per-actor submission order
    # placement
    pg_id: Optional[PlacementGroupID] = None
    pg_bundle_index: int = -1
    node_affinity: Optional[bytes] = None   # NodeID binary, soft=false only
    node_affinity_soft: bool = False
    scheduling_strategy: str = "DEFAULT"    # DEFAULT | SPREAD
    # node label requirements (reference: label_selector on tasks/actors,
    # the node-label scheduling strategy): every (k, v) must equal the
    # candidate node's labels
    label_selector: Optional[dict] = None
    owner: str = "driver"              # "driver" or worker-id hex
    # prepared runtime env (hashes, not blobs — core/runtime_env.py)
    runtime_env: Optional[dict] = None
    # num_returns="dynamic": the single return holds a list of ObjectRefs,
    # one per yielded item (reference: dynamic generators)
    dynamic_returns: bool = False
    # actor concurrency group this call runs in (transport
    # concurrency_group_manager.h analog)
    concurrency_group: Optional[str] = None
    # (trace_id, parent_span_id) — the submitter's span, so the task's
    # execution span parents correctly across processes (reference:
    # tracing_helper.py:293 injects OTel context into task metadata)
    trace_ctx: Optional[tuple] = None
    # worker retires (exits, pool respawns) after executing this function
    # this many times; 0 = unlimited (reference: @ray.remote(max_calls=N),
    # the leaked-state/GPU-memory release valve)
    max_calls: int = 0
    # submitting driver's namespace: in-task get_actor / named-actor
    # creation resolve in it, not in the worker host's default (reference:
    # tasks inherit the job's namespace)
    namespace: Optional[str] = None

    @property
    def is_actor_task(self) -> bool:
        return self.actor_id is not None and self.method_name is not None

    def __reduce__(self):
        # positional-tuple pickling: ~2.3x faster and ~half the bytes of
        # the default dataclass state-dict pickle — specs are the hottest
        # control message (every dispatch + every submit carries one).
        # Field ORDER is the wire format: any field add/remove/reorder
        # must bump protocol.PROTOCOL_VERSION (handshake-enforced).
        return (_rebuild_task_spec,
                (tuple(self.__dict__[f] for f in _TASK_FIELDS),))


@dataclasses.dataclass
class ActorSpec:
    actor_id: ActorID
    class_id: str                      # registry key for the pickled class
    name: str
    args_blob: bytes
    dep_oids: list[ObjectID]
    resources: dict[str, float]
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    pg_id: Optional[PlacementGroupID] = None
    pg_bundle_index: int = -1
    node_affinity: Optional[bytes] = None
    node_affinity_soft: bool = False
    label_selector: Optional[dict] = None
    named: Optional[str] = None        # ray.get_actor() name
    # named method pools: {"io": 2, ...} (concurrency groups)
    concurrency_groups: Optional[dict] = None
    # creation-readiness object: resolves when the actor __init__ finished
    ready_oid: Optional[ObjectID] = None
    runtime_env: Optional[dict] = None
    # creating driver's namespace — the actor's methods resolve named
    # actors in it (reference: an actor belongs to its job's namespace)
    namespace: Optional[str] = None

    def __reduce__(self):
        # see TaskSpec.__reduce__ — same wire-format/versioning contract
        return (_rebuild_actor_spec,
                (tuple(self.__dict__[f] for f in _ACTOR_FIELDS),))


_TASK_FIELDS = tuple(f.name for f in dataclasses.fields(TaskSpec))
_ACTOR_FIELDS = tuple(f.name for f in dataclasses.fields(ActorSpec))


def _rebuild_task_spec(vals):
    s = object.__new__(TaskSpec)
    s.__dict__.update(zip(_TASK_FIELDS, vals))
    return s


def _rebuild_actor_spec(vals):
    s = object.__new__(ActorSpec)
    s.__dict__.update(zip(_ACTOR_FIELDS, vals))
    return s


def validate_resources(res: dict[str, float]) -> dict[str, float]:
    out = {}
    for k, v in res.items():
        if v is None:
            continue
        v = float(v)
        if v < 0:
            raise ValueError(f"resource {k!r} must be >= 0, got {v}")
        if v:
            out[k] = v
    return out
