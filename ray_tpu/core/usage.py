"""Usage stats: opt-out feature-usage recording.

Reference parity: _private/usage/usage_lib.py (architecture comment
:20-28) — libraries record feature tags; a periodic job reports cluster
metadata + tags. This image has no egress, so the "report" is a json file
under the session dir (an operator's fleet tooling can scrape it);
``RTPU_USAGE_STATS_ENABLED=0`` disables recording entirely, matching the
reference's env opt-out.
"""
from __future__ import annotations

import json
import os
import threading
import time

_lock = threading.Lock()
_tags: dict[str, str] = {}
_libraries: set[str] = set()


def enabled() -> bool:
    return os.environ.get("RTPU_USAGE_STATS_ENABLED", "1") not in (
        "0", "false", "no")


def record_library_usage(name: str) -> None:
    """Called at first use of a library (data/train/tune/serve/rl/llm)."""
    if not enabled():
        return
    with _lock:
        _libraries.add(name)


def record_extra_usage_tag(key: str, value: str) -> None:
    if not enabled():
        return
    with _lock:
        _tags[key] = str(value)


def usage_snapshot() -> dict:
    from .._version import __version__
    with _lock:
        return {
            "version": __version__,
            "libraries": sorted(_libraries),
            "tags": dict(_tags),
            "ts": time.time(),
        }


def write_usage_file(session_dir: str) -> str | None:
    """Persist the snapshot (the head calls this at shutdown)."""
    if not enabled():
        return None
    path = os.path.join(session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(usage_snapshot(), f, indent=2)
        return path
    except OSError:
        return None
