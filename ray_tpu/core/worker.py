"""Worker process: executes tasks and hosts actors.

Reference parity: the worker side of the core worker (reference:
src/ray/core_worker/core_worker.h:166 task-execution loop, the Python hot
loop _raylet.pyx:2103 execute_task_with_cancellation_handler, and the actor
scheduling queues of transport/task_receiver.h:50 +
concurrency_group_manager.h). Differences from the reference, by design:

  - results are written straight into the node-shared mmap store and the head
    is notified with a tiny `done` message — no return-value RPC hop;
  - actor method ordering comes from head routing order + a single executor
    thread (max_concurrency=1), a thread pool for threaded actors, or an
    asyncio loop for async actors;
  - blocked-worker CPU release (`blocked`/`unblocked` messages) mirrors the
    reference's logic that returns a lease's resources while the worker waits
    in `ray.get` (raylet/local_task_manager.h).

Entry point: `python -m ray_tpu.core.worker` with RTPU_* env vars set by
Runtime._spawn_worker_locked.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import ctypes
import os
import sys
import threading
import time
import traceback
from collections import deque
from multiprocessing.connection import Client

import cloudpickle

from .. import exceptions as exc
from .ids import ObjectID
from .protocol import PROTOCOL_VERSION
from .object_store import GetTimeoutError as StoreTimeout
from .object_store import ObjectStoreFullError as StoreFull
from .object_store import SharedObjectStore, SpillStore
from .ref import ObjectRef
from .task_spec import ActorSpec, TaskSpec
from . import flight
from . import stacks
from . import runtime as rt_mod


import contextvars

# active task's namespace (a ContextVar flows into coroutines too, so
# async actor methods resolve names correctly — see _run_actor_task)
_ACTIVE_NS: "contextvars.ContextVar" = contextvars.ContextVar(
    "rtpu_active_namespace", default=None)


class WorkerRuntime:
    """Worker-side implementation of the runtime interface used by the public
    API (`ray_tpu.get/put/wait/...` called *inside* a task or actor)."""

    @property
    def namespace(self) -> str:
        """Namespace named-actor calls resolve in: the namespace of the
        job that submitted the RUNNING task (or created the running
        actor), falling back to the cluster default between tasks
        (reference: tasks/actors inherit their job's namespace)."""
        return _ACTIVE_NS.get() or self._default_ns

    @namespace.setter
    def namespace(self, value: str) -> None:
        # drivers (DriverRuntime) set their own default at connect
        self._default_ns = value

    def __init__(self, store: SharedObjectStore, conn, wid: str,
                 spill=None):
        from .config import cfg
        self.store = store
        self.spill = spill
        self.conn = conn
        self.wid = wid
        self.send_lock = threading.Lock()
        # adaptive flush buffer (protocol v3 batch frames), combining-lock
        # style: an async send appends and then TRY-acquires the
        # connection — uncontended it ships its own message immediately
        # (same cost as an unbuffered send: no extra thread, no wakeup
        # syscall), while under a burst the first sender becomes the
        # shipper and drains everything that accumulates during its pipe
        # writes into batch frames (one pickle + one syscall amortized
        # over N). Wire order is exactly buffer-append order: synchronous
        # send() drains the buffer in-order ahead of its own message, so
        # FIFO invariants (func_def before submit, ref_add before a later
        # drop) hold with batching on or off.
        self._batching = cfg.control_batching
        self._batch_max = max(1, cfg.send_batch_max)
        self._sbuf: list = []  # guarded by: self._sbuf_lock
        self._sbuf_lock = threading.Lock()
        self.func_registry: dict[str, object] = {}
        self._sent_fids: set[str] = set()
        self._sent_renvs: set[str] = set()
        # own-store node: misses pull via object_transfer; RPC replies come
        # over the conn into this dict instead of the (invisible) head store
        self.own_store = os.environ.get("RTPU_OWN_STORE") == "1"
        # fallback namespace when no task is executing (the head's);
        # during execution the SUBMITTING driver's namespace is active
        # (core/actor.py qualify_actor_name reads self.namespace)
        self._default_ns = os.environ.get("RTPU_NAMESPACE", "default")
        self._rpc_replies: dict[bytes, object] = {}
        self._rpc_reply_evt = threading.Event()
        self._rpc_abandoned: set[bytes] = set()
        self._last_fetch: dict = {}
        self._last_fetch_sweep = 0.0
        self.current_task_name = ""
        # process-local ObjectRef counts; 0<->1 transitions notify the head
        # (reference_count.h:73 borrower protocol, simplified)
        self._ref_counts: dict = {}  # guarded by: self._ref_lock
        self._ref_lock = threading.Lock()
        # return-ids of a task being submitted: their first ObjectRef needs
        # no ref_add send — the v2 submit/actor_call message itself carries
        # the submitter's interest (runtime._handle_msg "submit")
        self._presumed: set = set()  # guarded by: self._ref_lock
        # __del__ may fire from a GC pass triggered INSIDE send() or
        # ref_created() on the same thread; doing IPC or taking these locks
        # there would self-deadlock. Drops only enqueue (SimpleQueue.put is
        # reentrant-safe); a dedicated thread drains and notifies.
        import queue
        self._drop_q: "queue.SimpleQueue" = queue.SimpleQueue()
        threading.Thread(target=self._drop_loop, daemon=True,
                         name="ref-drops").start()

    # -- refcounting -------------------------------------------------------

    def ref_created(self, oid, from_transfer: bool):
        # the count transition and its notification must be ATOMIC per oid:
        # a drop-loop 1->0 send racing a fresh 0->1 add could otherwise
        # reach the head in the wrong order and strip live interest.
        # Holding _ref_lock across send() is safe: __del__ never takes
        # these locks (it only enqueues).
        with self._ref_lock:
            c = self._ref_counts.get(oid, 0)
            self._ref_counts[oid] = c + 1
            if c == 0 and not from_transfer and oid in self._presumed:
                self._presumed.discard(oid)
                return  # the submit message registers this interest
            if c == 0 or from_transfer:
                # async: appended under _ref_lock, so ordering against the
                # drop loop's sends (also under _ref_lock) is buffer order
                self.send_async({"t": "ref_add", "oid": oid.binary(),
                                 "transfer": from_transfer})

    def ref_deleted(self, oid):
        self._drop_q.put(oid)

    def _drop_loop(self):
        import queue as _q
        while True:
            oids = [self._drop_q.get()]
            # greedy drain: a GC pass killing a burst of refs becomes ONE
            # batched ref_drops message instead of one write per ref
            try:
                while len(oids) < 4096:
                    oids.append(self._drop_q.get_nowait())
            except _q.Empty:
                pass
            try:
                # compute + send under _ref_lock: a concurrent 0->1
                # ref_add must not land between our 1->0 decision and the
                # drop reaching the wire (same ordering rule as
                # ref_created's send-under-lock)
                with self._ref_lock:
                    dead = []
                    for oid in oids:
                        c = self._ref_counts.get(oid, 0) - 1
                        if c <= 0:
                            self._ref_counts.pop(oid, None)
                            dead.append(oid.binary())
                        else:
                            self._ref_counts[oid] = c
                    if len(dead) == 1:
                        self.send_async({"t": "ref_drop", "oid": dead[0]})
                    elif dead:
                        self.send_async({"t": "ref_drops", "oids": dead})
            except (OSError, EOFError):
                return  # connection gone: worker is exiting
            except Exception:
                # a combining drain can surface ANOTHER thread's poison-
                # message error here; this thread must keep servicing
                # drops or head-side refcounts leak for the process's life
                traceback.print_exc()

    def ref_serialized(self, oid):
        # async is safe: the xfer pin is appended BEFORE the message that
        # carries the serialized ref (same thread), so it reaches the head
        # first and the pin exists before any receiver can deserialize
        self.send_async({"t": "ref_xfer", "oid": oid.binary()})

    # -- messaging ---------------------------------------------------------

    def send(self, msg):
        """Synchronous send: drains the flush buffer in-order ahead of
        `msg` and ships everything as one frame."""
        with self._sbuf_lock:
            self._sbuf.append(msg)
        self._flush_now()

    def send_async(self, msg):
        """Buffered send: `msg` ships with this call when the connection
        is free, or rides the current shipper's next drain round when
        another thread is mid-write. Use for fire-and-forget control
        traffic; anything the caller waits on must go through send()."""
        if not self._batching:
            return self.send(msg)
        with self._sbuf_lock:
            self._sbuf.append(msg)
        self._try_flush()

    def flush(self):
        """Ship everything in the flush buffer now (no-op when empty)."""
        self._flush_now()

    def _try_flush(self):
        # Combining-lock drain. The liveness invariant: whoever sees a
        # non-empty buffer either drains it or observes send_lock held —
        # and every holder re-checks the buffer after releasing, so an
        # append racing a holder's final empty-check is picked up by that
        # holder's re-check (or by our next loop iteration). No message
        # can strand without a live shipper.
        while True:
            if not self.send_lock.acquire(blocking=False):
                return  # current holder's post-release re-check covers us
            try:
                # send_lock IS held here — via the try-acquire above,
                # which the with-block heuristic can't see
                self._drain_locked()  # graftlint: disable=GL001
            finally:
                self.send_lock.release()
            with self._sbuf_lock:
                if not self._sbuf:
                    return

    def _flush_now(self):
        while True:
            with self.send_lock:
                self._drain_locked()
            with self._sbuf_lock:
                if not self._sbuf:
                    return

    def _drain_locked(self):
        # pop + send are atomic under send_lock: a reconnecting driver
        # (client.py) holds send_lock while it replays state, so messages
        # still in the buffer are visible to (and excluded by) the replay
        while True:
            # batching off: one frame per message (the documented
            # debugging mode), still FIFO through the same buffer
            limit = self._batch_max if self._batching else 1
            with self._sbuf_lock:
                if not self._sbuf:
                    return
                if len(self._sbuf) > limit:
                    msgs = self._sbuf[:limit]
                    del self._sbuf[:limit]
                else:
                    msgs, self._sbuf = self._sbuf, []
            try:
                self.conn.send(msgs[0] if len(msgs) == 1
                               else {"t": "batch", "msgs": msgs})
                flight.evt(flight.CTRL_FLUSH, len(msgs))
            except (OSError, EOFError, KeyboardInterrupt, SystemExit):
                # transport failure (or an interrupt that may have landed
                # mid-write): put the unsent messages back at the FRONT,
                # in order — a reconnect replay (driver) or a later retry
                # must see them; re-sending individually here could
                # double-deliver bytes that already hit the wire
                with self._sbuf_lock:
                    self._sbuf[0:0] = msgs
                raise
            except BaseException as frame_err:
                if getattr(self.conn, "closed", False):
                    # e.g. ValueError from a connection torn down mid-send
                    # (a restart racing close): a transport symptom, not a
                    # bad payload — requeue for the ride/replay machinery
                    with self._sbuf_lock:
                        self._sbuf[0:0] = msgs
                    raise
                # deterministic failure (e.g. an unpicklable user payload
                # in a device_* message): Connection.send pickles BEFORE
                # writing, so nothing hit the wire — re-send individually
                # to isolate the poison message instead of requeueing a
                # frame that can never serialize (which would wedge every
                # later done/ref/put behind it forever)
                if len(msgs) == 1:
                    self._poison_dropped(msgs[0], frame_err)
                    raise frame_err
                poison = None
                for k, m in enumerate(msgs):
                    try:
                        self.conn.send(m)
                    except (OSError, EOFError, KeyboardInterrupt,
                            SystemExit):
                        with self._sbuf_lock:
                            self._sbuf[0:0] = msgs[k:]
                        raise
                    except BaseException as e:
                        if getattr(self.conn, "closed", False):
                            with self._sbuf_lock:
                                self._sbuf[0:0] = msgs[k:]
                            raise
                        if poison is None:
                            poison = e
                        traceback.print_exc()
                        self._poison_dropped(m, e)
                if poison is not None:
                    # raised to whichever thread is draining (the sender
                    # itself when uncontended); a submit's refs are made
                    # to error via _poison_dropped either way
                    raise poison

    def _poison_dropped(self, msg, err: BaseException) -> None:
        """A message was dropped because it can never serialize. If it
        was a submit, its return refs would otherwise hang every waiter
        forever (the head never learns of the task — and under combining
        the drop may surface in a DIFFERENT thread than the submitter):
        seal the error into the return oids so ray.get raises it."""
        try:
            if not isinstance(msg, dict) or \
                    msg.get("t") not in ("submit", "actor_call"):
                return
            spec = msg["spec"]
            werr = exc.RayTaskError(
                getattr(spec, "name", "task"),
                err if isinstance(err, Exception) else RuntimeError(
                    repr(err)))
            for oid in getattr(spec, "return_ids", ()):
                try:
                    self.store.put(oid, werr, is_exception=True)
                except Exception:
                    pass  # store full/closing; waiters time out
        except Exception:
            pass  # must never mask the original send error

    def _ship_func(self, fid: str, blob: bytes):
        if fid not in self._sent_fids:
            self.send_async({"t": "func_def", "fid": fid, "blob": blob})
            self._sent_fids.add(fid)

    def register_renv(self, h: str, blob: bytes):
        if h not in self._sent_renvs:
            self.send_async({"t": "renv_def", "hash": h, "blob": blob})
            self._sent_renvs.add(h)

    def register_function(self, fid: str, blob: bytes):
        self.func_registry.setdefault(fid, cloudpickle.loads(blob))
        self._ship_func(fid, blob)

    # -- object API --------------------------------------------------------

    def put(self, value, pin: bool = False):
        return self.put_at(ObjectID.from_random(), value)

    def expect(self, oid):
        """No-op (see Runtime.expect)."""

    def put_at(self, oid: ObjectID, value, is_exception: bool = False):
        self.store_or_spill(oid, value, is_exception, notify_put=True)
        return ObjectRef(oid)

    def store_or_spill(self, oid: ObjectID, value, is_exception: bool,
                       notify_put: bool):
        """Store a value, spilling the same serialized frame to disk when
        the shm store is full; refs pickled inside become containment edges
        on the head."""
        from .ref import capture_serialized_refs
        with capture_serialized_refs() as inner_ids:
            spilled = self.store.put_or_spill(oid, value, is_exception,
                                              self.spill)
        if inner_ids:
            self.send_async({"t": "contained", "oid": oid.binary(),
                             "inner": [i.binary() for i in inner_ids]})
        if spilled:
            self.send_async({"t": "put_spilled", "oid": oid.binary()})
        elif notify_put:
            self.send_async({"t": "put", "oid": oid})

    def get(self, refs, timeout: float | None = None):
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        try:
            if len(ref_list) > 1:
                # bulk fast path: one ensure for every missing ref up
                # front + one event-driven multi-oid wait, instead of a
                # per-ref ensure and a fresh poll slice per ref
                self._wait_all_present([r.id() for r in ref_list], deadline)
            for r in ref_list:
                out.append(self._get_one(r.id(), deadline,
                                         lambda: self._block(True)))
        finally:
            self._block(False)
        return out[0] if single else out

    def _sealed_is_exception(self, oid) -> bool:
        """Peek a sealed object's frame flags without deserializing."""
        view = self.store.get_raw(oid, timeout_ms=0)
        if view is None:
            return False
        try:
            from .object_store import _FLAG_EXCEPTION
            return bool(view[0] & _FLAG_EXCEPTION)
        finally:
            del view
            self.store.release(oid)

    def _spilled_is_exception(self, oid) -> bool:
        """Peek a spilled frame's flags byte (same wire framing)."""
        try:
            from .object_store import _FLAG_EXCEPTION
            with open(self.spill._path(oid), "rb") as f:
                b = f.read(1)
            return bool(b and b[0] & _FLAG_EXCEPTION)
        except OSError:
            return False

    def _present_is_exception(self, oid, sealed: bool) -> bool:
        return (self._sealed_is_exception(oid) if sealed
                else self._spilled_is_exception(oid))

    def _wait_all_present(self, oids, deadline):
        """Wait until every oid that the ordered materialization loop will
        actually reach is sealed in the store (or readable from spill),
        servicing whichever seals first via os_wait_sealed — the
        futex-on-seal notification path. Slices are only the re-check
        cadence for spill/cross-node fetches and grow exponentially; a
        seal wakes the waiter immediately regardless of slice length.
        Sequential-get parity: _get_one raises a stored task error at the
        FIRST errored index once everything before it resolved — so a
        sealed exception at index j stops this wait from blocking on
        anything at or past j (an error ahead of a never-completing ref
        must surface now, not after the hang). Returns on deadline expiry
        and leaves the per-ref timeout error (and value/error
        materialization) to _get_one."""
        flags = self.store.wait_sealed(oids, len(oids), 0)
        missing = [(i, o) for i, (o, f) in enumerate(zip(oids, flags))
                   if not f]
        if self.spill is not None and missing:
            missing = [(i, o) for i, o in missing
                       if not self.spill.contains(o)]
        if not missing:
            return  # all present: no waiting, no exception peeking
        # index of the first already-errored ref (sealed OR spilled
        # exception): only the prefix before it has to resolve before
        # _get_one can raise it in order. Peeked only now that we know
        # we'd otherwise block, and only up to the last missing index
        # (an error past every missing ref doesn't shrink the wait).
        err_before = len(oids)
        miss_idx = {i for i, _ in missing}
        for i in range(missing[-1][0]):
            if i in miss_idx:
                continue
            if self._present_is_exception(oids[i], sealed=flags[i]):
                err_before = i
                break
        missing = [(i, o) for i, o in missing if i < err_before]
        if not missing:
            return
        self._block(True)
        self.send({"t": "ensure",
                   "oids": [o.binary() for _, o in missing]})
        slice_ms = 10
        while True:
            active = [(i, o) for i, o in missing if i < err_before]
            if not active:
                return
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    return
                slice_ms = min(slice_ms, max(1, int(remain * 1000)))
            got = self.store.wait_sealed([o for _, o in active],
                                         len(active), slice_ms)
            still = []
            for (i, o), f in zip(active, got):
                spilled = (not f and self.spill is not None
                           and self.spill.contains(o))
                if f or spilled:
                    if self._present_is_exception(o, sealed=f):
                        err_before = min(err_before, i)
                    continue
                # ANY worker may need a cross-node pull (throttled to one
                # locate per object per second inside _try_fetch)
                self._try_fetch(o)
                still.append((i, o))
            missing = still
            slice_ms = min(slice_ms * 2, 200)

    def _mux_nudge(self, oid: ObjectID):
        """Completion-mux recovery hook (core/completion.py): an awaited
        oid stayed unsealed past the nudge window — ask the head to make
        it available (lineage re-exec of evicted objects) and try a
        cross-node pull (throttled inside _try_fetch)."""
        self.send({"t": "ensure", "oids": [oid.binary()]})
        self._try_fetch(oid)

    _did_block = False

    def _block(self, flag: bool):
        if flag and not self._did_block:
            self._did_block = True
            self.send({"t": "blocked"})
        elif not flag and self._did_block:
            self._did_block = False
            self.send({"t": "unblocked"})

    def _get_one(self, oid: ObjectID, deadline, on_wait):
        first = True
        while True:
            slice_ms = 200
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise exc.GetTimeoutError(f"timed out waiting for {oid}")
                slice_ms = max(1, min(slice_ms, int(remain * 1000)))
            try:
                return self.store.get(oid, timeout_ms=slice_ms)
            except StoreTimeout:
                if self.spill is not None and self.spill.contains(oid):
                    try:
                        return self.spill.load(oid)
                    except OSError:
                        pass  # freed between contains and load; keep waiting
                    except exc.RayTaskError as e:
                        raise e.as_instanceof_cause() from e
                if first:
                    flight.evt(flight.OBJ_MISS, flight.lo48(oid))
                    on_wait()
                    self.send({"t": "ensure", "oids": [oid.binary()]})
                    first = False
                # ANY worker may need a cross-node pull (a shared-store
                # worker can consume an own-store node's output too)
                self._try_fetch(oid)
                continue
            except exc.RayTaskError as e:
                raise e.as_instanceof_cause() from e

    def _try_fetch(self, oid: ObjectID) -> bool:
        """Pull a missing object from a holder node into the local store
        (the reference's PullManager retry loop, pull_manager.h:49 —
        throttled to one locate per object per second)."""
        now = time.monotonic()
        if now - self._last_fetch.get(oid, 0.0) < 1.0:
            return False
        self._last_fetch[oid] = now
        if len(self._last_fetch) > 1024 and \
                now - self._last_fetch_sweep > 10.0:
            # bounded: entries for refs that never fetch successfully are
            # only popped on success, so expire anything far outside the
            # 1s throttle window or a long-lived driver leaks the dict.
            # Time-gated so a bulk wait over >1024 hot refs (nothing
            # expirable yet) doesn't rebuild the dict on every attempt.
            self._last_fetch_sweep = now
            cutoff = now - 10.0
            self._last_fetch = {o: t for o, t in self._last_fetch.items()
                                if t > cutoff}
        try:
            addrs = self._rpc("locate", oid.binary(), timeout=10.0)
        except Exception:
            return False
        from .object_transfer import fetch_resilient
        try:
            if fetch_resilient(addrs, oid, self.store, self.spill):
                self._last_fetch.pop(oid, None)
                if self.own_store:
                    # the head must know this node holds a copy now
                    # (free fanout + future locates)
                    self.send({"t": "object_copied",
                               "oid": oid.binary()})
                return True
        except OSError:
            pass
        return False

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        # multi-oid wait primitive (os_wait_sealed): a seal wakes this
        # waiter immediately; the growing slice is only the fallback
        # cadence for spill re-checks and cross-node fetch retries —
        # replaces the fixed 2ms sleep poll that burned CPU and added up
        # to 2ms latency per completion
        ref_list = list(refs)
        deadline = None if timeout is None else time.monotonic() + timeout
        self.flush()  # buffered submits must ship before we park
        ready, pending = [], []
        flags = self.store.wait_sealed([r.id() for r in ref_list],
                                       len(ref_list), 0)
        for r, f in zip(ref_list, flags):
            present = f or (self.spill is not None
                            and self.spill.contains(r.id()))
            (ready if present else pending).append(r)
        notified = False
        slice_ms = 2
        while len(ready) < num_returns and pending:
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    break
                slice_ms = min(slice_ms, max(1, int(remain * 1000)))
            if not notified:
                # ONE ensure covering every pending ref up front (the old
                # loop also ensured once, but a later-starting wait on the
                # same refs never refreshed it)
                self.send({"t": "ensure",
                           "oids": [r.id().binary() for r in pending]})
                notified = True
            flags = self.store.wait_sealed(
                [r.id() for r in pending],
                num_returns - len(ready), slice_ms)
            still = []
            for r, f in zip(pending, flags):
                if f or (self.spill is not None
                         and self.spill.contains(r.id())):
                    ready.append(r)
                    continue
                if fetch_local:
                    self._try_fetch(r.id())
                still.append(r)
            pending = still
            slice_ms = min(slice_ms * 2, 200)  # exponential backoff
        # reference contract: at most num_returns refs in ready; extra
        # already-ready refs stay in the remaining list
        return ready[:num_returns], ready[num_returns:] + pending

    # -- task/actor API ----------------------------------------------------

    def submit_task(self, spec: TaskSpec):
        spec.owner = self.wid
        # v2: the submit message itself carries our interest in the
        # returns (head adds it before the task can run), so the local
        # refs are constructed without a ref_add send each
        with self._ref_lock:
            self._presumed.update(spec.return_ids)
        refs = [ObjectRef(o) for o in spec.return_ids]
        self.send_async({"t": "submit", "spec": spec})
        return refs

    def create_actor(self, spec: ActorSpec):
        self.send({"t": "create_actor", "spec": spec})

    def submit_actor_task_spec(self, spec: TaskSpec):
        spec.owner = self.wid
        with self._ref_lock:
            self._presumed.update(spec.return_ids)  # see submit_task
        refs = [ObjectRef(o) for o in spec.return_ids]
        self.send_async({"t": "actor_call", "spec": spec})
        return refs

    def kill_actor(self, actor_id, no_restart=True):
        self.send({"t": "kill_actor", "actor_id": actor_id.binary(),
                   "no_restart": no_restart})

    def cancel(self, ref, force=False, recursive=True):
        self.send({"t": "cancel", "oid": ref.id().binary(), "force": force})

    # -- head RPCs (reply lands in the shared store, see Runtime
    # _handle_worker_rpc) ---------------------------------------------------

    def _rpc(self, method: str, *args, timeout: float = 30.0):
        return self._rpc_frame({"t": "rpc", "m": method, "args": args},
                               method, timeout=timeout)

    def _rpc_frame(self, msg: dict, label: str, timeout: float = 30.0):
        """Send a request frame that the head answers through the rpc
        reply plumbing (a ("ok"/"err", payload) tuple at reply_oid —
        Runtime._reply_rpc), and wait for the reply. `msg` is any frame
        dict the head answers this way ("rpc" itself, "dir_query");
        the reply_oid is stamped here."""
        reply = ObjectID.from_random()
        msg = {**msg, "reply_oid": reply.binary()}
        self.send(msg)
        deadline = time.monotonic() + timeout
        rb = reply.binary()
        while True:
            got = self._rpc_replies.pop(rb, None)
            if got is not None:
                status, payload = got
                break
            if self.own_store:
                # reply arrives over the conn; park on the event
                self._rpc_reply_evt.wait(timeout=0.1)
                self._rpc_reply_evt.clear()
                if time.monotonic() > deadline:
                    self._rpc_abandoned.add(rb)
                    raise exc.GetTimeoutError(
                        f"head rpc {label} timed out") from None
                continue
            # event-driven: the reply's seal wakes this futex wait
            # immediately (was: a 100ms store.get poll slice per pass);
            # the bounded slice only re-arms against a reconnect-swapped
            # store object
            remain_ms = int((deadline - time.monotonic()) * 1000)
            if remain_ms <= 0:
                # let the head reclaim the reply if it lands later
                self.send({"t": "rpc_abandon",
                           "reply_oid": reply.binary()})
                raise exc.GetTimeoutError(
                    f"head rpc {label} timed out") from None
            sealed = self.store.wait_sealed(
                [reply], 1, min(1000, remain_ms))[0]
            if sealed:
                try:
                    status, payload = self.store.get(reply, timeout_ms=0)
                except StoreTimeout:
                    continue  # evicted between seal and read: retry
                self.store.delete(reply)
                break
        if status == "err":
            raise payload
        return payload

    def get_actor_by_name(self, name):
        return self._rpc("get_actor_by_name", name)

    def create_placement_group(self, bundles, strategy, name="",
                               same_label=None, bundle_selectors=None):
        from ..util.placement_group import PlacementGroup
        pg_id, specs = self._rpc("create_placement_group_rpc",
                                 bundles, strategy, name,
                                 same_label, bundle_selectors)
        return PlacementGroup(pg_id, specs)

    def remove_placement_group(self, pg_id):
        self._rpc("remove_placement_group_rpc", pg_id)

    def pg_wait(self, pg_id, timeout: float = 30.0) -> bool:
        return self._rpc("pg_wait", pg_id, timeout, timeout=timeout + 10.0)

    def cluster_resources(self):
        return self._rpc("cluster_resources")

    def available_resources(self):
        return self._rpc("available_resources")

    def node_table(self):
        return self._rpc("node_table")

    def timeline(self):
        return []

    def shutdown(self):
        pass


def _dial_head(addr: str, authkey: bytes, timeout_s: float = 15.0):
    """Connect to the head's control listener, retrying transient connect
    failures. Under load (single-CPU CI, a burst of worker spawns) the
    AF_UNIX connect can hit the listener's backlog and fail with EAGAIN
    (BlockingIOError) — the head's accept loop just hasn't been scheduled
    yet. Giving up on the first try killed the worker at birth, failing
    its dispatched task with WorkerCrashedError."""
    deadline = time.monotonic() + timeout_s
    delay = 0.05
    while True:
        try:
            if os.environ.get("RTPU_HEAD_FAMILY") == "AF_INET":
                host, port = addr.rsplit(":", 1)
                return Client((host, int(port)), authkey=authkey)
            return Client(addr, "AF_UNIX", authkey=authkey)
        except (BlockingIOError, InterruptedError, ConnectionRefusedError,
                ConnectionResetError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 0.5)


class WorkerLoop:
    def __init__(self):
        store_path = os.environ["RTPU_STORE_PATH"]
        addr = os.environ["RTPU_HEAD_ADDR"]
        authkey = bytes.fromhex(os.environ["RTPU_AUTHKEY"])
        self.wid = os.environ["RTPU_WORKER_ID"]
        flight.set_proc_name("worker:" + self.wid)
        self.store = SharedObjectStore(store_path)
        spill_dir = os.environ.get("RTPU_SPILL_DIR")
        spill = SpillStore(spill_dir) if spill_dir else None
        self.conn = _dial_head(addr, authkey)
        self.rt = WorkerRuntime(self.store, self.conn, self.wid, spill)
        rt_mod.set_runtime(self.rt)
        self.actor_instance = None
        self.actor_spec: ActorSpec | None = None
        self.executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task-exec")
        self.actor_pool: concurrent.futures.ThreadPoolExecutor | None = None
        self.group_pools: dict[str, concurrent.futures.ThreadPoolExecutor] = {}
        self.aio_loop: asyncio.AbstractEventLoop | None = None
        self._exec_tid: int | None = None
        self._current_task_id = None
        self._cancel_lock = threading.Lock()
        self._renv_error: BaseException | None = None
        self._dynamic_items = None
        # dispatch nonces the head reclaimed from our pipeline (set by the
        # recv loop, checked by the exec thread before running)
        self._stolen: set[str] = set()
        # per-function execution counts for @remote(max_calls=N) retirement
        self._fn_calls: dict[str, int] = {}

    # -- arg resolution ----------------------------------------------------

    def _resolve_args(self, blob: bytes):
        args, kwargs = cloudpickle.loads(blob)
        args = [self.rt.get(a) if isinstance(a, ObjectRef) else a
                for a in args]
        kwargs = {k: (self.rt.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        return args, kwargs

    # -- execution ---------------------------------------------------------

    def _store_value(self, oid, value, is_exception=False):
        """Store a task output, spilling to disk when the store is full."""
        self.rt.store_or_spill(oid, value, is_exception, notify_put=False)

    def _store_returns(self, spec: TaskSpec, result):
        n = len(spec.return_ids)
        if n == 0:
            return
        if getattr(spec, "dynamic_returns", False):
            # generator task: each yielded item becomes its own object at a
            # DETERMINISTIC id derived from the task id, so a lineage
            # re-execution regenerates the SAME ids and in-hand item refs
            # resolve again (reference reconstructs dynamic returns too);
            # the declared return resolves to the list of refs (containment
            # edges keep items alive); the head links item lineage from the
            # dynamic_items field of the done message
            import hashlib as _h
            if self.store.contains(spec.return_ids[0]):
                return  # a retry re-executed an already-stored return
            item_refs = []
            for idx, item in enumerate(result):
                oid = ObjectID(_h.sha1(
                    spec.task_id.binary() + b"dyn%d" % idx).digest()[:16])
                try:
                    self.rt.put_at(oid, item)
                except FileExistsError:
                    pass  # retry: the item is already there
                item_refs.append(ObjectRef(oid))
            self._dynamic_items = [r.id().binary() for r in item_refs]
            try:
                self._store_value(spec.return_ids[0], item_refs)
            except FileExistsError:
                pass  # lost the race with another attempt; dropping
                # item_refs frees this attempt's items via refcounting
            return
        if n == 1:
            vals = [result]
        else:
            vals = list(result)
            if len(vals) != n:
                raise ValueError(
                    f"task {spec.name} declared num_returns={n} but returned "
                    f"{len(vals)} values")
        for oid, v in zip(spec.return_ids, vals):
            try:
                self._store_value(oid, v)
            except FileExistsError:
                pass  # retry re-executed an already-stored return

    def _run_task(self, spec: TaskSpec, nonce: str | None = None):
        if nonce is not None and nonce in self._stolen:
            # the head reclaimed this pipelined dispatch (we blocked or it
            # was cancelled); it runs elsewhere — no done, no returns
            self._stolen.discard(nonce)
            return
        self._current_task_id = spec.task_id
        self.rt.current_task_name = spec.name
        t0 = time.time()
        flight.evt(flight.EXEC_BEGIN, flight.lo48(spec.task_id))
        # live-stack annotation: this thread is running this task (the
        # head's stack/hang reports resolve the lo48 back to the record)
        stacks.set_task(flight.lo48(spec.task_id))
        span_rec = None
        ns_tok = _ACTIVE_NS.set(getattr(spec, "namespace", None))
        try:
            if self._renv_error is not None:
                raise self._renv_error
            fn = self.rt.func_registry[spec.func_id]
            args, kwargs = self._resolve_args(spec.args_blob)
            tctx = getattr(spec, "trace_ctx", None)
            if tctx is not None:
                # child span of the submitter; tasks submitted inside fn
                # inherit it (util/tracing.py; reference
                # tracing_helper.py:326)
                from ..util.tracing import activate
                with activate(tctx, spec.name) as span_rec:
                    span_rec["task_id"] = spec.task_id.hex()
                    result = fn(*args, **kwargs)
            else:
                result = fn(*args, **kwargs)
            self._store_returns(spec, result)
            ok, err, retryable = True, None, False
        except BaseException as e:  # noqa: BLE001
            ok = False
            retryable = spec.retries_left > 0 and (
                spec.retry_exceptions or isinstance(e, exc.WorkerCrashedError))
            err = "".join(traceback.format_exception_only(type(e), e)).strip()
            if not retryable:
                werr = e if isinstance(e, exc.RayError) else exc.RayTaskError(
                    spec.name, e)
                for oid in spec.return_ids:
                    try:
                        self.store.delete(oid)
                        self._store_value(oid, werr, is_exception=True)
                    except Exception:
                        pass  # store full/closing; done msg carries err
        finally:
            self._current_task_id = None
            stacks.set_task(0)
            _ACTIVE_NS.reset(ns_tok)
        flight.evt(flight.EXEC_END, flight.lo48(spec.task_id), int(ok))
        self.rt._did_block = False
        done_msg = {"t": "done", "task_id": spec.task_id, "ok": ok,
                    "err": err, "retryable": retryable, "name": spec.name,
                    "dur": time.time() - t0}
        if span_rec is not None:
            done_msg["span"] = span_rec
        if getattr(self, "_dynamic_items", None):
            done_msg["dynamic_items"] = self._dynamic_items
            self._dynamic_items = None
        mc = getattr(spec, "max_calls", 0)
        retire = False
        if mc:
            # @remote(max_calls=N): retire this worker after N executions
            # of the function — the release valve for user code that
            # leaks process state (reference: worker_pool's
            # max-calls-triggered worker exit). Exit AFTER the done send:
            # the head sees done, then EOF; anything pipelined behind us
            # requeues via _on_worker_death.
            n = self._fn_calls[spec.func_id] = \
                self._fn_calls.get(spec.func_id, 0) + 1
            retire = n >= mc
        if retire:
            # synchronous: the done (and everything buffered before it)
            # must be on the wire before os._exit
            self.rt.send(done_msg)
            os._exit(0)
        # async: the result is already SEALED in the store (that futex
        # wake is what unblocks a ray.get), so the done only feeds head
        # bookkeeping — back-to-back completions coalesce into one frame
        self.rt.send_async(done_msg)

    def _run_actor_create(self, spec: ActorSpec):
        # the actor lives in its creating job's namespace: __init__ AND
        # every later method call resolve names there
        self._actor_ns = getattr(spec, "namespace", None)
        ns_tok = _ACTIVE_NS.set(self._actor_ns)
        try:
            if self._renv_error is not None:
                raise self._renv_error
            cls = self.rt.func_registry[spec.class_id]
            args, kwargs = self._resolve_args(spec.args_blob)
            self.actor_instance = cls(*args, **kwargs)
            self.actor_spec = spec
            if spec.max_concurrency > 1:
                self.actor_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=spec.max_concurrency,
                    thread_name_prefix="actor-exec")
            # named concurrency groups: independent pools so one group's
            # long calls never block another's
            # (transport/concurrency_group_manager.h analog)
            for gname, width in (spec.concurrency_groups or {}).items():
                self.group_pools[gname] = \
                    concurrent.futures.ThreadPoolExecutor(
                        max_workers=max(1, int(width)),
                        thread_name_prefix=f"cg-{gname}")
            if any(asyncio.iscoroutinefunction(getattr(cls, m, None))
                   for m in dir(cls) if not m.startswith("__")):
                self.aio_loop = asyncio.new_event_loop()
                threading.Thread(target=self.aio_loop.run_forever,
                                 daemon=True, name="actor-aio").start()
            self.rt.send({"t": "actor_ready", "actor_id": spec.actor_id,
                          "ok": True})
        except BaseException as e:  # noqa: BLE001
            tb = traceback.format_exc()
            self.rt.send({"t": "actor_ready", "actor_id": spec.actor_id,
                          "ok": False, "err": tb})
        finally:
            _ACTIVE_NS.reset(ns_tok)

    def _run_actor_task(self, spec: TaskSpec):
        t0 = time.time()
        flight.evt(flight.EXEC_BEGIN, flight.lo48(spec.task_id))
        stacks.set_task(flight.lo48(spec.task_id))
        span_rec = None
        try:
            group = getattr(spec, "concurrency_group", None)
            if group is not None and group not in self.group_pools:
                raise ValueError(
                    f"unknown concurrency group {group!r}; declare it via "
                    f"Actor.options(concurrency_groups={{...}}) "
                    f"(have: {sorted(self.group_pools)})")
            if group is not None and asyncio.iscoroutinefunction(
                    getattr(type(self.actor_instance), spec.method_name,
                            None)):
                raise ValueError(
                    "concurrency groups bound sync methods only; async "
                    "methods all share the actor's event loop (use an "
                    "asyncio.Semaphore inside the actor to bound them)")
            args, kwargs = self._resolve_args(spec.args_blob)
            if spec.method_name == "__rtpu_exec__":
                # internal injection point: run an arbitrary function with
                # the actor instance (compiled-DAG loops, debugging probes;
                # reference analog: __ray_call__)
                fn = cloudpickle.loads(args[0])
                method = lambda *a, **kw: fn(self.actor_instance, *a, **kw)  # noqa: E731
                args = args[1:]
            else:
                method = getattr(self.actor_instance, spec.method_name)
            tctx = getattr(spec, "trace_ctx", None)

            # methods resolve names in the actor's CREATION namespace
            # (reference: an actor belongs to its job's namespace), not
            # the caller's; async methods get it via the coroutine
            # wrapper since a thread-local set here wouldn't cross into
            # the event loop
            actor_ns = getattr(self, "_actor_ns", None)

            async def _with_ns(coro):
                tok = _ACTIVE_NS.set(actor_ns)
                try:
                    return await coro
                finally:
                    _ACTIVE_NS.reset(tok)

            def _invoke():
                # async methods run on the actor's event loop; the span
                # wraps the synchronous wait so sync and async methods
                # both trace (reference tracing_helper.py:407 wraps all
                # actor methods regardless of kind)
                if asyncio.iscoroutinefunction(method):
                    fut = asyncio.run_coroutine_threadsafe(
                        _with_ns(method(*args, **kwargs)), self.aio_loop)
                    return fut.result()
                tok = _ACTIVE_NS.set(actor_ns)
                try:
                    return method(*args, **kwargs)
                finally:
                    _ACTIVE_NS.reset(tok)

            if tctx is not None:
                from ..util.tracing import activate
                with activate(tctx, spec.name) as span_rec:
                    span_rec["task_id"] = spec.task_id.hex()
                    result = _invoke()
            else:
                result = _invoke()
            self._store_returns(spec, result)
            ok, err = True, None
        except BaseException as e:  # noqa: BLE001
            ok = False
            err = "".join(traceback.format_exception_only(type(e), e)).strip()
            werr = e if isinstance(e, exc.RayError) else exc.RayTaskError(
                spec.name, e)
            for oid in spec.return_ids:
                try:
                    self.store.delete(oid)
                    self.store.put(oid, werr, is_exception=True)
                except Exception:
                    pass  # store full/closing; done msg carries err
        stacks.set_task(0)
        flight.evt(flight.EXEC_END, flight.lo48(spec.task_id), int(ok))
        done_msg = {"t": "done", "task_id": spec.task_id, "ok": ok,
                    "err": err, "retryable": False, "name": spec.name,
                    "dur": time.time() - t0}
        if span_rec is not None:
            done_msg["span"] = span_rec
        self.rt.send_async(done_msg)

    def _cancel_current(self, task_id):
        """Best-effort cooperative cancel: raise TaskCancelledError inside the
        executor thread (reference analog: the KeyboardInterrupt raised by
        _raylet.pyx execute_task_with_cancellation_handler)."""
        with self._cancel_lock:
            if self._current_task_id != task_id or self._exec_tid is None:
                return
            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(self._exec_tid),
                ctypes.py_object(exc.TaskCancelledError))

    def _exec_wrapper(self, fn, *a):
        self._exec_tid = threading.get_ident()
        fn(*a)

    def _serve_device_get(self, msg: dict):
        from ..experimental.device_objects import _fetch_payload
        try:
            self.rt.send({"t": "device_payload",
                          "reply_oid": msg["reply_oid"],
                          "requester": msg.get("requester", "driver"),
                          "payload": _fetch_payload(msg["key"])})
        except Exception:
            traceback.print_exc()

    def _apply_renv(self, msg: dict):
        from . import runtime_env as renv_mod
        if msg.get("missing"):
            # blobs lost head-side; poison this worker's tasks clearly
            self._renv_error = RuntimeError(
                f"runtime_env blobs missing on head: {msg['missing']}")
            return
        try:
            renv_mod.apply_in_worker(msg["spec"], msg["blobs"],
                                     base_dir="/tmp/ray_tpu/renvs")
        except Exception as e:  # noqa: BLE001 — surface via task errors
            self._renv_error = e

    def run(self):
        self.conn.send({"t": "register", "wid": self.wid,
                        "pid": os.getpid(), "pv": PROTOCOL_VERSION})
        backlog: deque = deque()
        while True:
            if backlog:
                msg = backlog.popleft()
            else:
                try:
                    msg = self.conn.recv()
                except (EOFError, OSError):
                    # head gone (SIGKILL/crash — not the graceful "exit"
                    # frame). A plain return would hang interpreter
                    # shutdown joining executor threads: long-lived actor
                    # loops (compiled-DAG node loops, rl rollout
                    # producers) park in channel waits whose stop flag
                    # the dead head can never seal. Nothing left to
                    # flush to — exit hard, never orphan the process.
                    if _pre_exit_hook is not None:
                        _pre_exit_hook()   # profiler dump (main() sets it)
                    os._exit(0)
            if msg["t"] == "batch":
                # one pipe write from the head's scheduling pass carrying
                # several ordered control messages; they run BEFORE any
                # already-queued batch's remainder (extendleft preserves
                # the batch's own order)
                backlog.extendleft(reversed(msg["msgs"]))
                continue
            t = msg["t"]
            if t == "func":
                self.rt.func_registry[msg["fid"]] = cloudpickle.loads(
                    msg["blob"])
                self.rt._sent_fids.add(msg["fid"])
            elif t == "renv":
                # dedicate this worker to the runtime env BEFORE the task
                # that needs it arrives (messages are ordered); application
                # runs on the exec thread so it cannot race a running task
                self.executor.submit(self._exec_wrapper, self._apply_renv,
                                     msg)
            elif t == "task":
                self.executor.submit(self._exec_wrapper, self._run_task,
                                     msg["spec"], msg.get("n"))
            elif t == "actor_create":
                self.executor.submit(self._exec_wrapper,
                                     self._run_actor_create, msg["spec"])
            elif t == "actor_task":
                group = getattr(msg["spec"], "concurrency_group", None)
                pool = (self.group_pools.get(group)
                        or self.actor_pool or self.executor)
                if self.aio_loop is not None and asyncio.iscoroutinefunction(
                        getattr(type(self.actor_instance),
                                msg["spec"].method_name, None)):
                    # async methods run concurrently on the loop; dispatch
                    # from a shim thread so the recv loop never blocks
                    threading.Thread(target=self._run_actor_task,
                                     args=(msg["spec"],), daemon=True).start()
                else:
                    pool.submit(self._exec_wrapper, self._run_actor_task,
                                msg["spec"])
            elif t == "rpc_reply":
                if msg["reply_oid"] in self.rt._rpc_abandoned:
                    self.rt._rpc_abandoned.discard(msg["reply_oid"])
                else:
                    self.rt._rpc_replies[msg["reply_oid"]] = msg["payload"]
                    self.rt._rpc_reply_evt.set()
            elif t == "device_get":
                # serve a device-object fetch; serialization can be large,
                # keep the recv loop free
                threading.Thread(
                    target=self._serve_device_get, args=(msg,),
                    daemon=True).start()
            elif t == "flight_pull":
                # head pulling this process's flight-recorder ring; the
                # snapshot samples (mono_ns, wall_ns) together for the
                # head's wall-clock-bridge offset estimate, and is a
                # buffer copy — cheap enough for this loop
                self.rt.send_async(flight.pull_reply(msg))
            elif t == "stack_dump":
                # head pulling live thread stacks (stall doctor). Handled
                # HERE, on the recv thread, exactly like flight_pull: the
                # dump must succeed even when every executor thread is
                # wedged — that is the whole point of the feature
                self.rt.send_async(stacks.dump_reply(msg))
            elif t == "cancel":
                self._cancel_current(msg["task_id"])
            elif t == "steal":
                # handled on the recv thread so it lands BEFORE the exec
                # thread reaches the stolen dispatch in its queue
                self._stolen.update(msg["nonces"])
            elif t == "exit":
                try:
                    import sys
                    # zero this process's per-proc engine gauges first:
                    # the head store is last-write-wins and no one else
                    # will ever update a dead replica's series
                    tmod = sys.modules.get("ray_tpu.llm.telemetry")
                    if tmod is not None:
                        tmod.zero_proc_gauges()
                    from ..util.metrics import shutdown_flush
                    shutdown_flush()   # final counter deltas to the head
                except Exception:
                    pass  # final flush is best-effort on exit
                try:
                    self.rt.flush()    # buffered dones/refs before _exit
                except Exception:
                    pass  # conn may be gone; exiting anyway
                if _pre_exit_hook is not None:
                    _pre_exit_hook()   # profiler dump (main() sets it)
                os._exit(0)


_pre_exit_hook = None


def main():
    prof_dir = os.environ.get("RTPU_WORKER_PROFILE_DIR")
    if prof_dir:
        # per-worker cProfile dumps (reference analog: worker profiling via
        # py-spy in _private/profiling.py); enable with
        # RTPU_WORKER_PROFILE_DIR=/some/dir before init. The exit message
        # calls os._exit, so the dump runs via _pre_exit_hook.
        import cProfile
        import io
        import pstats
        pr = cProfile.Profile()

        def dump():
            pr.disable()
            s = io.StringIO()
            pstats.Stats(pr, stream=s).sort_stats(
                "tottime").print_stats(25)
            try:
                with open(os.path.join(
                        prof_dir, f"worker-{os.getpid()}.prof"), "w") as f:
                    f.write(s.getvalue())
            except OSError:
                pass

        global _pre_exit_hook
        _pre_exit_hook = dump
        pr.enable()
        try:
            main_inner()
        finally:
            dump()
    else:
        main_inner()


def main_inner():
    loop = WorkerLoop()
    try:
        loop.run()
    except Exception:
        traceback.print_exc()
        sys.exit(1)


if __name__ == "__main__":
    main()
