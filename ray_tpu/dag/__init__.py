"""ray_tpu.dag — compiled graphs (aDAG analog).

Reference parity: ray compiled graphs (python/ray/dag/compiled_dag_node.py:808
CompiledDAG, schedule generation dag_node_operation.py:686, shared-memory
channels experimental/channel/shared_memory_channel.py over the C++ mutable
objects, experimental_mutable_object_manager.h:44).

TPU-first redesign: the reference compiles DAGs to avoid per-call task
overhead for GPU pipelines; here the same is achieved with **sealed ring
channels** (dag/channel.py): every DAG edge gets a pair of id bases
(data + ack); message ``seq`` seals at ``base[:12] + uint32(seq)``, the
consumer parks in ONE ``os_wait_sealed`` futex wait over ``{data, stop}``
and reads **zero-copy** (ids are never reused, so pinned views can't
collide with a rewrite), then retires the ring position by sealing a tiny
ack object the producer consumes before writing ``seq + ring``. Objects
stay immutable, matching the store's contract, where the reference needed
a special mutable-object type with reader/writer semaphores.
Each participating actor runs a compiled loop (installed via the internal
``__rtpu_exec__`` injection) that steps its nodes in topological order;
after compile, ``execute()`` never touches the head scheduler — the
driver writes input channels and reads output channels directly.
``cfg.dag_sealed_channels = False`` restores the legacy consume-once
polling transport (delete-and-recreate slots, 100ms poll slices).

    with InputNode() as inp:
        x = preproc.step.bind(inp)
        out = trainer.step.bind(x)
    cdag = out.experimental_compile(max_inflight=2)
    for batch in data:
        print(cdag.execute(batch).get())
    cdag.teardown()
"""
from .channel import ChannelClosed, RingReader, RingWriter
from .compiled import CompiledDAG, CompiledDAGRef
from .nodes import ClassMethodNode, InputNode

__all__ = ["InputNode", "ClassMethodNode", "CompiledDAG", "CompiledDAGRef",
           "ChannelClosed", "RingReader", "RingWriter"]
