"""Sealed ring channels — the event-driven shm transport behind compiled
DAGs and the serve static decode plan.

Protocol (replaces the delete-and-recreate polling transport):

- A channel is a pair of 12-byte id *bases* (``data``, ``ack``); message
  ``seq`` lives at ``ObjectID(base[:12] + uint32le(seq))``. Ids are unique
  for the channel's lifetime (4B seqs), so a slot is never rewritten under
  an id a stale reader might still pin — which is what makes **zero-copy**
  reads safe here (the old transport recreated the SAME id every ring pass
  and had to force the copy path; see store.get's zero_copy note).
- The producer seals slot ``seq``; the consumer parks in ONE
  ``os_wait_sealed`` futex wait over ``{data[seq], stop}`` and wakes the
  instant either seals — no 100ms ``store.get`` poll slices, no
  ``contains(stop)`` probe per slice.
- After reading, the consumer deletes the data slot (lazy if zero-copy
  views still pin it — harmless, the id is never reused).
- **Backpressure** is credit-based and optional: a FREE-RUNNING producer
  (serve decode streams) writing ``seq`` first waits on
  ``{ack[seq - ring], stop}`` — the consumer seals the tiny ack object
  for each message it reads — and deletes the observed ack; that retires
  the ring position and bounds the channel to ``ring`` in-flight
  messages without any delete-and-recreate. Driver-PACED pipelines
  (compiled DAGs) skip acks entirely: the driver only feeds input ``n``
  after draining output ``n - ring``, which already proves every edge
  consumed ``n - ring`` (all nodes are ancestors of the output node).
- Teardown seals ``stop`` in every participating store; every parked
  wait in the channel wakes and raises :class:`ChannelClosed`.

- **Multi-producer fan-in** (:class:`MultiRingReader`): N producers each
  own a (data, ack) base pair sharing one stop flag; the consumer parks
  in ONE ``os_wait_sealed`` over {every producer's next slot, stop} and
  services whichever seals first, acking per-producer so credit windows
  stay independent (rl/podracer's RolloutQueue rides this).

Cross-store edges: data pushes into the consumer's store and acks push
back into the producer's (``object_transfer.push_object``); same-store
edges are plain seals. Channel objects are invisible to the head's object
directory on purpose — lifetime is fully owned by the seal/ack handshake.
"""
from __future__ import annotations

import struct
import time
from typing import Any, Optional

from ..core.ids import ObjectID
from ..core import flight
from ..core import stacks

# how long one futex park lasts before the waiter re-checks its deadline
# and (optionally) its liveness callback; a seal/stop wakes it instantly
# regardless, so this bounds failure detection latency, not throughput
_WAIT_SLICE_MS = 500


class ChannelClosed(Exception):
    """The channel's stop flag sealed while waiting (teardown/cancel)."""


def slot_oid(base: bytes, seq: int) -> ObjectID:
    return ObjectID(base[:12] + struct.pack("<I", seq & 0xFFFFFFFF))


# Sequence number reserved for the end-of-stream marker. Writers allocate
# seqs from 0 upward and a channel never lives long enough to reach it, so
# the id can't collide with a data slot.
EOS_SEQ = 0xFFFFFFFF


def eos_oid(base: bytes) -> ObjectID:
    """The end-of-stream marker id for a channel. Unlike a sentinel
    message, sealing it needs NO ring credit — a producer can always end
    a stream even when every data slot is un-acked (the data.streaming
    fan-out writers depend on that: EOS for an idle consumer must not
    wait on that consumer's credit)."""
    return slot_oid(base, EOS_SEQ)


def seal_eos(store, base: bytes, count: int,
             push_addr: Optional[str] = None) -> None:
    """Seal the end-of-stream marker carrying the final message count.
    Consumers treat a ring as exhausted once ``eos`` is sealed AND their
    cursor reached ``count``."""
    oid = eos_oid(base)
    flight.evt(flight.CHAN_SEAL, flight.lo48(base), EOS_SEQ)
    if push_addr is not None:
        from ..core.object_transfer import push_object
        push_object(push_addr, oid, value=int(count))
        return
    try:
        store.put(oid, int(count))
    except FileExistsError:
        pass  # idempotent (teardown retry)


def read_eos(store, base: bytes) -> Optional[int]:
    """Non-blocking: the final message count if EOS sealed, else None."""
    from ..core.object_store import GetTimeoutError
    try:
        return int(store.get(eos_oid(base), timeout_ms=0))
    except GetTimeoutError:
        return None


def ack_base_for(base: bytes) -> bytes:
    """The ack-channel id base paired with a data base (derived, so only
    the data base needs plumbing through plans and channel specs)."""
    import hashlib
    return hashlib.sha1(base + b"/ack").digest()[:16]


def _store_frame(store, oid: ObjectID, frame) -> None:
    """Write a pre-serialized _FramedValue under `oid` (serialize once,
    fan out to many targets)."""
    buf = store.create_raw(oid, frame.total)
    frame.write_into(buf)
    del buf
    store.seal(oid)


def write_slot(store, base: bytes, seq: int, value: Any = None,
               frame=None, push_addr: Optional[str] = None) -> None:
    """Seal message `seq` into the channel. With `push_addr`, the value
    lands in the remote store behind it (cross-store edge); `frame` is an
    optional pre-built _FramedValue shared across fan-out targets."""
    oid = slot_oid(base, seq)
    # the producer half of the per-message seal->wake flow edge: the
    # consumer's CHAN_WAKE carries the same (chan48, seq) pair, which is
    # what lets the exporter draw the cross-process arrow. Recorded
    # BEFORE the physical seal: the consumer wakes the instant the seal
    # lands, so stamping afterwards would let a descheduled producer
    # record its seal LATER than the wake that consumed it — the edge
    # must stay ordered on a shared clock
    b48 = flight.lo48(base)
    flight.evt(flight.CHAN_SEAL, b48, seq)
    # producer endpoint registration (one dict store): the wait-graph
    # deadlock fold resolves "thread X parked on channel C" to THIS
    # thread through it (stacks.py)
    stacks.note_producer(b48)
    if push_addr is not None:
        from ..core.object_store import _FramedValue
        from ..core.object_transfer import push_object
        if frame is None:
            frame = _FramedValue(value, False)
        if not push_object(push_addr, oid, frame=frame):
            raise RuntimeError(
                f"channel push to {push_addr} rejected (store full?)")
    elif frame is not None:
        _store_frame(store, oid, frame)
    else:
        store.put(oid, value)


def read_slot(store, base: bytes, seq: int, stop_oid: ObjectID,
              timeout_s: Optional[float] = None,
              zero_copy: Optional[bool] = None,
              ack_base: Optional[bytes] = None,
              ack_push_addr: Optional[str] = None, on_idle=None) -> Any:
    """Consume message `seq`: block on {data, stop}, read, delete the
    slot, optionally ack.

    The block+read is ONE stop-aware native call (os_chan_get) — same
    cost as a plain blocking get, and teardown wakes it instantly.
    Raises ChannelClosed if the stop flag seals with no data present
    (data wins over a concurrent stop: drain, then close). `on_idle`
    runs between wait slices — liveness probes ("did the producing actor
    die?") hook in there and may raise. The delete is lazy while
    zero-copy views pin the payload — safe, the id is never reused.
    With `ack_base`, the 1-byte ack for `seq` seals into the producer's
    store (free-running producers need it for ring backpressure;
    driver-paced DAGs don't — the output auto-drain already bounds every
    edge to the ring)."""
    from ..core.object_store import ChannelStopped, GetTimeoutError
    oid = slot_oid(base, seq)
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        slice_ms = _WAIT_SLICE_MS if (on_idle is not None
                                      or deadline is not None) else -1
        if deadline is not None:
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise GetTimeoutError(
                    f"timed out waiting for channel slot {seq}")
            slice_ms = max(1, min(slice_ms, int(remain * 1000)))
        try:
            val = store.get_chan(oid, stop_oid, timeout_ms=slice_ms,
                                 zero_copy=zero_copy)
            break
        except ChannelStopped:
            raise ChannelClosed("channel stop flag sealed") from None
        except GetTimeoutError:
            if on_idle is not None:
                on_idle()
    flight.evt(flight.CHAN_WAKE, flight.lo48(base), seq)
    store.delete(oid)
    if ack_base is not None:
        send_ack(store, ack_base, seq, ack_push_addr)
    return val


def send_ack(store, ack_base: bytes, seq: int,
             push_addr: Optional[str] = None) -> None:
    """Seal the 1-byte ack for `seq` into the producer's store."""
    oid = slot_oid(ack_base, seq)
    a48 = flight.lo48(ack_base)
    flight.evt(flight.CHAN_ACK, a48, seq)
    # the CONSUMER produces acks: a producer parked in an ack wait
    # resolves to this thread in the wait-graph fold
    stacks.note_producer(a48)
    if push_addr is not None:
        from ..core.object_transfer import push_object
        push_object(push_addr, oid, value=True)
        return
    buf = store.create_raw(oid, 1)
    buf[0:1] = b"\x01"
    del buf
    store.seal(oid)


def await_ack(store, ack_base: bytes, seq: int, stop_oid: ObjectID,
              timeout_s: Optional[float] = None, on_idle=None) -> None:
    """Producer-side ring retirement: block until the consumer acked
    `seq`, then delete the ack object. Raises ChannelClosed on stop."""
    from ..core.object_store import GetTimeoutError
    oid = slot_oid(ack_base, seq)
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    a48 = flight.lo48(ack_base)
    flight.evt(flight.CREDIT_BEGIN, a48, seq)
    # credit-wait beacon spanning the whole retirement wait: the inner
    # wait_sealed slices see it armed and leave it in place, so a stack
    # dump reports "channel_credit on <ack chan>" instead of a generic
    # object wait per slice
    bcn = stacks.beacon()
    armed = not bcn[0]
    if armed:
        stacks.set_wait(bcn, stacks.WAIT_ACK, a48, tag=seq)
    try:
        while True:
            slice_ms = _WAIT_SLICE_MS
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise GetTimeoutError(
                        f"timed out waiting for channel ack {seq}")
                slice_ms = max(1, min(slice_ms, int(remain * 1000)))
            acked, stopped = store.wait_sealed([oid, stop_oid], 1,
                                               slice_ms)
            if acked:
                store.delete(oid)
                return
            if stopped:
                raise ChannelClosed("channel stop flag sealed")
            if on_idle is not None:
                on_idle()
    finally:
        if armed:
            stacks.clear_wait(bcn)
        flight.evt(flight.CREDIT_END, a48)


def signal_stop(store, stop_oid: ObjectID) -> None:
    """Seal the stop flag locally (idempotent): every parked channel wait
    in this store wakes and raises ChannelClosed."""
    flight.evt(flight.CHAN_STOP, flight.lo48(stop_oid))
    try:
        store.put(stop_oid, True)
    except FileExistsError:
        pass  # already stopped


def drain_stale_slots(store, bases: list[bytes], lo: int, hi: int,
                      eos: bool = False) -> None:
    """Best-effort teardown sweep: delete any [lo, hi) slots still in the
    local store for the given bases. The ack handshake bounds live slots
    to the last ring positions, so callers pass a window, not the full
    history. With ``eos``, each base's end-of-stream marker is swept
    too (streams torn down before the consumer observed it)."""
    for base in bases:
        for seq in range(max(0, lo), hi):
            try:
                store.delete(slot_oid(base, seq))
            except Exception:
                return  # store closing; slots die with it
        if eos:
            try:
                store.delete(eos_oid(base))
            except Exception:
                return  # store closing; slots die with it


class MultiRingReader:
    """Fan-in consumer over N independent ring channels sharing ONE stop
    flag (multi-producer support: each producer owns its own (data, ack)
    base pair, so per-producer seqs never interleave and a slot id is
    still never reused). The consumer parks in ONE ``os_wait_sealed``
    futex wait spanning every producer's next-expected slot plus the
    stop flag and services whichever seals first — the multi-oid analog
    of ``os_chan_get``'s {data, stop} pair, with the same semantics:
    data wins over a concurrent stop (drain, then close).

    Fairness: when several producers have a sealed slot in the same
    wake, service rotates round-robin from the last producer served, so
    a fast producer can't starve the rest. Backpressure stays
    per-producer: each read acks into THAT producer's ack channel, so
    one producer's credit window never throttles another's.
    """

    def __init__(self, store, bases: list[bytes], stop_oid: ObjectID,
                 ring: int, zero_copy: Optional[bool] = None,
                 ack_push_addrs: Optional[list] = None):
        self.store = store
        self.bases = list(bases)
        self.ack_bases = [ack_base_for(b) for b in self.bases]
        self.stop = stop_oid
        self.ring = max(1, ring)
        self.zero_copy = zero_copy
        self.ack_push_addrs = (list(ack_push_addrs) if ack_push_addrs
                               else [None] * len(self.bases))
        self.seqs = [0] * len(self.bases)
        self._rr = 0  # next producer index favoured by the rotation
        self._fl_open = True
        flight.chan_opened(len(self.bases))
        for ab in self.ack_bases:
            stacks.note_producer(flight.lo48(ab))  # this end seals acks

    def _slots(self) -> list[ObjectID]:
        return [slot_oid(b, s) for b, s in zip(self.bases, self.seqs)]

    def sealed_now(self) -> list[bool]:
        """Non-blocking: which producers have their next slot sealed."""
        return self.store.wait_sealed(self._slots(), 0, 0)

    def depth(self) -> int:
        """Sealed-but-unread messages across all producers, scanning each
        producer's credit window (bounded: ring slots per producer).
        Telemetry only — one bulk non-blocking wait_sealed probe."""
        oids = [slot_oid(b, s + k)
                for b, s in zip(self.bases, self.seqs)
                for k in range(self.ring)]
        return len(self.store.wait_sealed_indices(oids, 0, 0))

    def read_any(self, timeout_s: Optional[float] = None,
                 on_idle=None) -> tuple[int, Any]:
        """Block until ANY producer's next message seals; consume it and
        return ``(producer_index, value)``. Raises ChannelClosed when the
        stop flag seals with no data pending, GetTimeoutError past the
        deadline. ``on_idle`` runs between wait slices (liveness probes
        — "did a producer actor die?" — hook in there and may raise)."""
        from ..core.object_store import GetTimeoutError
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        n = len(self.bases)
        while True:
            oids = self._slots() + [self.stop]
            slice_ms = _WAIT_SLICE_MS
            if deadline is not None:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise GetTimeoutError(
                        "timed out waiting for any rollout channel slot")
                slice_ms = max(1, min(slice_ms, int(remain * 1000)))
            sealed = self.store.wait_sealed(oids, 1, slice_ms)
            ready = [i for i in range(n) if sealed[i]]
            if ready:
                # round-robin among the producers that are ready NOW
                idx = min(ready, key=lambda i: (i - self._rr) % n)
                self._rr = (idx + 1) % n
                return idx, self._take(idx)
            if sealed[n]:
                raise ChannelClosed("channel stop flag sealed")
            if on_idle is not None:
                on_idle()

    def _take(self, idx: int) -> Any:
        """Consume producer `idx`'s next (already sealed) slot: read,
        delete, ack — retiring its ring position."""
        seq = self.seqs[idx]
        oid = slot_oid(self.bases[idx], seq)
        val = self.store.get(oid, timeout_ms=5000,
                             zero_copy=self.zero_copy)
        flight.evt(flight.CHAN_WAKE, flight.lo48(self.bases[idx]), seq)
        self.store.delete(oid)
        send_ack(self.store, self.ack_bases[idx], seq,
                 self.ack_push_addrs[idx])
        self.seqs[idx] = seq + 1
        return val

    def close(self) -> None:
        """Consumer-side teardown: seal the stop flag (every producer's
        parked ack wait / closed() probe aborts) and sweep the slot and
        ack windows around every cursor, in case a producer already
        exited and will never observe the stop."""
        signal_stop(self.store, self.stop)
        if self._fl_open:
            self._fl_open = False
            flight.chan_closed(len(self.bases))
        for base, ack_base, seq in zip(self.bases, self.ack_bases,
                                       self.seqs):
            drain_stale_slots(self.store, [base, ack_base],
                              seq - self.ring - 1, seq + self.ring)


class RingWriter:
    """Sequential producer end (serve decode streams; DAG edges use the
    functional API since one loop step writes many channels)."""

    def __init__(self, store, base: bytes, stop_oid: ObjectID, ring: int,
                 push_addr: Optional[str] = None,
                 ack_base: Optional[bytes] = None):
        self.store = store
        self.base = base
        self.ack_base = ack_base if ack_base is not None \
            else ack_base_for(base)
        self.stop = stop_oid
        self.ring = max(1, ring)
        self.push_addr = push_addr
        self.seq = 0
        # seed the endpoint table at construction: a deadlocked channel
        # that never got its first write still resolves to this thread
        # in the wait-graph fold (overwritten by the actual writing
        # thread on the first write_slot)
        stacks.note_producer(flight.lo48(self.base))

    def closed(self) -> bool:
        return self.store.contains(self.stop)

    def credit_ready(self) -> bool:
        """Non-blocking: would the next write() proceed without parking
        in a credit wait? True while the ring has free positions or the
        retiring ack is already sealed. Fan-out writers use this to pick
        a consumer with capacity (and to count backpressure stalls)
        before committing to a blocking write."""
        n = self.seq
        if n < self.ring:
            return True
        ack = slot_oid(self.ack_base, n - self.ring)
        return self.store.wait_sealed([ack], 0, 0)[0]

    def pending_ack_oid(self) -> Optional[ObjectID]:
        """The ack object the next write() would park on (None when the
        ring still has free positions). Lets a fan-out writer build ONE
        multi-oid wait across every full consumer ring instead of
        committing to a single consumer's credit."""
        n = self.seq
        if n < self.ring:
            return None
        return slot_oid(self.ack_base, n - self.ring)

    def write(self, value: Any, timeout_s: Optional[float] = None) -> None:
        n = self.seq
        if n >= self.ring:
            await_ack(self.store, self.ack_base, n - self.ring, self.stop,
                      timeout_s)
        write_slot(self.store, self.base, n, value,
                   push_addr=self.push_addr)
        self.seq = n + 1

    def finish(self, timeout_s: Optional[float] = None) -> None:
        """End the stream cleanly: seal EOS (carrying the final count —
        needs no ring credit), retire every still-outstanding ring
        position by consuming the consumer's trailing acks, then wait
        for the consumer's EOS ack and delete the marker. The producer
        owns every object it created, so after finish() the channel
        holds ZERO store objects — the store-returns-to-baseline
        teardown contract. (Deleting the marker without the EOS ack
        would strand a consumer that had not observed it yet: it would
        park on a data slot that never comes.) Raises ChannelClosed if
        the pipeline stop flag seals while draining.

        Same-store channels with an EOS-aware consumer only (the
        data.streaming BlockReceiver): a plain RingReader never acks
        EOS_SEQ, and on a cross-store edge the marker lives in the
        remote store where the local delete could not reach it."""
        if self.push_addr is not None:
            raise NotImplementedError(
                "RingWriter.finish() is same-store only: the EOS "
                "marker and its ack live in the remote store on a "
                "push edge")
        seal_eos(self.store, self.base, self.seq, self.push_addr)
        self.drain_trailing(timeout_s)

    def drain_trailing(self, timeout_s: Optional[float] = None) -> None:
        """The retirement half of finish(): consume the trailing data
        acks and the EOS ack, then delete the marker. Split out so
        fan-out writers can seal EOS on EVERY ring before parking on
        any single consumer's acks (data/streaming BlockSender)."""
        for seq in range(max(0, self.seq - self.ring), self.seq):
            await_ack(self.store, self.ack_base, seq, self.stop, timeout_s)
        await_ack(self.store, self.ack_base, EOS_SEQ, self.stop, timeout_s)
        try:
            self.store.delete(eos_oid(self.base))
        except Exception:
            pass  # store closing; the marker dies with it


class RingReader:
    """Sequential consumer end."""

    def __init__(self, store, base: bytes, stop_oid: ObjectID, ring: int,
                 ack_push_addr: Optional[str] = None,
                 zero_copy: Optional[bool] = None,
                 ack_base: Optional[bytes] = None):
        self.store = store
        self.base = base
        self.ack_base = ack_base if ack_base is not None \
            else ack_base_for(base)
        self.stop = stop_oid
        self.ring = max(1, ring)
        self.ack_push_addr = ack_push_addr
        self.zero_copy = zero_copy
        self.seq = 0
        self._fl_open = True
        flight.chan_opened()
        stacks.note_producer(flight.lo48(self.ack_base))  # acks originate here

    def _fl_close(self) -> None:
        if self._fl_open:
            self._fl_open = False
            flight.chan_closed()

    def read(self, timeout_s: Optional[float] = None, on_idle=None) -> Any:
        val = read_slot(self.store, self.base, self.seq, self.stop,
                        timeout_s, self.zero_copy, self.ack_base,
                        self.ack_push_addr, on_idle)
        self.seq += 1
        return val

    def retire(self) -> None:
        """Call once the stream has ENDED (final sentinel consumed): the
        producer wrote its last message at seq-1 and consumed acks only
        up to seq-1-ring, so the trailing ring of ack objects this
        reader sealed would otherwise leak one store entry each, every
        stream. Local-store readers only (pushed acks live in the
        producer's store, which sweeps on its own exit)."""
        self._fl_close()
        if self.ack_push_addr is None:
            drain_stale_slots(self.store, [self.ack_base],
                              self.seq - self.ring - 1, self.seq)

    def close(self) -> None:
        """Consumer-side cancel: seal the stop flag so the producer's
        next ack wait (or stop probe) aborts the stream and sweeps its
        window; also sweep the slots/acks around OUR cursor in case the
        producer already exited normally and will never observe the
        stop."""
        self._fl_close()
        signal_stop(self.store, self.stop)
        drain_stale_slots(self.store, [self.base, self.ack_base],
                          self.seq - self.ring - 1, self.seq + self.ring)
