"""Compiled DAG execution (reference: dag/compiled_dag_node.py:808).

See package docstring for the channel protocol. Compilation:

1. topo-sort the graph; group ClassMethodNodes by owning actor;
2. allocate channel id rings per cross-process edge (deterministic ids:
   sha1(dag_id, producer, consumer) + slot byte);
3. install one `_dag_actor_loop` per actor via `handle._exec` — a
   long-running actor task stepping that actor's nodes in topo order
   (same-actor edges pass values in-process, no shm hop);
4. `execute()` writes the input channels and returns a CompiledDAGRef
   over the output channel; the ring bounds in-flight executions
   (auto-draining the oldest when full).
"""
from __future__ import annotations

import hashlib
import os
import time
from collections import deque
from typing import Any, Optional

from ..core.ids import ObjectID
from .nodes import ClassMethodNode, DAGNode, InputNode

_STOP = "__rtpu_dag_stop__"


def _slot_oid(base: bytes, slot: int) -> ObjectID:
    return ObjectID(base[:-1] + bytes([slot]))


def _read_channel(store, oid: ObjectID, stop_oid: ObjectID,
                  timeout_s: Optional[float] = None):
    """Blocking consume-once read: wait for the object, read, DELETE.
    Returns _STOP if the stop flag appears while waiting."""
    from ..core.object_store import GetTimeoutError
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        try:
            # zero_copy=False: a channel slot is deleted and RECREATED
            # under the same id each ring pass; a zero-copy pin would make
            # the delete lazy and the recreate collide or read stale data
            val = store.get(oid, timeout_ms=100, zero_copy=False)
            store.delete(oid)
            return val
        except GetTimeoutError:
            if store.contains(stop_oid):
                return _STOP
            if deadline is not None and time.monotonic() > deadline:
                raise


def _dag_actor_loop(instance, plan: list, stop_hex: str, max_inflight: int):
    """Installed in each participating actor (via __rtpu_exec__): steps
    this actor's nodes forever until the stop flag object appears."""
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    store = rt.store
    stop_oid = ObjectID(bytes.fromhex(stop_hex))
    seq = 0
    while True:
        slot = seq % max_inflight
        local: dict[int, Any] = {}
        for step in plan:
            if store.contains(stop_oid):
                return seq
            args = []
            for kind, val in step["args"]:
                if kind == "const":
                    args.append(val)
                elif kind == "local":
                    args.append(local[val])
                else:  # chan
                    v = _read_channel(store, _slot_oid(val, slot), stop_oid)
                    if v is _STOP:
                        return seq
                    args.append(v)
            out = getattr(instance, step["method"])(*args)
            local[step["idx"]] = out
            frame = None   # serialize once per value, reuse across targets
            for base, addr in step["out_chans"]:
                if addr is None:
                    store.put(_slot_oid(base, slot), out)
                else:
                    # cross-store edge: push into the consumer's store
                    from ..core.object_store import _FramedValue
                    from ..core.object_transfer import push_object
                    if frame is None:
                        frame = _FramedValue(out, False)
                    if not push_object(addr, _slot_oid(base, slot),
                                       frame=frame):
                        raise RuntimeError(
                            f"DAG channel push to {addr} rejected "
                            "(consumer store full?)")
        seq += 1


class CompiledDAGRef:
    """Result handle for one execute() (reference: CompiledDAGRef).
    get() consumes the output channel; repeated get() returns the cache."""

    def __init__(self, store, oid: ObjectID, stop_oid: ObjectID):
        self._store = store
        self._oid = oid
        self._stop = stop_oid
        self._value: Any = None
        self._consumed = False

    def get(self, timeout_s: Optional[float] = 60.0):
        if not self._consumed:
            v = _read_channel(self._store, self._oid, self._stop, timeout_s)
            if v is _STOP:
                raise RuntimeError("compiled DAG was torn down")
            self._value = v
            self._consumed = True
        return self._value


class CompiledDAG:
    def __init__(self, output_node: DAGNode, max_inflight: int = 2):
        import ray_tpu
        from ..core import runtime as rt_mod
        self._rt = rt_mod.get_runtime_if_exists()
        if self._rt is None:
            raise RuntimeError("ray_tpu.init() first")
        self.store = self._rt.store
        self.max_inflight = max_inflight
        self.dag_id = os.urandom(8)
        self._seq = 0
        self._outstanding: deque[CompiledDAGRef] = deque()
        stop_digest = hashlib.sha1(self.dag_id + b"stop").digest()
        self.stop_oid = ObjectID(stop_digest[:ObjectID.SIZE])
        self._torn_down = False

        # ---- topo order (args before node) --------------------------- #
        order: list[ClassMethodNode] = []
        seen: dict[int, int] = {}

        def visit(n):
            if isinstance(n, InputNode):
                return
            if not isinstance(n, ClassMethodNode):
                return
            if id(n) in seen:
                return
            for a in n.args:
                visit(a)
            seen[id(n)] = len(order)
            order.append(n)

        visit(output_node)
        if not order:
            raise ValueError("DAG has no actor-method nodes")
        self.output_node = order[-1]
        if output_node is not self.output_node:
            raise ValueError("compile from the DAG's final node")

        # ---- channels -------------------------------------------------- #
        def chan_base(tag: str) -> bytes:
            return hashlib.sha1(self.dag_id + tag.encode()).digest()[
                :ObjectID.SIZE]

        self.input_chans: list[bytes] = []
        self.output_chan = chan_base("out")
        # per-actor plans
        plans: dict[bytes, list] = {}
        actors: dict[bytes, Any] = {}
        node_actor = {}
        for idx, n in enumerate(order):
            aid = n.actor._actor_id.binary()
            actors[aid] = n.actor
            node_actor[id(n)] = aid
            step = {"idx": idx, "method": n.method_name, "args": [],
                    "out_chans": []}
            for a in n.args:
                if isinstance(a, InputNode):
                    base = chan_base(f"in->{idx}")
                    # (channel, consuming actor) — resolved to a push
                    # target after placement is known
                    self.input_chans.append((base, aid))
                    step["args"].append(("chan", base))
                elif isinstance(a, ClassMethodNode):
                    src_idx = seen[id(a)]
                    if node_actor[id(a)] == aid:
                        step["args"].append(("local", src_idx))
                    else:
                        base = chan_base(f"{src_idx}->{idx}")
                        # producer writes this channel toward consumer aid
                        for s in plans[node_actor[id(a)]]:
                            if s["idx"] == src_idx:
                                s["out_chans"].append((base, aid))
                        step["args"].append(("chan", base))
                else:
                    step["args"].append(("const", a))
            plans.setdefault(aid, []).append(step)
        # final node also writes the driver-facing output channel
        # (consumer None = the driver/head store)
        out_aid = node_actor[id(self.output_node)]
        for s in plans[out_aid]:
            if s["idx"] == seen[id(self.output_node)]:
                s["out_chans"].append((self.output_chan, None))

        # ---- cross-store channel routing ------------------------------ #
        # A consumer polls its node-LOCAL store, so the producer of every
        # cross-store edge PUSHES the value into the consumer's store via
        # the transfer service (reference: aDAG remote channels over RPC,
        # local ones over shm — compiled_dag_node.py:808). Same-store
        # edges stay plain store writes. Resolve placement by pinging each
        # actor (forces scheduling), then mapping it to its node's data
        # address (None = shares the driver's store).
        from ..core import runtime as rt_mod
        from ..core.ids import ActorID
        actor_addr: dict[bytes, Optional[str]] = {a: None for a in plans}
        head_addr: Optional[str] = None
        if isinstance(self._rt, rt_mod.Runtime):
            ray_tpu.get([actors[aid]._exec(lambda inst: None)
                         for aid in plans], timeout=120)
            with self._rt.lock:
                head_addr = self._rt.head_node.data_addr
                for aid in plans:
                    a = self._rt.actors.get(ActorID(aid))
                    w = (self._rt.workers.get(a.wid)
                         if a is not None and a.wid else None)
                    n = (self._rt.nodes.get(w.node_id)
                         if w is not None else None)
                    if n is not None and n.own_store:
                        actor_addr[aid] = n.data_addr

        def route(producer_addr: Optional[str],
                  consumer_addr: Optional[str]) -> Optional[str]:
            """Where the producer must place the value; None = its own
            local store."""
            target = consumer_addr if consumer_addr is not None else \
                head_addr
            own = producer_addr if producer_addr is not None else head_addr
            return None if target == own else target

        def consumer_addr(c) -> Optional[str]:
            return actor_addr[c] if c is not None else None

        for aid, plan in plans.items():
            for step in plan:
                step["out_chans"] = [
                    (base, route(actor_addr[aid], consumer_addr(c)))
                    for base, c in step["out_chans"]]
        # driver-side channel targets (driver writes/reads the head store)
        self.input_chans = [
            (base, route(None, consumer_addr(c)))
            for base, c in self.input_chans]
        self._push_addrs = sorted({addr for addr in actor_addr.values()
                                   if addr is not None})

        # ---- install loops -------------------------------------------- #
        self._loop_refs = []
        for aid, plan in plans.items():
            self._loop_refs.append(actors[aid]._exec(
                _dag_actor_loop, plan, self.stop_oid.hex(), max_inflight))

    # ------------------------------------------------------------------- #

    def execute(self, value: Any) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG is torn down")
        if len(self._outstanding) >= self.max_inflight:
            # ring full: auto-drain the oldest so slots recycle
            self._outstanding.popleft().get()
        slot = self._seq % self.max_inflight
        self._seq += 1
        from ..core.object_store import _FramedValue
        from ..core.object_transfer import push_object
        frame = None   # serialize once per execute, reuse across targets
        for base, addr in self.input_chans:
            if addr is None:
                self.store.put(_slot_oid(base, slot), value)
            else:
                if frame is None:
                    frame = _FramedValue(value, False)
                if not push_object(addr, _slot_oid(base, slot),
                                   frame=frame):
                    raise RuntimeError(
                        f"DAG input push to {addr} rejected "
                        "(consumer store full?)")
        ref = CompiledDAGRef(self.store, _slot_oid(self.output_chan, slot),
                             self.stop_oid)
        self._outstanding.append(ref)
        return ref

    def teardown(self, timeout_s: float = 30.0):
        if self._torn_down:
            return
        self._torn_down = True
        self.store.put(self.stop_oid, True)
        # own-store actors poll their LOCAL stores for the flag
        from ..core.object_transfer import push_object
        for addr in self._push_addrs:
            try:
                push_object(addr, self.stop_oid, True)
            except OSError:
                pass  # node gone: its loop died with it
        import ray_tpu
        try:
            ray_tpu.get(self._loop_refs, timeout=timeout_s)
        except Exception:
            pass  # loops may have errored; teardown continues
        try:
            self.store.delete(self.stop_oid)
        except Exception:
            pass  # store closing; the oid dies with it
