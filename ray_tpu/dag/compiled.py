"""Compiled DAG execution (reference: dag/compiled_dag_node.py:808).

See package docstring for the channel protocol. Compilation:

1. topo-sort the graph; group ClassMethodNodes by owning actor;
2. allocate sealed ring channels per cross-process edge (deterministic id
   bases: sha1(dag_id, producer, consumer); message ``seq`` maps to
   ``base[:12] + uint32(seq)`` — see dag/channel.py for the seal/ack
   protocol that retires ring positions without delete-and-recreate);
3. install one ``_dag_actor_loop_sealed`` per actor via ``handle._exec``
   — a long-running actor task stepping that actor's nodes in topo order
   (same-actor edges pass values in-process, no shm hop);
4. ``execute()`` writes the input channels and returns a CompiledDAGRef
   over the output channel; the ring bounds in-flight executions
   (auto-draining the oldest when full).

``cfg.dag_sealed_channels = False`` restores the legacy polling transport
(consume-once slots, delete-and-recreate, 100ms poll slices, copies
forced on every read) — results must be bit-identical either way.
"""
from __future__ import annotations

import hashlib
import os
import time
from collections import deque
from typing import Any, Optional

from ..core.ids import ObjectID
from ..core import flight
from . import channel as ch
from .nodes import ClassMethodNode, DAGNode, InputNode

_STOP = "__rtpu_dag_stop__"


def _slot_oid(base: bytes, slot: int) -> ObjectID:
    return ObjectID(base[:-1] + bytes([slot]))


def _read_channel(store, oid: ObjectID, stop_oid: ObjectID,
                  timeout_s: Optional[float] = None):
    """LEGACY transport: blocking consume-once read — wait for the
    object, read, DELETE. Returns _STOP if the stop flag appears while
    waiting. Kept behind cfg.dag_sealed_channels=False as the
    bit-identical fallback; the sealed-channel path replaces the poll
    slices below with one futex wait over {data, stop}."""
    from ..core.object_store import GetTimeoutError
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    while True:
        try:
            # zero_copy=False: a channel slot is deleted and RECREATED
            # under the same id each ring pass; a zero-copy pin would make
            # the delete lazy and the recreate collide or read stale data
            val = store.get(oid, timeout_ms=100,  # graftlint: disable=GL009
                            zero_copy=False)
            store.delete(oid)
            return val
        except GetTimeoutError:
            if store.contains(stop_oid):
                return _STOP
            if deadline is not None and time.monotonic() > deadline:
                raise


def _dag_actor_loop(instance, plan: list, stop_hex: str, max_inflight: int):
    """LEGACY transport loop (cfg.dag_sealed_channels=False): steps this
    actor's nodes forever until the stop flag object appears."""
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    store = rt.store
    stop_oid = ObjectID(bytes.fromhex(stop_hex))
    seq = 0
    while True:
        slot = seq % max_inflight
        local: dict[int, Any] = {}
        for step in plan:
            if store.contains(stop_oid):
                return seq
            args = []
            for kind, val in step["args"]:
                if kind == "const":
                    args.append(val)
                elif kind == "local":
                    args.append(local[val])
                else:  # chan: the edge's data base
                    v = _read_channel(store, _slot_oid(val, slot),
                                      stop_oid)
                    if v is _STOP:
                        return seq
                    args.append(v)
            out = getattr(instance, step["method"])(*args)
            local[step["idx"]] = out
            frame = None   # serialize once per value, reuse across targets
            for base, addr in step["out_chans"]:
                if addr is None:
                    store.put(_slot_oid(base, slot), out)
                else:
                    # cross-store edge: push into the consumer's store
                    from ..core.object_store import _FramedValue
                    from ..core.object_transfer import push_object
                    if frame is None:
                        frame = _FramedValue(out, False)
                    if not push_object(addr, _slot_oid(base, slot),
                                       frame=frame):
                        raise RuntimeError(
                            f"DAG channel push to {addr} rejected "
                            "(consumer store full?)")
        seq += 1


def _dag_actor_loop_sealed(instance, plan: list, stop_hex: str, ring: int):
    """Sealed-channel transport loop: an in-edge read is one native get
    when the slot is already sealed (the pipelined steady state), else
    one futex wait over {data, stop} (dag/channel.py read_slot). No ack
    traffic: the driver paces the whole pipeline (execute() drains output
    seq-ring before feeding seq), which bounds every edge to the ring.
    Values cross the DAG zero-copy when cfg.zero_copy_get allows."""
    from . import channel as _ch
    from ..core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    store = rt.store
    stop_oid = ObjectID(bytes.fromhex(stop_hex))
    seq = 0
    from ..core import flight as _fl
    try:
        while True:
            local: dict[int, Any] = {}
            for step in plan:
                args = []
                for kind, val in step["args"]:
                    if kind == "const":
                        args.append(val)
                    elif kind == "local":
                        args.append(local[val])
                    else:  # chan: the edge's data base
                        args.append(_ch.read_slot(store, val, seq,
                                                  stop_oid))
                _fl.evt(_fl.DAG_STEP_BEGIN, step["idx"], seq)
                out = getattr(instance, step["method"])(*args)
                _fl.evt(_fl.DAG_STEP_END, step["idx"], seq)
                local[step["idx"]] = out
                outs = step["out_chans"]
                if not outs:
                    continue
                frame = None   # serialize once, fan out to every target
                if len(outs) > 1 or any(a is not None for _, a in outs):
                    from ..core.object_store import _FramedValue
                    frame = _FramedValue(out, False)
                for base, addr in outs:
                    _ch.write_slot(store, base, seq, out, frame=frame,
                                   push_addr=addr)
            seq += 1
    except _ch.ChannelClosed:
        return seq  # teardown: stop flag sealed while waiting


class CompiledDAGRef:
    """Result handle for one execute() (reference: CompiledDAGRef).
    get() consumes the output channel; repeated get() returns the cache.
    If a participating actor dies mid-loop, get() raises instead of
    hanging (the liveness probe runs between wait slices)."""

    def __init__(self, store, oid: ObjectID, stop_oid: ObjectID,
                 dag: Optional["CompiledDAG"] = None,
                 seq: Optional[int] = None):
        self._store = store
        self._oid = oid          # legacy transport slot (None when sealed)
        self._stop = stop_oid
        self._dag = dag
        self._seq = seq          # sealed transport message seq
        self._value: Any = None
        self._consumed = False

    def get(self, timeout_s: Optional[float] = 60.0):
        if self._consumed:
            return self._value
        if self._seq is None:
            v = _read_channel(self._store, self._oid, self._stop, timeout_s)
            if v is _STOP:
                raise RuntimeError("compiled DAG was torn down")
        else:
            dag = self._dag
            try:
                v = ch.read_slot(self._store, dag.output_chan, self._seq,
                                 self._stop, timeout_s,
                                 on_idle=dag._probe_loops)
            except ch.ChannelClosed:
                raise RuntimeError("compiled DAG was torn down") from None
        self._value = v
        self._consumed = True
        return self._value


class CompiledDAG:
    def __init__(self, output_node: DAGNode, max_inflight: int = 2):
        import ray_tpu
        from ..core import runtime as rt_mod
        from ..core.config import cfg
        self._rt = rt_mod.get_runtime_if_exists()
        if self._rt is None:
            raise RuntimeError("ray_tpu.init() first")
        self.store = self._rt.store
        self.max_inflight = max_inflight
        self.sealed = bool(cfg.dag_sealed_channels)
        self.dag_id = os.urandom(8)
        self._seq = 0
        self._outstanding: deque[CompiledDAGRef] = deque()
        stop_digest = hashlib.sha1(self.dag_id + b"stop").digest()
        self.stop_oid = ObjectID(stop_digest[:ObjectID.SIZE])
        self._torn_down = False

        # ---- topo order (args before node) --------------------------- #
        order: list[ClassMethodNode] = []
        seen: dict[int, int] = {}

        def visit(n):
            if isinstance(n, InputNode):
                return
            if not isinstance(n, ClassMethodNode):
                return
            if id(n) in seen:
                return
            for a in n.args:
                visit(a)
            seen[id(n)] = len(order)
            order.append(n)

        visit(output_node)
        if not order:
            raise ValueError("DAG has no actor-method nodes")
        self.output_node = order[-1]
        if output_node is not self.output_node:
            raise ValueError("compile from the DAG's final node")

        # ---- channels -------------------------------------------------- #
        def chan_base(tag: str) -> bytes:
            return hashlib.sha1(self.dag_id + tag.encode()).digest()[
                :ObjectID.SIZE]

        self.input_chans: list = []
        self.output_chan = chan_base("out")
        # per-actor plans
        plans: dict[bytes, list] = {}
        actors: dict[bytes, Any] = {}
        node_actor = {}
        for idx, n in enumerate(order):
            aid = n.actor._actor_id.binary()
            actors[aid] = n.actor
            node_actor[id(n)] = aid
            step = {"idx": idx, "method": n.method_name, "args": [],
                    "out_chans": []}
            for a in n.args:
                if isinstance(a, InputNode):
                    base = chan_base(f"in->{idx}")
                    # (channel, consuming actor) — resolved to a push
                    # target after placement is known
                    self.input_chans.append((base, aid))
                    step["args"].append(("chan", base))
                elif isinstance(a, ClassMethodNode):
                    src_idx = seen[id(a)]
                    if node_actor[id(a)] == aid:
                        step["args"].append(("local", src_idx))
                    else:
                        base = chan_base(f"{src_idx}->{idx}")
                        # producer writes this channel toward consumer aid
                        for s in plans[node_actor[id(a)]]:
                            if s["idx"] == src_idx:
                                s["out_chans"].append((base, aid))
                        step["args"].append(("chan", base))
                else:
                    step["args"].append(("const", a))
            plans.setdefault(aid, []).append(step)
        # final node also writes the driver-facing output channel
        # (consumer None = the driver/head store)
        out_aid = node_actor[id(self.output_node)]
        for s in plans[out_aid]:
            if s["idx"] == seen[id(self.output_node)]:
                s["out_chans"].append((self.output_chan, None))

        # ---- cross-store channel routing ------------------------------ #
        # A consumer waits on its node-LOCAL store, so the producer of
        # every cross-store edge PUSHES the value into the consumer's
        # store via the transfer service (reference: aDAG remote channels
        # over RPC, local ones over shm — compiled_dag_node.py:808).
        # Same-store edges stay plain store seals. Resolve placement by
        # pinging each actor (forces scheduling), then mapping it to its
        # node's data address (None = shares the driver's store).
        from ..core.ids import ActorID
        actor_addr: dict[bytes, Optional[str]] = {a: None for a in plans}
        head_addr: Optional[str] = None
        if isinstance(self._rt, rt_mod.Runtime):
            ray_tpu.get([actors[aid]._exec(lambda inst: None)
                         for aid in plans], timeout=120)
            with self._rt.lock:
                head_addr = self._rt.head_node.data_addr
                for aid in plans:
                    a = self._rt.actors.get(ActorID(aid))
                    w = (self._rt.workers.get(a.wid)
                         if a is not None and a.wid else None)
                    n = (self._rt.nodes.get(w.node_id)
                         if w is not None else None)
                    if n is not None and n.own_store:
                        actor_addr[aid] = n.data_addr

        def route(src_addr: Optional[str],
                  dst_addr: Optional[str]) -> Optional[str]:
            """Where a value produced on `src` must be placed to be
            visible to `dst`; None = the producer's own local store."""
            target = dst_addr if dst_addr is not None else head_addr
            own = src_addr if src_addr is not None else head_addr
            return None if target == own else target

        def addr_of(c) -> Optional[str]:
            return actor_addr[c] if c is not None else None

        for aid, plan in plans.items():
            for step in plan:
                # data flows producer -> consumer store
                step["out_chans"] = [
                    (base, route(actor_addr[aid], addr_of(c)))
                    for base, c in step["out_chans"]]
        # driver-side channels (driver writes inputs / reads the output
        # against the head store)
        self.input_chans = [
            (base, route(None, addr_of(c)))
            for base, c in self.input_chans]
        self._push_addrs = sorted({addr for addr in actor_addr.values()
                                   if addr is not None})
        # channel-endpoint accounting for state.summary(): every edge
        # (inputs + cross-actor + the driver-facing output) is one live
        # channel until teardown
        self._n_chans = len(self.input_chans) + sum(
            len(step["out_chans"]) for plan in plans.values()
            for step in plan)
        flight.chan_opened(self._n_chans)

        # ---- install loops -------------------------------------------- #
        self._loop_refs = []
        for aid, plan in plans.items():
            if self.sealed:
                self._loop_refs.append(actors[aid]._exec(
                    _dag_actor_loop_sealed, plan, self.stop_oid.hex(),
                    max_inflight))
            else:
                self._loop_refs.append(actors[aid]._exec(
                    _dag_actor_loop, plan, self.stop_oid.hex(),
                    max_inflight))

    # ------------------------------------------------------------------- #

    def _probe_loops(self):
        """Between wait slices: raise if any actor loop exited while the
        DAG is live (actor death / a step raising) — a CompiledDAGRef
        must never hang on a pipeline that can no longer produce."""
        if self._torn_down:
            return
        import ray_tpu
        ready, _ = ray_tpu.wait(self._loop_refs,
                                num_returns=1, timeout=0)
        if ready:
            val = ray_tpu.get(ready[0])   # raises ActorDiedError & co.
            raise RuntimeError(
                f"compiled DAG actor loop exited mid-pipeline "
                f"(returned {val!r}); tear the DAG down")

    def execute(self, value: Any) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("DAG is torn down")
        if len(self._outstanding) >= self.max_inflight:
            # ring full: auto-drain the oldest so slots recycle
            self._outstanding.popleft().get()
        seq = self._seq
        self._seq += 1
        flight.evt(flight.DAG_EXEC, seq)
        if self.sealed:
            ref = self._execute_sealed(seq, value)
        else:
            ref = self._execute_poll(seq, value)
        self._outstanding.append(ref)
        return ref

    def _execute_sealed(self, seq: int, value: Any) -> CompiledDAGRef:
        frame = None   # serialize once per execute, reuse across targets
        if len(self.input_chans) > 1 or any(
                a is not None for _, a in self.input_chans):
            from ..core.object_store import _FramedValue
            frame = _FramedValue(value, False)
        # no ack wait: the auto-drain in execute() already proved every
        # stage consumed seq - max_inflight (all nodes are ancestors of
        # the drained output node), so this ring position is retired
        for base, addr in self.input_chans:
            ch.write_slot(self.store, base, seq, value, frame=frame,
                          push_addr=addr)
        return CompiledDAGRef(self.store, None, self.stop_oid,
                              dag=self, seq=seq)

    def _execute_poll(self, seq: int, value: Any) -> CompiledDAGRef:
        slot = seq % self.max_inflight
        from ..core.object_store import _FramedValue
        from ..core.object_transfer import push_object
        frame = None
        for base, addr in self.input_chans:
            if addr is None:
                self.store.put(_slot_oid(base, slot), value)
            else:
                if frame is None:
                    frame = _FramedValue(value, False)
                if not push_object(addr, _slot_oid(base, slot),
                                   frame=frame):
                    raise RuntimeError(
                        f"DAG input push to {addr} rejected "
                        "(consumer store full?)")
        return CompiledDAGRef(self.store, _slot_oid(self.output_chan, slot),
                              self.stop_oid)

    def teardown(self, timeout_s: float = 30.0):
        if self._torn_down:
            return
        self._torn_down = True
        flight.chan_closed(self._n_chans)
        ch.signal_stop(self.store, self.stop_oid)
        # own-store actors wait on their LOCAL stores for the flag
        from ..core.object_transfer import push_object
        for addr in self._push_addrs:
            try:
                push_object(addr, self.stop_oid, True)
            except OSError:
                pass  # node gone: its loop died with it
        import ray_tpu
        try:
            ray_tpu.get(self._loop_refs, timeout=timeout_s)
        except Exception:
            pass  # loops may have errored; teardown continues
        if self.sealed:
            # sweep unconsumed slots (inputs never read, outputs never
            # got) so a torn-down DAG leaves no channel objects behind in
            # the store; the driver pacing bounds live slots to the
            # trailing ring window
            bases = [base for base, _ in self.input_chans]
            bases.append(self.output_chan)
            ch.drain_stale_slots(self.store, bases,
                                 self._seq - 2 * self.max_inflight,
                                 self._seq)
        try:
            self.store.delete(self.stop_oid)
        except Exception:
            pass  # store closing; the oid dies with it
