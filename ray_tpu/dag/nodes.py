"""DAG node types (reference: python/ray/dag/dag_node.py,
class_node.py ClassMethodNode, input_node.py InputNode).

``actor.method.bind(*args)`` builds a ClassMethodNode; args may be the
InputNode, other nodes, or plain constants. ``node.experimental_compile()``
compiles the graph rooted at that node.
"""
from __future__ import annotations

from typing import Any


class DAGNode:
    def experimental_compile(self, max_inflight: int = 2):
        from .compiled import CompiledDAG
        return CompiledDAG(self, max_inflight=max_inflight)


class InputNode(DAGNode):
    """The driver-supplied input (one per DAG; context-manager form
    mirrors the reference API)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __repr__(self):
        return "InputNode()"


class ClassMethodNode(DAGNode):
    def __init__(self, actor, method_name: str, args: tuple):
        self.actor = actor
        self.method_name = method_name
        self.args = args

    def __repr__(self):
        return (f"ClassMethodNode({self.actor._class_name}."
                f"{self.method_name})")


class _BoundMethodBinder:
    """Gives ActorMethod a .bind() without importing dag into core."""

    @staticmethod
    def bind(actor_method, *args) -> ClassMethodNode:
        return ClassMethodNode(actor_method._handle, actor_method._name,
                               args)


def _install_bind():
    """Attach .bind to core ActorMethod (kept out of core/actor.py so the
    core has no dag dependency)."""
    from ..core.actor import ActorMethod

    def bind(self, *args: Any) -> ClassMethodNode:
        return ClassMethodNode(self._handle, self._name, args)

    if not hasattr(ActorMethod, "bind"):
        ActorMethod.bind = bind


_install_bind()
