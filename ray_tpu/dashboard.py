"""ray_tpu.dashboard — cluster overview over HTTP.

Reference parity: the dashboard head + its API modules
(dashboard/head.py:48, dashboard/modules/{node,job,actor,state,metrics})
and the React frontend, reduced TPU-first: the head runtime IS the data
source, so the dashboard is an in-process aiohttp thread serving the
state API as JSON plus one self-contained HTML page — no separate
process tree, no node agents, no build step.

    import ray_tpu
    from ray_tpu import dashboard
    ray_tpu.init()
    port = dashboard.start_dashboard(port=8265)
    # GET /            -> HTML overview (auto-refreshing)
    # GET /api/summary | /api/nodes | /api/actors | /api/tasks
    #     /api/objects | /api/workers | /api/jobs | /api/config
    # GET /metrics     -> Prometheus text (same as state.start_metrics_server)
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

_server = {"runner": None, "loop": None, "port": None, "thread": None}

_PAGE = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.4em; }
 table { border-collapse: collapse; width: 100%; background: #fff; }
 th, td { border: 1px solid #ddd; padding: 4px 8px; font-size: 0.85em;
          text-align: left; }
 th { background: #f0f0f0; }
 .pill { padding: 1px 8px; border-radius: 8px; font-size: 0.8em; }
 .ALIVE, .FINISHED, .SUCCEEDED, .alive { background: #d4f7d4; }
 .DEAD, .FAILED, .ERROR, .dead { background: #f7d4d4; }
 .RUNNING, .PENDING, .busy { background: #fdf3cf; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Workers</h2><table id="workers"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<script>
function row(tr, cells, tag) {
  const r = document.createElement('tr');
  for (const c of cells) {
    const td = document.createElement(tag || 'td');
    if (typeof c === 'object' && c && c.pill) {
      const s = document.createElement('span');
      s.className = 'pill ' + c.pill; s.textContent = c.pill;
      td.appendChild(s);
    } else td.textContent = c;
    r.appendChild(td);
  }
  tr.appendChild(r);
}
function fill(id, header, rows) {
  const t = document.getElementById(id);
  t.innerHTML = '';
  row(t, header, 'th');
  for (const r of rows) row(t, r);
}
async function refresh() {
  const s = await (await fetch('api/summary')).json();
  document.getElementById('summary').textContent =
    `nodes ${s.nodes_alive} | actors ${s.actors} | pending tasks ` +
    `${s.pending_tasks} | finished ${s.tasks.tasks_finished} | failed ` +
    `${s.tasks.tasks_failed} | store ` +
    `${(s.object_store.bytes_in_use/1048576).toFixed(1)}MB/` +
    `${(s.object_store.capacity/1048576).toFixed(0)}MB`;
  const nodes = await (await fetch('api/nodes')).json();
  fill('nodes', ['node', 'state', 'resources', 'available'],
       nodes.map(n => [n.NodeName, {pill: n.Alive ? 'ALIVE' : 'DEAD'},
                       JSON.stringify(n.Resources),
                       JSON.stringify(n.Available)]));
  const workers = await (await fetch('api/workers')).json();
  fill('workers', ['id', 'state', 'pid', 'task/actor'],
       workers.map(w => [w.worker_id, {pill: w.state}, w.pid,
                         w.current_task || w.actor_id]));
  const actors = await (await fetch('api/actors')).json();
  fill('actors', ['id', 'class', 'state', 'name', 'pending', 'running'],
       actors.map(a => [a.actor_id.slice(0, 12), a.class_name,
                        {pill: a.state}, a.name, a.pending_calls,
                        a.running_calls]));
  const jobs = await (await fetch('api/jobs')).json();
  fill('jobs', ['id', 'status', 'entrypoint'],
       jobs.map(j => [j.job_id, {pill: j.status}, j.entrypoint]));
  const tasks = await (await fetch('api/tasks?limit=25')).json();
  fill('tasks', ['name', 'state', 'worker'],
       tasks.map(t => [t.name, {pill: t.state}, t.worker || '']));
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def start_dashboard(port: int = 0, host: str = "127.0.0.1") -> int:
    """Start the dashboard on the head; returns the bound port."""
    from aiohttp import web

    from . import state as state_api
    from .core import runtime as rt_mod
    from .core.config import cfg

    rt = rt_mod.get_runtime_if_exists()
    if rt is None or not isinstance(rt, rt_mod.Runtime):
        raise RuntimeError("start_dashboard() runs on the head driver")
    if _server["runner"] is not None:
        return _server["port"]

    async def page(request):
        return web.Response(text=_PAGE, content_type="text/html")

    async def api(request):
        kind = request.match_info["kind"]
        limit = int(request.query.get("limit", 1000))
        try:
            if kind == "summary":
                out = state_api.summary()
            elif kind == "config":
                out = cfg.dump()
            elif kind == "jobs":
                out = state_api.list_jobs()
            elif kind == "serve":
                from . import serve as serve_api
                # remote round-trip: keep it off the dashboard event loop
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, serve_api.status)
            elif kind in ("tasks", "actors", "objects", "nodes", "workers"):
                fn = getattr(state_api, f"list_{kind}")
                out = fn(limit) if kind in ("tasks", "actors",
                                            "objects") else fn()
            else:
                return web.json_response(
                    {"error": f"unknown kind {kind}"}, status=404)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(out, dumps=lambda o: json.dumps(
            o, default=str))

    async def metrics(request):
        return web.Response(text=state_api._prometheus_text(),
                            content_type="text/plain")

    ready = threading.Event()

    def run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_get("/", page)
        app.router.add_get("/api/{kind}", api)
        app.router.add_get("/metrics", metrics)
        runner = web.AppRunner(app)

        async def boot():
            await runner.setup()
            site = web.TCPSite(runner, host, port)
            await site.start()
            _server["port"] = site._server.sockets[0].getsockname()[1]
            _server["runner"] = runner
        loop.run_until_complete(boot())
        _server["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True, name="rtpu-dashboard")
    t.start()
    _server["thread"] = t
    if not ready.wait(10):
        raise RuntimeError("dashboard failed to start")
    return _server["port"]


def stop_dashboard() -> None:
    loop = _server.get("loop")
    if loop is not None:
        loop.call_soon_threadsafe(loop.stop)
    _server.update({"runner": None, "loop": None, "port": None,
                    "thread": None})
