"""ray_tpu.dashboard — cluster overview over HTTP.

Reference parity: the dashboard head + its API modules
(dashboard/head.py:48, dashboard/modules/{node,job,actor,state,metrics,
log,serve}) and the React frontend, reduced TPU-first: the head runtime
IS the data source, so the dashboard is an in-process aiohttp thread
serving the state API as JSON plus one self-contained HTML page — no
separate process tree, no node agents, no build step.

    import ray_tpu
    from ray_tpu import dashboard
    ray_tpu.init()
    port = dashboard.start_dashboard(port=8265)
    # GET /            -> HTML overview (auto-refreshing; tasks/actors
    #                     click through to detail, logs are browsable)
    # GET /api/summary | /api/nodes | /api/actors | /api/tasks
    #     /api/objects | /api/workers | /api/jobs | /api/config
    #     /api/serve   | /api/serve_metrics | /api/logs
    #     /api/stacks  | /api/hangs   (stall doctor: live stacks + hang
    #                                  diagnosis, see core/stacks.py)
    # GET /api/metrics_history?name=N[&window=S][&tags=JSON]
    #     [&quantiles=0.5,0.95]      -> TSDB range query (ray_tpu/obs)
    # GET /api/slo                   -> SLO burn-rate report
    # GET /api/cache                 -> prefix-cache heat map (cache
    #                                   heat plane: hot chains, pools,
    #                                   tenant warmth)
    # GET /api/task/{id}   -> full task record + its timeline events
    # GET /api/actor/{id}  -> full actor record + per-call queues
    # GET /api/log?file=worker-X.log&tail=N -> log tail (session dir only)
    # GET /metrics     -> Prometheus text (same as state.start_metrics_server)
"""
from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

_server = {"runner": None, "loop": None, "port": None, "thread": None}

_PAGE = """<!DOCTYPE html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2em; background: #fafafa; }
 h1 { font-size: 1.3em; } h2 { font-size: 1.05em; margin-top: 1.4em; }
 table { border-collapse: collapse; width: 100%; background: #fff; }
 th, td { border: 1px solid #ddd; padding: 4px 8px; font-size: 0.85em;
          text-align: left; }
 th { background: #f0f0f0; }
 .pill { padding: 1px 8px; border-radius: 8px; font-size: 0.8em; }
 .ALIVE, .FINISHED, .SUCCEEDED, .alive { background: #d4f7d4; }
 .DEAD, .FAILED, .ERROR, .dead { background: #f7d4d4; }
 .RUNNING, .PENDING, .busy { background: #fdf3cf; }
 .ok { background: #d4f7d4; } .warn { background: #fdf3cf; }
 .page { background: #f7d4d4; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Workers</h2><table id="workers"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Serve</h2><table id="serve"></table>
<h2>SLOs</h2><table id="slo"></table>
<h2>Autoscaler</h2><table id="autoscaler"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<h2>Memory <small>(<a href="api/timeline" download="timeline.json">
download chrome trace</a>)</small></h2>
<div id="memsum"></div><table id="memory"></table>
<h2>Detail</h2><pre id="detail"
 style="background:#fff;border:1px solid #ddd;padding:8px;min-height:2em;
        font-size:0.8em;white-space:pre-wrap">click a task or actor id</pre>
<h2>Logs</h2><table id="logs"></table>
<pre id="logview"
 style="background:#111;color:#ddd;padding:8px;max-height:24em;
        overflow:auto;font-size:0.78em;display:none"></pre>
<script>
function row(tr, cells, tag) {
  const r = document.createElement('tr');
  for (const c of cells) {
    const td = document.createElement(tag || 'td');
    if (typeof c === 'object' && c && c.pill) {
      const s = document.createElement('span');
      s.className = 'pill ' + c.pill; s.textContent = c.pill;
      td.appendChild(s);
    } else if (typeof c === 'object' && c && c.click) {
      const a = document.createElement('a');
      a.textContent = c.text; a.href = '#';
      a.onclick = (e) => { e.preventDefault(); c.click(); };
      td.appendChild(a);
    } else td.textContent = c;
    r.appendChild(td);
  }
  tr.appendChild(r);
}
function fill(id, header, rows) {
  const t = document.getElementById(id);
  t.innerHTML = '';
  row(t, header, 'th');
  for (const r of rows) row(t, r);
}
async function detail(url) {
  const d = await (await fetch(url)).json();
  document.getElementById('detail').textContent =
    JSON.stringify(d, null, 2);
}
async function showLog(name) {
  const v = document.getElementById('logview');
  v.style.display = 'block';
  const d = await (await fetch(
    'api/log?tail=200&file=' + encodeURIComponent(name))).json();
  v.textContent = `== ${name} ==\\n` + (d.lines || []).join('\\n');
}
async function refresh() {
  const s = await (await fetch('api/summary')).json();
  document.getElementById('summary').textContent =
    `nodes ${s.nodes_alive} | actors ${s.actors} | pending tasks ` +
    `${s.pending_tasks} | finished ${s.tasks.tasks_finished} | failed ` +
    `${s.tasks.tasks_failed} | store ` +
    `${(s.object_store.bytes_in_use/1048576).toFixed(1)}MB/` +
    `${(s.object_store.capacity/1048576).toFixed(0)}MB`;
  const nodes = await (await fetch('api/nodes')).json();
  fill('nodes', ['node', 'state', 'resources', 'available'],
       nodes.map(n => [n.NodeName, {pill: n.Alive ? 'ALIVE' : 'DEAD'},
                       JSON.stringify(n.Resources),
                       JSON.stringify(n.Available)]));
  const workers = await (await fetch('api/workers')).json();
  fill('workers', ['id', 'state', 'pid', 'task/actor'],
       workers.map(w => [w.worker_id, {pill: w.state}, w.pid,
                         w.current_task || w.actor_id]));
  const actors = await (await fetch('api/actors')).json();
  fill('actors', ['id', 'class', 'state', 'name', 'pending', 'running'],
       actors.map(a => [{text: a.actor_id.slice(0, 12),
                         click: () => detail('api/actor/' + a.actor_id)},
                        a.class_name, {pill: a.state}, a.name,
                        a.pending_calls, a.running_calls]));
  const jobs = await (await fetch('api/jobs')).json();
  fill('jobs', ['id', 'status', 'entrypoint'],
       jobs.map(j => [j.job_id, {pill: j.status}, j.entrypoint]));
  try {
    const sv = await (await fetch('api/serve')).json();
    const rows = [];
    for (const [app, dep] of Object.entries(sv.applications || {}))
      for (const [name, d] of Object.entries(dep.deployments || {}))
        rows.push([app, name, {pill: d.status || 'RUNNING'},
                   `${d.running_replicas ?? d.num_replicas_running ?? d.replicas ?? ''}`]);
    for (const p of (sv.proxies || []))
      rows.push(['(front door)', `proxy-${p.index}`, {pill: 'RUNNING'},
                 `:${p.port}`]);
    fill('serve', ['app', 'deployment', 'status', 'replicas'], rows);
  } catch (e) { fill('serve', ['(serve not running)'], []); }
  try {
    const so = await (await fetch('api/slo')).json();
    fill('slo', ['slo', 'state', 'objective', 'burn fast', 'burn slow'],
         (so.slos || []).map(r => [r.slo, {pill: r.state}, r.objective,
           (r.burn_fast || []).map(b => b.toFixed(2)).join('/'),
           (r.burn_slow || []).map(b => b.toFixed(2)).join('/')]));
  } catch (e) { fill('slo', ['(no slo engine)'], []); }
  try {
    const ac = await (await fetch('api/autoscaler')).json();
    fill('autoscaler', ['instance', 'type', 'state', 'provider_id', 'retries'],
         (ac.instances || []).map(r => [r.instance, r.type, {pill: r.state},
                                        r.provider_id || r.node_id || '',
                                        r.retries ?? '']));
  } catch (e) { fill('autoscaler', ['(no autoscaler)'], []); }
  const tasks = await (await fetch('api/tasks?limit=25')).json();
  fill('tasks', ['task_id', 'name', 'state', 'worker', 'duration'],
       tasks.map(t => [{text: (t.task_id || '').slice(0, 12),
                        click: () => detail('api/task/' + t.task_id)},
                       t.name, {pill: t.state}, t.worker || '',
                       t.duration_s ? t.duration_s.toFixed(3) + 's' : '']));
  const mem = await (await fetch('api/memory?limit=25')).json();
  document.getElementById('memsum').textContent =
    `${mem.num_objects_tracked} objects tracked | ` +
    `${mem.num_transfer_pins} transfer pins | ` +
    `${mem.num_task_arg_refs} task-arg refs | store ` +
    `${(mem.object_store.bytes_in_use/1048576).toFixed(1)}MB in ` +
    `${mem.object_store.num_objects} objects`;
  fill('memory', ['object', 'state', 'refs', 'holders', 'pins',
                  'in store', 'spilled', 'pinned'],
       mem.objects.map(o => [o.object_id.slice(0, 16), {pill: o.state},
                             o.num_refs, o.ref_holders.join(','),
                             o.transfer_pins, o.in_store ? 'y' : '',
                             o.spilled ? 'y' : '', o.pinned ? 'y' : '']));
  const logs = await (await fetch('api/logs')).json();
  fill('logs', ['file', 'size'],
       logs.map(l => [{text: l.file, click: () => showLog(l.file)},
                      `${(l.size/1024).toFixed(1)} KB`]));
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


def start_dashboard(port: int = 0, host: str = "127.0.0.1") -> int:
    """Start the dashboard on the head; returns the bound port."""
    from aiohttp import web

    from . import state as state_api
    from .core import runtime as rt_mod
    from .core.config import cfg

    rt = rt_mod.get_runtime_if_exists()
    if rt is None or not isinstance(rt, rt_mod.Runtime):
        raise RuntimeError("start_dashboard() runs on the head driver")
    if _server["runner"] is not None:
        return _server["port"]

    async def page(request):
        return web.Response(text=_PAGE, content_type="text/html")

    async def api(request):
        kind = request.match_info["kind"]
        limit = int(request.query.get("limit", 1000))
        try:
            if kind == "summary":
                out = state_api.summary()
            elif kind == "config":
                out = cfg.dump()
            elif kind == "jobs":
                out = state_api.list_jobs()
            elif kind == "autoscaler":
                out = state_api.autoscaler_status()
            elif kind == "serve":
                from . import serve as serve_api
                # remote round-trip: keep it off the dashboard event loop
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, serve_api.status)
            elif kind == "serve_metrics":
                # p50/p95/p99 TTFT / e2e / replica latency + headline
                # counters, condensed from the head's merged metric store
                from .serve.metrics import metrics_summary
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, metrics_summary)
            elif kind == "metrics_history":
                # TSDB range query: ?name=...&window=S&tags={"app":..}
                # &quantiles=0.5,0.95 — the trend source for any panel
                name = request.query.get("name", "")
                if not name:
                    return web.json_response(
                        {"error": "name parameter required"},
                        status=400)
                window = request.query.get("window")
                tags = request.query.get("tags")
                qs = request.query.get("quantiles")
                # TSDB lock + point materialization (up to
                # max_series x retention tuples): keep it off the
                # dashboard event loop, same rule as the serve branch
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(
                    None, rt.metrics_history, name,
                    json.loads(tags) if tags else None,
                    float(window) if window else None,
                    tuple(float(q) for q in qs.split(","))
                    if qs else None)
            elif kind == "slo":
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, rt.slo_report)
            elif kind == "cache":
                # prefix-cache heat map: walks directories + the merged
                # metric store under the head lock — off the event loop
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, rt.cache_report)
            elif kind == "memory":
                # head lock + per-object residency probes: keep it off
                # the dashboard event loop (same rule as the serve branch)
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(
                    None, state_api.memory_summary, limit)
            elif kind == "timeline":
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, rt.timeline)
            elif kind == "stacks":
                # cluster-wide live-stack pull (stall doctor): control-
                # plane round trips, keep it off the dashboard loop
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, rt.stack_report)
            elif kind == "hangs":
                loop = asyncio.get_event_loop()
                out = await loop.run_in_executor(None, rt.hang_report)
            elif kind in ("tasks", "actors", "objects", "nodes", "workers"):
                fn = getattr(state_api, f"list_{kind}")
                out = fn(limit) if kind in ("tasks", "actors",
                                            "objects") else fn()
            else:
                return web.json_response(
                    {"error": f"unknown kind {kind}"}, status=404)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response(out, dumps=lambda o: json.dumps(
            o, default=str))

    async def task_detail(request):
        """Per-task drill-in: the full record + its timeline events
        (reference: dashboard task detail via StateHead)."""
        tid = request.match_info["id"]
        with rt.lock:
            rec = next((dict(r) for r in rt.task_records.values()
                        if r.get("task_id") == tid), None)
            events = [e for e in rt.events
                      if e.get("tid") == tid[:8]]
        if rec is None:
            return web.json_response({"error": f"no task {tid}"},
                                     status=404)
        rec["events"] = events
        return web.json_response(rec, dumps=lambda o: json.dumps(
            o, default=str))

    async def actor_detail(request):
        aid_hex = request.match_info["id"]
        with rt.lock:
            hit = next(((aid, a) for aid, a in rt.actors.items()
                        if aid.hex() == aid_hex), None)
            if hit is None:
                return web.json_response(
                    {"error": f"no actor {aid_hex}"}, status=404)
            aid, a = hit
            out = {
                "actor_id": aid.hex(), "class_name": a.spec.name,
                "state": a.state.upper(), "name": a.spec.named or "",
                "worker": a.wid or "", "restarts_left": a.restarts_left,
                "death_cause": a.death_cause,
                "max_concurrency": a.spec.max_concurrency,
                "resources": dict(a.spec.resources),
                "pending_calls": [s.name for s in a.queue],
                "running_calls": [s.name for s in a.running.values()],
            }
        return web.json_response(out, dumps=lambda o: json.dumps(
            o, default=str))

    async def logs_index(request):
        import glob as _glob
        import os as _os
        out = []
        for p in sorted(_glob.glob(
                _os.path.join(rt.session_dir, "*.log"))):
            out.append({"file": _os.path.basename(p),
                        "size": _os.path.getsize(p)})
        return web.json_response(out)

    async def log_tail(request):
        """Log viewer endpoint (reference: dashboard log module). Only
        basenames inside THIS session's dir are served."""
        import os as _os
        name = _os.path.basename(request.query.get("file", ""))
        try:
            tail = int(request.query.get("tail", 200))
        except ValueError:
            return web.json_response({"error": "tail must be an int"},
                                     status=400)
        tail = max(1, min(tail, 5000))
        path = _os.path.join(rt.session_dir, name)
        if not name.endswith(".log") or not _os.path.isfile(path):
            return web.json_response({"error": f"no log {name!r}"},
                                     status=404)
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - 256 * 1024))
            lines = f.read().decode("utf-8", "replace").splitlines()
        return web.json_response({"file": name, "lines": lines[-tail:]})

    async def metrics(request):
        return web.Response(text=state_api._prometheus_text(),
                            content_type="text/plain")

    ready = threading.Event()

    def run():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        app = web.Application()
        app.router.add_get("/", page)
        app.router.add_get("/api/task/{id}", task_detail)
        app.router.add_get("/api/actor/{id}", actor_detail)
        app.router.add_get("/api/logs", logs_index)
        app.router.add_get("/api/log", log_tail)
        app.router.add_get("/api/{kind}", api)
        app.router.add_get("/metrics", metrics)
        runner = web.AppRunner(app)

        async def boot():
            await runner.setup()
            site = web.TCPSite(runner, host, port)
            await site.start()
            _server["port"] = site._server.sockets[0].getsockname()[1]
            _server["runner"] = runner
        loop.run_until_complete(boot())
        _server["loop"] = loop
        ready.set()
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True, name="rtpu-dashboard")
    t.start()
    _server["thread"] = t
    if not ready.wait(10):
        raise RuntimeError("dashboard failed to start")
    return _server["port"]


def stop_dashboard() -> None:
    loop = _server.get("loop")
    if loop is not None:
        loop.call_soon_threadsafe(loop.stop)
    _server.update({"runner": None, "loop": None, "port": None,
                    "thread": None})
