"""ray_tpu.data — lazy, streaming distributed datasets.

Reference parity: python/ray/data (Dataset dataset.py, logical plans
_internal/logical/interfaces/logical_plan.py:10, streaming executor
_internal/execution/streaming_executor.py:52, read_api.py). Same shape here:
a Dataset is a lazy logical plan over blocks (pyarrow Tables in the shared
object store); execution fuses map chains into single tasks and streams
blocks through the gang (executor.py). The TPU-facing surface is
`iter_batches(batch_format="numpy")` feeding jax device_put, and
`streaming_split(n)` shards for Train worker gangs.
"""
from .context import DataContext
from .dataset import ActorPoolStrategy, DataIterator, Dataset, Schema
from .read_api import (
    from_arrow,
    from_huggingface,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A001 — reference API name (ray.data.range)
    read_csv,
    read_json,
    read_binary_files,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "ActorPoolStrategy",
    "DataContext", "Dataset", "DataIterator", "Schema", "from_arrow",
    "from_huggingface",
    "from_items", "from_numpy", "from_pandas", "range", "read_csv",
    "read_json", "read_parquet", "read_text", "read_binary_files", "read_numpy",
]
