"""Blocks: the unit of distributed data — pyarrow Tables in the object store.

Reference parity: python/ray/data/block.py (Block = pyarrow.Table | pandas
DataFrame; BlockAccessor). Here blocks are always Arrow tables (zero-copy
into the shm store via pickle-5 buffers) and this module is the accessor:
conversion to/from rows, numpy batches, pandas; slicing; concatenation.
"""
from __future__ import annotations

from typing import Any, Iterable, Iterator, Optional

import numpy as np
import pyarrow as pa

Block = pa.Table

TENSOR_COLUMN = "data"  # default column name for tensor/ndarray datasets


def from_items(items: list) -> Block:
    """Rows of dicts -> table; scalars go into an 'item' column (reference:
    ray.data.from_items semantics)."""
    if items and isinstance(items[0], dict):
        cols: dict[str, list] = {k: [] for k in items[0]}
        for row in items:
            for k in cols:
                cols[k].append(row.get(k))
        return pa.table({k: _to_array(v) for k, v in cols.items()})
    return pa.table({"item": _to_array(list(items))})


def _to_array(values: list) -> pa.Array:
    if values and isinstance(values[0], np.ndarray):
        flat = np.stack(values)
        return _tensor_array(flat)
    return pa.array(values)


def _tensor_array(arr: np.ndarray) -> pa.Array:
    """Fixed-shape tensor column (reference: ArrowTensorArray)."""
    if arr.ndim == 1:
        return pa.array(arr)
    inner = arr.reshape(len(arr), -1)
    return pa.FixedSizeListArray.from_arrays(
        pa.array(inner.ravel()), inner.shape[1])


def from_numpy(arr: np.ndarray, column: str = TENSOR_COLUMN) -> Block:
    return pa.table({column: _tensor_array(arr)})


def column_to_numpy(col: pa.ChunkedArray | pa.Array) -> np.ndarray:
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    if pa.types.is_fixed_size_list(col.type):
        width = col.type.list_size
        flat = col.flatten().to_numpy(zero_copy_only=False)
        return flat.reshape(-1, width)
    return col.to_numpy(zero_copy_only=False)


def to_numpy_batch(block: Block) -> dict[str, np.ndarray]:
    return {name: column_to_numpy(block.column(name))
            for name in block.column_names}


def to_rows(block: Block) -> Iterator[dict]:
    yield from block.to_pylist()


def num_rows(block: Block) -> int:
    return block.num_rows


def size_bytes(block: Block) -> int:
    return block.nbytes


def slice_block(block: Block, start: int, end: int) -> Block:
    return block.slice(start, end - start)


def concat(blocks: Iterable[Block]) -> Block:
    blocks = [b for b in blocks if b is not None and b.num_rows >= 0]
    if not blocks:
        return pa.table({})
    return pa.concat_tables(blocks, promote_options="default")


def from_batch(batch: Any) -> Block:
    """A user map_batches return value -> Block. Accepts dict[str, ndarray],
    pyarrow Table, pandas DataFrame, or list of row dicts."""
    import pandas as pd
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, pd.DataFrame):
        return pa.Table.from_pandas(batch, preserve_index=False)
    if isinstance(batch, dict):
        return pa.table({
            k: (_tensor_array(v) if isinstance(v, np.ndarray) and v.ndim > 1
                else pa.array(v))
            for k, v in batch.items()})
    if isinstance(batch, list):
        return from_items(batch)
    raise TypeError(
        f"map_batches must return dict/Table/DataFrame/list, got "
        f"{type(batch).__name__}")


def format_batch(block: Block, batch_format: Optional[str]):
    """(reference: batch formats of iter_batches, dataset.py:4661)"""
    if batch_format in (None, "default", "numpy"):
        return to_numpy_batch(block)
    if batch_format == "pandas":
        return block.to_pandas()
    if batch_format == "pyarrow":
        return block
    raise ValueError(f"unknown batch_format {batch_format!r}")
