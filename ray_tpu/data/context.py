"""Execution-context knobs (reference parity: python/ray/data/context.py
DataContext — a process-wide singleton of tunables)."""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    default_batch_size: int = 256
    # max concurrently in-flight block tasks per executing dataset
    max_tasks_in_flight: int = 16
    read_default_num_blocks: int = 8
    # actor-pool autoscaling (reference: _internal/execution/autoscaler/
    # default_autoscaler.py): scale UP when every active actor has at
    # least this many calls queued; scale DOWN when more than half the
    # pool sits idle
    actor_pool_scale_up_queued: int = 2
    # streaming physical executor (data/streaming): "auto" streams any
    # streamable plan through stage actors on sealed channels when a
    # cluster with a shared shm store is up (falling back to the task
    # executor otherwise), "off" always uses the task executor, "force"
    # raises instead of falling back (tests/benches pin the path)
    streaming_executor: str = "auto"
    # per-edge credit window, in blocks, per (producer, consumer) ring:
    # bounds in-flight memory under skew (a stage 10x slower parks its
    # senders at this limit instead of flooding the store)
    streaming_ring: int = 4
    # source-stage workers (read tasks / block fetches run this wide)
    streaming_source_workers: int = 2
    # streaming_split transport: "actor" = the work-stealing coordinator
    # actor (one dispatch per block, any consumption pattern), "chan" =
    # push-mode sealed-channel shards (zero dispatches per block; shards
    # should be consumed concurrently for balanced splits, though any
    # order stays correct)
    split_transport: str = "actor"

    _instance = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
