"""Execution-context knobs (reference parity: python/ray/data/context.py
DataContext — a process-wide singleton of tunables)."""
from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    default_batch_size: int = 256
    # max concurrently in-flight block tasks per executing dataset
    max_tasks_in_flight: int = 16
    read_default_num_blocks: int = 8
    # actor-pool autoscaling (reference: _internal/execution/autoscaler/
    # default_autoscaler.py): scale UP when every active actor has at
    # least this many calls queued; scale DOWN when more than half the
    # pool sits idle
    actor_pool_scale_up_queued: int = 2

    _instance = None

    @classmethod
    def get_current(cls) -> "DataContext":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
