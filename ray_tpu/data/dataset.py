"""Dataset: the lazy user-facing handle over a logical plan.

Reference parity: python/ray/data/dataset.py (map/map_batches/filter/
flat_map, iter_batches :4661, streaming_split :1731, groupby, sort, limit,
take, count, schema, union, zip, repartition, random_shuffle, write_*,
materialize). `iter_jax_batches` replaces iter_torch_batches as the
accelerator hand-off (device_put onto the current mesh's batch sharding).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from . import block as B
from .context import DataContext
from .executor import (
    BlockMeta,
    BlockOp,
    Exchange,
    Executor,
    InputData,
    LogicalOp,
    Read,
    iter_blocks,
)


class Schema:
    def __init__(self, arrow_schema):
        self._schema = arrow_schema

    @property
    def names(self) -> list[str]:
        return list(self._schema.names)

    @property
    def types(self) -> list:
        return list(self._schema.types)

    def __repr__(self):
        cols = ", ".join(f"{n}: {t}" for n, t in
                         zip(self._schema.names, self._schema.types))
        return f"Schema({cols})"


# -- block-op builders (top-level for cheap pickling) -----------------------

def _map_batches_block(fn, batch_format, batch):
    out = fn(B.format_batch(batch, batch_format))
    return B.from_batch(out)


def _call_batch_block(batch_format, fn_instance, batch):
    """Actor-pool variant of _map_batches_block: the callable is a
    constructed instance living in the pool actor."""
    out = fn_instance(B.format_batch(batch, batch_format))
    return B.from_batch(out)


@dataclasses.dataclass
class ActorPoolStrategy:
    """compute= strategy for stateful map_batches (reference:
    ray.data.ActorPoolStrategy)."""
    size: int = 2
    min_size: Optional[int] = None   # accepted for API compat
    max_size: Optional[int] = None


def _map_rows_block(fn, batch):
    return B.from_items([fn(r) for r in B.to_rows(batch)])


def _flat_map_block(fn, batch):
    out = []
    for r in B.to_rows(batch):
        out.extend(fn(r))
    return B.from_items(out)


def _filter_block(fn, batch):
    keep = np.fromiter((bool(fn(r)) for r in B.to_rows(batch)),
                       dtype=bool, count=batch.num_rows)
    return batch.take(np.nonzero(keep)[0])


def _select_block(cols, batch):
    return batch.select(cols)


def _drop_block(cols, batch):
    return batch.drop_columns(cols)


def _rename_block(mapping, batch):
    return batch.rename_columns(
        [mapping.get(n, n) for n in batch.column_names])


def _add_column_block(name, fn, batch):
    col = fn(B.format_batch(batch, "pandas"))
    return batch.append_column(name, B.from_batch({name: np.asarray(col)})
                               .column(name))


def _clamp(v, lo, hi):
    return max(lo, min(hi, v))


def _write_block(fs_, path_template, fmt, index, batch):
    # shard writes stream through the filesystem's output stream, so
    # gs://-style destinations never stage a local copy (reference:
    # file_datasink.py write path through pyarrow fs)
    import pyarrow.csv as pcsv
    import pyarrow.parquet as pq
    path = path_template.format(i=index)
    with fs_.open_output_stream(path) as f:
        if fmt == "parquet":
            pq.write_table(batch, f)
        elif fmt == "csv":
            pcsv.write_csv(batch, f)
        elif fmt == "json":
            f.write(batch.to_pandas().to_json(
                orient="records", lines=True).encode("utf-8"))
    return path


class Dataset:
    """Lazy distributed dataset (reference: dataset.py Dataset)."""

    def __init__(self, plan: LogicalOp, ctx: Optional[DataContext] = None):
        from ..core.usage import record_library_usage
        record_library_usage("data")
        self._plan = plan
        self._ctx = ctx or DataContext.get_current()
        self._cached: Optional[list[tuple[Any, BlockMeta]]] = None

    # -- transforms (lazy) ------------------------------------------------

    def _block_op(self, fn, name) -> "Dataset":
        return Dataset(BlockOp(self._plan, fn, name), self._ctx)

    def map_batches(self, fn: Callable, *, batch_format: str = "numpy",
                    compute=None, concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    **_ignored) -> "Dataset":
        """Stateless path: fn fuses into per-block tasks. Stateful path
        (``compute=ActorPoolStrategy(size=n)`` / ``concurrency=n`` with a
        callable CLASS): the class is constructed once per pool actor —
        model weights load once, batches stream through (reference:
        ActorPoolMapOperator). ``concurrency=(min, max)`` (or an
        ActorPoolStrategy with min_size/max_size) makes the pool
        AUTOSCALING between the two from queue depth (reference:
        autoscaler/default_autoscaler.py)."""
        if compute is None and concurrency is None:
            return self._block_op(
                functools.partial(_map_batches_block, fn, batch_format),
                "MapBatches")
        import cloudpickle

        from .executor import ActorPoolOp
        if isinstance(concurrency, (tuple, list)):
            size, max_size = int(concurrency[0]), int(concurrency[1])
            if size < 1 or max_size < size:
                raise ValueError(
                    f"concurrency=(min, max) needs 1 <= min <= max, "
                    f"got {concurrency!r}")
        else:
            size = concurrency or getattr(compute, "size", None) or 2
            max_size = size
            if compute is not None and getattr(compute, "min_size", None):
                size = compute.min_size
                max_size = compute.max_size or size
        wrap = functools.partial(_call_batch_block, batch_format)
        blob = cloudpickle.dumps((fn, tuple(fn_constructor_args),
                                  fn_constructor_kwargs or {}, wrap))
        return Dataset(ActorPoolOp(self._plan, blob, int(size),
                                   "MapBatches(actors)",
                                   max_size=int(max_size)), self._ctx)

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._block_op(functools.partial(_map_rows_block, fn), "Map")

    def flat_map(self, fn: Callable[[dict], list]) -> "Dataset":
        return self._block_op(functools.partial(_flat_map_block, fn),
                              "FlatMap")

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._block_op(functools.partial(_filter_block, fn), "Filter")

    def select_columns(self, cols: list[str]) -> "Dataset":
        return self._block_op(functools.partial(_select_block, cols),
                              "Select")

    def drop_columns(self, cols: list[str]) -> "Dataset":
        return self._block_op(functools.partial(_drop_block, cols), "Drop")

    def rename_columns(self, mapping: dict[str, str]) -> "Dataset":
        return self._block_op(functools.partial(_rename_block, mapping),
                              "Rename")

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        return self._block_op(functools.partial(_add_column_block, name, fn),
                              "AddColumn")

    def limit(self, n: int) -> "Dataset":
        return Dataset(Exchange([self._plan], "limit", n=n), self._ctx)

    def repartition(self, num_blocks: int) -> "Dataset":
        return Dataset(Exchange([self._plan], "repartition", n=num_blocks),
                       self._ctx)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return Dataset(Exchange([self._plan], "shuffle", n=None,
                                seed=seed or 0), self._ctx)

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return Dataset(Exchange([self._plan], "sort", key=key,
                                descending=descending), self._ctx)

    def union(self, *others: "Dataset") -> "Dataset":
        return Dataset(Exchange([self._plan, *(o._plan for o in others)],
                                "union"), self._ctx)

    def zip(self, other: "Dataset") -> "Dataset":
        return Dataset(Exchange([self._plan, other._plan], "zip"), self._ctx)

    def join(self, other: "Dataset", on, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on key column(s) (reference:
        Dataset.join / _internal/execution/operators/join.py)."""
        return Dataset(Exchange([self._plan, other._plan], "join", on=on,
                                how=how, num_partitions=num_partitions),
                       self._ctx)

    def groupby(self, key: str) -> "GroupedData":
        from .grouped import GroupedData
        return GroupedData(self, key)

    # -- execution --------------------------------------------------------

    def _execute(self) -> list[tuple[Any, BlockMeta]]:
        if self._cached is None:
            self._cached = Executor(self._ctx).execute(self._plan)
        return self._cached

    def _stream_pairs(self):
        """(block_ref, meta) pairs for consumption: the cached list when
        materialized, otherwise the streaming executor's bounded-window
        generator (read/map/consume overlap; at most
        ctx.max_tasks_in_flight blocks in flight)."""
        if self._cached is not None:
            return self._cached
        return Executor(self._ctx).execute_streaming(self._plan)

    def _streaming_pipeline_factory(self):
        """A () -> StreamingPipeline factory when the channel-based
        streaming executor (data/streaming) should drive consumption,
        else None (task executor). "auto" engages whenever the plan is
        streamable and a cluster with a shared shm store is up —
        results are bit-identical either way, only the dispatch bill
        differs."""
        mode = getattr(self._ctx, "streaming_executor", "off")
        if mode == "off" or self._cached is not None:
            return None
        from ..core import runtime as rt_mod
        rt = rt_mod.get_runtime_if_exists()
        if getattr(rt, "store", None) is None:
            if mode == "force":
                raise RuntimeError(
                    "streaming_executor='force' needs an initialized "
                    "cluster with a shared shm object store")
            return None
        from .streaming.executor import (StreamingPipeline, compile_plan,
                                         worker_budget)
        drafts = compile_plan(self._plan, self._ctx)
        if drafts is None:
            if mode == "force":
                raise RuntimeError(
                    "streaming_executor='force': this plan has nothing "
                    "to stream (bare materialized blocks)")
            return None
        if mode != "force" and len(drafts) > worker_budget():
            # more stages than the worker pool can run concurrently
            # (a many-way zip tree on a tiny cluster): the pipeline
            # could never schedule every run_loop — use tasks instead
            return None
        ctx = self._ctx
        return lambda **kw: StreamingPipeline(drafts, ctx, **kw)

    def _stream_feed(self):
        """What iteration consumers drink from: the channel pipeline
        when streaming engages, else (ref, meta) pairs."""
        make = self._streaming_pipeline_factory()
        if make is not None:
            from .streaming.executor import PipelineFeed
            return PipelineFeed(make)
        return self._stream_pairs()

    def materialize(self) -> "Dataset":
        pairs = self._execute()
        out = Dataset(InputData(pairs), self._ctx)
        out._cached = pairs
        return out

    def count(self) -> int:
        return sum(m.rows for _, m in self._execute())

    def size_bytes(self) -> int:
        return sum(m.bytes for _, m in self._execute())

    def num_blocks(self) -> int:
        return len(self._execute())

    def schema(self) -> Optional[Schema]:
        pairs = self._execute()
        if not pairs:
            return None
        import ray_tpu
        return Schema(ray_tpu.get(pairs[0][0]).schema)

    def columns(self) -> list[str]:
        s = self.schema()
        return s.names if s is not None else []

    # -- column aggregates (reference: Dataset.sum/min/max/mean/std over
    # AggregateFn, data/aggregate.py — per-block partials combined
    # driver-side) ---------------------------------------------------------

    def _col_partials(self, on: str) -> list[dict]:
        import ray_tpu

        def partial(blk):
            col = B.column_to_numpy(blk.column(on)).astype(np.float64)
            if len(col) == 0:
                return {"n": 0}
            m = float(col.mean())
            return {"n": len(col), "sum": float(col.sum()),
                    "min": float(col.min()), "max": float(col.max()),
                    "mean": m, "m2": float(((col - m) ** 2).sum())}

        part = ray_tpu.remote(partial)
        return ray_tpu.get([part.remote(ref)
                            for ref, _ in self._execute()])

    def sum(self, on: str) -> float:
        ps = [p for p in self._col_partials(on) if p["n"]]
        return sum(p["sum"] for p in ps)

    def min(self, on: str) -> float:
        ps = [p for p in self._col_partials(on) if p["n"]]
        if not ps:
            raise ValueError("min() on an empty dataset")
        return min(p["min"] for p in ps)

    def max(self, on: str) -> float:
        ps = [p for p in self._col_partials(on) if p["n"]]
        if not ps:
            raise ValueError("max() on an empty dataset")
        return max(p["max"] for p in ps)

    def mean(self, on: str) -> float:
        ps = [p for p in self._col_partials(on) if p["n"]]
        n = sum(p["n"] for p in ps)
        if n == 0:
            raise ValueError("mean() on an empty dataset")
        return sum(p["sum"] for p in ps) / n

    def std(self, on: str, ddof: int = 1) -> float:
        """Pairwise Welford merge of per-block (n, mean, M2) partials —
        numerically stable for large-magnitude columns (the naive
        sumsq - sum^2/n cancels catastrophically)."""
        import math
        ps = [p for p in self._col_partials(on) if p["n"]]
        n_tot = sum(p["n"] for p in ps)
        if n_tot <= ddof:
            raise ValueError("std() needs more rows than ddof")
        n, mean, m2 = 0.0, 0.0, 0.0
        for p in ps:
            delta = p["mean"] - mean
            tot = n + p["n"]
            mean += delta * p["n"] / tot
            m2 += p["m2"] + delta ** 2 * n * p["n"] / tot
            n = tot
        return math.sqrt(m2 / (n - ddof))

    def unique(self, column: str) -> list:
        import ray_tpu

        def uniq(blk):
            return set(B.column_to_numpy(blk.column(column)).tolist())

        u = ray_tpu.remote(uniq)
        out: set = set()
        for part in ray_tpu.get([u.remote(ref)
                                 for ref, _ in self._execute()]):
            out |= part
        return sorted(out)

    def random_sample(self, fraction: float,
                      *, seed: Optional[int] = None) -> "Dataset":
        """Bernoulli row sample (reference: Dataset.random_sample)."""
        if not 0.0 <= fraction <= 1.0:
            raise ValueError("fraction must be in [0, 1]")
        def sample_block(blk):
            rows = B.num_rows(blk)
            if seed is None:
                rng = np.random.RandomState()   # fresh OS entropy
            else:
                # fold block CONTENT into the seed (the block fn gets no
                # index): equal-sized blocks must not draw identical masks
                import zlib
                head = B.to_rows(B.slice_block(blk, 0, min(3, rows)))
                h = zlib.crc32(repr((rows, head)).encode())
                rng = np.random.RandomState(
                    (seed * 1_000_003 + h) % (2 ** 31))
            keep = np.nonzero(rng.random_sample(rows) < fraction)[0]
            return blk.take(keep)

        return self._block_op(sample_block, "RandomSample")

    def train_test_split(self, test_size: float, *, shuffle: bool = False,
                         seed: Optional[int] = None
                         ) -> tuple["Dataset", "Dataset"]:
        """(train, test) row split (reference: Dataset.train_test_split)."""
        if not 0.0 < test_size < 1.0:
            raise ValueError("test_size must be in (0, 1)")
        import ray_tpu
        from .executor import _slice_task
        ds = self.random_shuffle(seed=seed) if shuffle else self
        pairs = ds._execute()
        total = sum(m.rows for _, m in pairs)
        n_test = int(round(total * test_size))
        sl = ray_tpu.remote(_slice_task).options(num_returns=2)
        train_pairs, test_pairs = [], []
        seen = 0
        for ref, meta in pairs:
            cut = _clamp(n_test - seen, 0, meta.rows)  # rows going to test
            seen += meta.rows
            if cut == 0:
                train_pairs.append((ref, meta))
            elif cut == meta.rows:
                test_pairs.append((ref, meta))
            else:
                # _slice_task returns (block, real BlockMeta) — byte
                # sizes stay accurate for the boundary halves
                hb, hm = sl.remote(ref, 0, cut)
                tb, tm = sl.remote(ref, cut, meta.rows)
                test_pairs.append((hb, ray_tpu.get(hm)))
                train_pairs.append((tb, ray_tpu.get(tm)))
        return (Dataset(InputData(train_pairs), self._ctx),
                Dataset(InputData(test_pairs), self._ctx))

    def to_pandas(self, limit: Optional[int] = None):
        import pandas as pd
        rows = self.take(limit) if limit is not None else self.take_all()
        return pd.DataFrame(rows)

    def take(self, n: int = 20) -> list[dict]:
        out: list[dict] = []
        for blk in DataIterator(self._stream_feed()).iter_blocks():
            for row in B.to_rows(blk):
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> list[dict]:
        return [r for blk in iter_blocks(self._execute())
                for r in B.to_rows(blk)]

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    # -- iteration --------------------------------------------------------

    def iter_rows(self) -> Iterator[dict]:
        for blk in DataIterator(self._stream_feed()).iter_blocks():
            yield from B.to_rows(blk)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator:
        return DataIterator(self._stream_feed()).iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = True, sharding=None) -> Iterator:
        return DataIterator(self._stream_feed()).iter_jax_batches(
            batch_size=batch_size, drop_last=drop_last, sharding=sharding)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False) -> Iterator:
        return DataIterator(self._stream_feed()).iter_torch_batches(
            batch_size=batch_size, drop_last=drop_last)

    def streaming_split(self, n: int) -> list["DataIterator"]:
        """n iterators sharing ONE streaming execution, one per Train
        worker (reference: dataset.py:1731 + the output-splitter operator).
        Work-stealing split either way: with
        ``ctx.split_transport="actor"`` (default) a coordinator actor
        hands out finished blocks one dispatch at a time; with "chan"
        the streaming pipeline's sink edge fans out over n sealed-ring
        consumer slots — zero dispatches per block, blocks flow to
        whichever shard is consuming (consume shards concurrently for
        balanced cuts). Shards are picklable to workers either way and
        no one waits for the whole dataset to materialize."""
        if self._cached is not None:
            return [DataIterator(self._cached[i::n]) for i in range(n)]
        if getattr(self._ctx, "split_transport", "actor") == "chan":
            make = self._streaming_pipeline_factory()
            if make is not None:
                from .streaming.executor import ChannelShardFeed
                pipe = make(consumers=n, split=True).start()
                return [DataIterator(ChannelShardFeed(
                    pipe.sink_edge, i, pipeline=pipe)) for i in range(n)]
        import ray_tpu as ray
        Coord = ray.remote(_SplitCoordinator)
        coord = Coord.remote(self._plan, self._ctx, n)
        return [DataIterator(_ActorFeed(coord)) for _ in range(n)]

    def split(self, n: int) -> list["Dataset"]:
        pairs = self._execute()
        return [Dataset(InputData(pairs[i::n]), self._ctx) for i in range(n)]

    # -- writes -----------------------------------------------------------

    def _write(self, path: str, fmt: str, ext: str,
               filesystem=None) -> list[str]:
        import posixpath

        import ray_tpu
        from ..util.fs import makedirs, resolve
        fs_, root = resolve(path, filesystem)
        makedirs(fs_, root)
        tmpl = posixpath.join(root.replace("\\", "/"),
                              f"part-{{i:05d}}.{ext}")
        write = ray_tpu.remote(_write_block)
        refs = [write.remote(fs_, tmpl, fmt, i, ref)
                for i, (ref, _) in enumerate(self._execute())]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str, *, filesystem=None) -> list[str]:
        return self._write(path, "parquet", "parquet", filesystem)

    def write_csv(self, path: str, *, filesystem=None) -> list[str]:
        return self._write(path, "csv", "csv", filesystem)

    def write_json(self, path: str, *, filesystem=None) -> list[str]:
        return self._write(path, "json", "json", filesystem)

    def stats(self) -> str:
        pairs = self._execute()
        return (f"Dataset: {len(pairs)} blocks, {self.count()} rows, "
                f"{self.size_bytes()} bytes")

    def __repr__(self):
        return f"Dataset(plan={self._plan!r})"


class _SplitCoordinator:
    """Actor owning one streaming execution for streaming_split consumers
    (reference analog: the output-splitter coordination of
    _internal/execution/operators/output_splitter.py). Single-threaded
    actor => next() calls serialize; consumers fetch block payloads from
    the store in parallel afterwards.

    Self-terminates after every consumer has seen exhaustion, so repeated
    streaming_split calls don't accumulate idle actors."""

    def __init__(self, plan, ctx, n_consumers: int):
        self._it = Executor(ctx).execute_streaming(plan)
        self._nones_left = n_consumers

    def next(self):
        try:
            return next(self._it)
        except StopIteration:
            self._nones_left -= 1
            if self._nones_left <= 0:
                import os
                import threading
                # reply first, then exit (daemon timer outlives this call)
                threading.Timer(0.5, lambda: os._exit(0)).start()
            return None


class _ActorFeed:
    """Picklable pair-iterable backed by a _SplitCoordinator handle.

    Claimed pairs are CACHED so a shard is re-iterable (multi-epoch train
    loops replay the same blocks); only the first pass pulls from the
    coordinator."""

    def __init__(self, coord):
        self._coord = coord
        self._cache: list = []
        self._complete = False

    def __iter__(self):
        yield from self._cache
        if self._complete:
            return
        import ray_tpu
        while True:
            pair = ray_tpu.get(self._coord.next.remote())
            if pair is None:
                self._complete = True
                return
            self._cache.append(pair)
            yield pair


class DataIterator:
    """Streams batches from block pairs — a materialized list, a live
    task-executor generator, or a channel-pipeline feed exposing
    ``iter_blocks()`` (reference: data/iterator.py DataIterator;
    iter_torch_batches -> iter_jax_batches)."""

    def __init__(self, pairs):
        self._pairs = pairs

    def _as_list(self) -> list[tuple[Any, BlockMeta]]:
        if isinstance(self._pairs, _ActorFeed) and not self._pairs._complete:
            # draining the shared coordinator here would claim every
            # remaining block for THIS shard and starve its siblings
            raise TypeError(
                "count() on an unconsumed streaming_split shard would "
                "steal the other shards' blocks; iterate it (or "
                "materialize() the dataset) first")
        if not isinstance(self._pairs, list):
            self._pairs = list(self._pairs)
        return self._pairs

    def count(self) -> int:
        if hasattr(self._pairs, "count_rows"):
            return self._pairs.count_rows()
        if hasattr(self._pairs, "iter_blocks"):
            return sum(b.num_rows for b in self._pairs.iter_blocks())
        return sum(m.rows for _, m in self._as_list())

    def iter_blocks(self) -> Iterator[B.Block]:
        if hasattr(self._pairs, "iter_blocks"):
            return self._pairs.iter_blocks()
        return iter_blocks(self._pairs)

    def iter_rows(self) -> Iterator[dict]:
        for blk in self.iter_blocks():
            yield from B.to_rows(blk)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator:
        if local_shuffle_buffer_size:
            yield from self._iter_shuffled(
                batch_size or 256, batch_format, drop_last,
                local_shuffle_buffer_size, local_shuffle_seed)
            return
        carry: Optional[B.Block] = None
        for blk in self.iter_blocks():
            if carry is not None and carry.num_rows:
                blk = B.concat([carry, blk])
                carry = None
            if batch_size is None:
                yield B.format_batch(blk, batch_format)
                continue
            start = 0
            while blk.num_rows - start >= batch_size:
                yield B.format_batch(
                    B.slice_block(blk, start, start + batch_size),
                    batch_format)
                start += batch_size
            if start < blk.num_rows:
                carry = B.slice_block(blk, start, blk.num_rows)
        if carry is not None and carry.num_rows and not drop_last:
            yield B.format_batch(carry, batch_format)

    def _iter_shuffled(self, batch_size: int, batch_format: str,
                      drop_last: bool, buf_size: int,
                      seed: Optional[int]) -> Iterator:
        """Streaming local shuffle (reference: iter_batches'
        local_shuffle_buffer_size): hold ~buf_size rows, emit each batch
        as a random draw from the buffer while the stream refills it —
        per-epoch randomization at buffer-memory cost, without a full
        distributed random_shuffle()."""
        rng = np.random.default_rng(seed)
        buf: Optional[B.Block] = None
        for blk in self.iter_blocks():
            buf = blk if buf is None else B.concat([buf, blk])
            while buf.num_rows >= buf_size + batch_size:
                pick = rng.choice(buf.num_rows, size=batch_size,
                                  replace=False)
                mask = np.ones(buf.num_rows, bool)
                mask[pick] = False
                yield B.format_batch(buf.take(pick), batch_format)
                buf = buf.take(np.nonzero(mask)[0])
        if buf is None or not buf.num_rows:
            return
        order = rng.permutation(buf.num_rows)
        start = 0
        while buf.num_rows - start >= batch_size:
            yield B.format_batch(
                buf.take(order[start:start + batch_size]), batch_format)
            start += batch_size
        if start < buf.num_rows and not drop_last:
            yield B.format_batch(buf.take(order[start:]), batch_format)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           drop_last: bool = False) -> Iterator:
        """numpy batches -> torch tensors (reference:
        dataset.py:4732 iter_torch_batches; torch-cpu in this image)."""
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            yield {k: torch.from_numpy(np.ascontiguousarray(v))
                   for k, v in batch.items()}

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = True,
                         sharding=None) -> Iterator:
        """numpy batches -> jax arrays, device_put with `sharding` (or the
        current mesh's batch sharding when inside parallel.use_mesh)."""
        import jax
        if sharding is None:
            from ..parallel.mesh import get_mesh
            from ..parallel.sharding import batch_spec
            mesh = get_mesh()
            if mesh is not None:
                from jax.sharding import NamedSharding
                sharding = NamedSharding(mesh, batch_spec(mesh))
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding)
                       for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}
