"""Logical plan + executor: fused block tasks over the runtime.

Reference parity: the logical/physical plan split of
python/ray/data/_internal/logical/ (LogicalPlan interfaces logical_plan.py:10)
and the streaming executor (streaming_executor.py:52). Scoped to one design
idea for round 1: every op is either

* a **block op** — pure fn(Block) -> Block. Chains of block ops FUSE into a
  single remote task per block (the reference's OperatorFusionRule,
  _internal/logical/rules/operator_fusion.py), so map/filter/flat_map
  pipelines cost one task per block; or
* an **exchange** — an all-to-all boundary (shuffle, repartition, sort,
  groupby) implemented as map-partition + reduce tasks.

Execution yields (block_ref, meta) pairs; block payloads stay in the shm
object store and stream to consumers via per-block ray.get.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, Optional

import numpy as np

from . import block as B
from .context import DataContext


@dataclasses.dataclass
class BlockMeta:
    rows: int
    bytes: int


class LogicalOp:
    """Node in the lazy plan DAG."""

    def __init__(self, name: str, inputs: list["LogicalOp"]):
        self.name = name
        self.inputs = inputs

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.inputs))})"


class InputData(LogicalOp):
    def __init__(self, refs_and_meta: list[tuple]):
        super().__init__("InputData", [])
        self.refs_and_meta = refs_and_meta


class Read(LogicalOp):
    """One task per read callable (reference: planner/plan_read_op.py)."""

    def __init__(self, read_tasks: list[Callable[[], B.Block]], name="Read"):
        super().__init__(name, [])
        self.read_tasks = read_tasks


class BlockOp(LogicalOp):
    """Fusable fn(Block)->Block (map_batches/map/filter/flat_map/project)."""

    def __init__(self, input_op: LogicalOp, fn: Callable[[B.Block], B.Block],
                 name: str):
        super().__init__(name, [input_op])
        self.fn = fn


class Exchange(LogicalOp):
    """All-to-all boundary. kind in {repartition, shuffle, sort, groupby,
    limit, union, zip, join}; args carried per kind."""

    def __init__(self, inputs: list[LogicalOp], kind: str, **kwargs):
        super().__init__(f"Exchange[{kind}]", inputs)
        self.kind = kind
        self.kwargs = kwargs


class ActorPoolOp(LogicalOp):
    """map_batches over a pool of stateful actors (reference:
    ActorPoolMapOperator, _internal/execution/operators/actor_map_operator.py
    + ActorPoolStrategy). The fn is a CLASS: constructed once per actor
    (model load happens once), called per batch. Breaks block-op fusion
    above it; downstream block fns ride along into the actor call.
    The pool autoscales between min_size and max_size from queue depth
    (reference: autoscaler/default_autoscaler.py try_trigger_scaling)."""

    def __init__(self, input_op: LogicalOp, fn_blob: bytes, size: int,
                 name: str, max_size: Optional[int] = None):
        super().__init__(name, [input_op])
        self.fn_blob = fn_blob      # cloudpickle((cls, args, kwargs, wrap))
        self.size = size            # initial/min pool size
        self.max_size = max_size or size


# ---------------------------------------------------------------------------
# Remote task bodies (top-level so cloudpickle ships them cheaply)
# ---------------------------------------------------------------------------

def _run_fused(fns, block):
    for fn in fns:
        block = fn(block)
    return block, BlockMeta(B.num_rows(block), B.size_bytes(block))


def _run_read(read_fn, fns):
    block = read_fn()
    return _run_fused(fns, block)


def _split_for_exchange(block, n_out, shuffle, seed):
    """Map side of an exchange: partition rows into n_out slices."""
    rows = B.num_rows(block)
    if shuffle:
        rng = np.random.RandomState(seed)
        idx = rng.permutation(rows)
        block = block.take(idx)
    # contiguous split keeps arrow slicing zero-copy
    bounds = np.linspace(0, rows, n_out + 1).astype(int)
    return tuple(B.slice_block(block, bounds[i], bounds[i + 1])
                 for i in range(n_out))


def _combine_partition(shuffle, seed, *parts):
    out = B.concat(list(parts))
    if shuffle:
        rng = np.random.RandomState(seed)
        out = out.take(rng.permutation(B.num_rows(out)))
    return out, BlockMeta(B.num_rows(out), B.size_bytes(out))


def _sort_and_partition(block, key, descending, boundaries):
    """Sort-map: locally sort, then split at the sampled boundaries."""
    order = "descending" if descending else "ascending"
    block = block.sort_by([(key, order)])
    col = B.column_to_numpy(block.column(key))
    if descending:
        cuts = len(col) - np.searchsorted(col[::-1], boundaries, side="left")
    else:
        cuts = np.searchsorted(col, boundaries, side="right")
    bounds = [0] + list(cuts) + [len(col)]
    return tuple(B.slice_block(block, bounds[i], bounds[i + 1])
                 for i in range(len(bounds) - 1))


def _merge_sorted(key, descending, *parts):
    out = B.concat(list(parts))
    order = "descending" if descending else "ascending"
    out = out.sort_by([(key, order)])
    return out, BlockMeta(B.num_rows(out), B.size_bytes(out))


def _sample_block(block, key, n):
    col = B.column_to_numpy(block.column(key))
    if len(col) == 0:
        return np.array([])
    idx = np.random.RandomState(0).randint(0, len(col), min(n, len(col)))
    return col[idx]


def _stable_hash(x) -> int:
    # Python's str hash is per-process randomized (PYTHONHASHSEED); block
    # tasks run in different workers, so partitioning must use a stable
    # hash. Numpy scalars normalize to Python values first: repr is dtype-
    # tagged (np.int64(5) vs np.int32(5)), and a join across sides with
    # different key widths must co-partition equal values.
    import zlib
    if isinstance(x, tuple):
        x = tuple(v.item() if hasattr(v, "item") else v for v in x)
    elif hasattr(x, "item"):
        x = x.item()
    return zlib.crc32(repr(x).encode())


def _hash_partition(block, key, n_out):
    if B.num_rows(block) == 0:
        empty = block
        return tuple(empty for _ in range(n_out))
    col = B.column_to_numpy(block.column(key))
    hashes = np.array([_stable_hash(x) % n_out for x in col])
    return tuple(block.take(np.nonzero(hashes == i)[0])
                 for i in range(n_out))


def _slice_task(block, start, end):
    out = B.slice_block(block, start, end)
    return out, BlockMeta(B.num_rows(out), B.size_bytes(out))


def zip_blocks(lb, rb):
    """Column-concat of two row-aligned blocks (right side wins on
    column-name collision) — THE zip merge, shared by this executor and
    the streaming zip stage so the two paths can never drift."""
    import pyarrow as pa
    cols = {**{n: lb.column(n) for n in lb.column_names},
            **{n: rb.column(n) for n in rb.column_names}}
    return pa.table(cols)


def _hash_partition_multi(block, keys, n_out):
    """Hash-partition on one or more key columns (joins, multi-key ops)."""
    if B.num_rows(block) == 0:
        return tuple(block for _ in range(n_out))
    cols = [B.column_to_numpy(block.column(k)) for k in keys]
    hashes = np.array([_stable_hash(tuple(c[i] for c in cols)) % n_out
                       for i in range(B.num_rows(block))])
    return tuple(block.take(np.nonzero(hashes == i)[0])
                 for i in range(n_out))


def _join_partition(keys, how, n_left, *parts):
    """Reduce side of a hash join: pandas merge of one co-partition."""
    import pandas as pd

    def side_df(blocks):
        df = B.concat(list(blocks)).to_pandas() if blocks else pd.DataFrame()
        if df.shape[1] == 0:
            # an empty SIDE (zero blocks / zero columns) still needs the
            # key columns for merge — and so outer joins emit the other
            # side's rows
            df = pd.DataFrame({k: pd.Series([], dtype="object")
                               for k in keys})
        return df

    ldf = side_df(parts[:n_left])
    rdf = side_df(parts[n_left:])
    out = ldf.merge(rdf, on=list(keys), how=how,
                    suffixes=("", "_right"))
    if how != "inner":
        # unmatched rows put NaN into int columns ONLY in partitions that
        # have misses — convert to pandas nullable dtypes so every
        # partition emits the same arrow schema (concat/sort need that)
        out = out.convert_dtypes()
    tbl = B.from_batch(out)
    return tbl, BlockMeta(B.num_rows(tbl), B.size_bytes(tbl))


class _ActorMapWorker:
    """Actor body for ActorPoolOp: builds the user's callable once, maps
    blocks through it (plus any fused downstream block fns) per call."""

    def __init__(self, fn_blob: bytes):
        import cloudpickle
        cls, args, kwargs, wrap = cloudpickle.loads(fn_blob)
        self._fn = cls(*args, **kwargs) if isinstance(cls, type) else cls
        self._wrap = wrap

    def map(self, fused_fns, block):
        block = self._wrap(self._fn, block)
        for fn in fused_fns:
            block = fn(block)
        return block, BlockMeta(B.num_rows(block), B.size_bytes(block))


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

_DEFAULT = object()


def _ray():
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    return ray_tpu


class Executor:
    """Executes a logical plan bottom-up, fusing BlockOp chains.

    Two modes:
      * `execute` — materialize every output pair (used by count/schema/
        materialize and inside exchanges, which are all-to-all barriers);
      * `execute_streaming` — a generator with BOUNDED in-flight tasks
        (ctx.max_tasks_in_flight): tasks are submitted as the consumer
        pulls, completed blocks yield as they finish, so read/map/consume
        overlap and at most `window` blocks wait in the object store
        (reference: _internal/execution/streaming_executor.py:52 +
        the memory-aware admission of streaming_executor_state.py:646).
    """

    def __init__(self, ctx: Optional[DataContext] = None):
        self.ctx = ctx or DataContext.get_current()
        # high-water mark of concurrently in-flight tasks (observable by
        # tests and stats)
        self.max_in_flight_seen = 0
        # ticks where store pressure shrank the submission window
        self.backpressure_events = 0
        # actor-pool scale up/down decisions (observable by tests/stats)
        self.autoscale_events: list[dict] = []

    @staticmethod
    def _store_pressured(ray) -> bool:
        from ..core import runtime as rt_mod
        from ..core.config import cfg
        rt = rt_mod.get_runtime_if_exists()
        store = getattr(rt, "store", None)
        if store is None:
            return False
        try:
            return (store.bytes_in_use()
                    > cfg.object_spilling_threshold * store.capacity())
        except Exception:
            return False

    def _peel(self, op: LogicalOp):
        """Split a plan top into (fused block fns, source node)."""
        chain: list[BlockOp] = []
        node = op
        while isinstance(node, BlockOp):
            chain.append(node)
            node = node.inputs[0]
        return [c.fn for c in reversed(chain)], node

    def execute(self, op: LogicalOp) -> list[tuple[Any, BlockMeta]]:
        """Returns [(block_ref, meta)] — metas are concrete. Barrier mode:
        everything is submitted at once (the results are materialized into
        a list anyway, so the streaming window would only serialize it)."""
        return list(self.execute_streaming(op, window=None))

    def execute_streaming(self, op: LogicalOp, window: int | object = _DEFAULT):
        """Yield (block_ref, meta) in PLAN ORDER as tasks finish, with at
        most `window` (default ctx.max_tasks_in_flight; None = unbounded)
        tasks in flight. Plan-order delivery keeps order-sensitive
        consumers (zip alignment, limit/take, seeded shuffles) exact while
        still overlapping read/map/consume."""
        ray = _ray()
        fused, node = self._peel(op)
        if isinstance(node, Read):
            remote_read = ray.remote(_run_read).options(num_returns=2)
            thunks = (
                (lambda rt=rt: remote_read.remote(rt, fused))
                for rt in node.read_tasks)
            yield from self._stream(thunks, window)
            return
        if isinstance(node, ActorPoolOp):
            yield from self._execute_actor_pool(node, fused, window)
            return
        if isinstance(node, InputData):
            base = node.refs_and_meta
        elif isinstance(node, Exchange):
            base = self._execute_exchange(node)   # all-to-all barrier
        else:
            raise TypeError(f"cannot execute {node!r}")
        if not fused:
            yield from base
            return
        remote_fused = ray.remote(_run_fused).options(num_returns=2)
        thunks = (
            (lambda ref=ref: remote_fused.remote(fused, ref))
            for ref, _ in base)
        yield from self._stream(thunks, window)

    def _execute_actor_pool(self, node: ActorPoolOp, fused, window):
        """Stream upstream blocks through a pool of stateful map actors,
        least-loaded dispatch, preserving plan order; the pool autoscales
        between node.size and node.max_size from queue depth inside the
        streaming loop (reference: autoscaler/default_autoscaler.py:26,
        try_trigger_scaling :50 over autoscaling_actor_pool.py metrics)."""
        ray = _ray()
        worker_cls = ray.remote(_ActorMapWorker)
        lo, hi = node.size, max(node.max_size, node.size)
        up_at = max(1, self.ctx.actor_pool_scale_up_queued)
        pool = [worker_cls.remote(node.fn_blob) for _ in range(lo)]
        RETIRED = float("inf")
        outstanding: list[float] = [0] * lo   # per-actor queued calls
        owner: dict[int, int] = {}            # submit seq -> actor index
        seq = {"n": 0}

        def active() -> list[int]:
            return [j for j, o in enumerate(outstanding) if o != RETIRED]

        def make_thunk(ref):
            def thunk():
                i = min(active(), key=outstanding.__getitem__)
                if outstanding[i] >= up_at and len(active()) < hi:
                    # every live actor is backed up: grow the pool
                    pool.append(worker_cls.remote(node.fn_blob))
                    outstanding.append(0)
                    i = len(pool) - 1
                    self.autoscale_events.append(
                        {"op": node.name, "event": "up",
                         "size": len(active())})
                k = seq["n"]
                seq["n"] += 1
                owner[k] = i
                outstanding[i] += 1
                return pool[i].map.options(num_returns=2).remote(fused, ref)
            return thunk

        try:
            upstream = self.execute_streaming(node.inputs[0], window=window)
            thunks = (make_thunk(ref) for ref, _ in upstream)
            for k, pair in enumerate(self._stream(thunks, window)):
                outstanding[owner.pop(k)] -= 1
                live = active()
                idle = [j for j in live if outstanding[j] == 0]
                if len(live) > lo and len(idle) > len(live) // 2:
                    # over half the pool idle: retire one actor down
                    # toward min (never below)
                    j = idle[-1]
                    outstanding[j] = RETIRED
                    self.autoscale_events.append(
                        {"op": node.name, "event": "down",
                         "size": len(active())})
                    try:
                        ray.kill(pool[j])
                    except Exception:
                        pass  # actor already dead
                yield pair
        finally:
            for j in active():
                try:
                    ray.kill(pool[j])
                except Exception:
                    pass  # actor already dead

    def _stream(self, thunks, window=_DEFAULT):
        """Bounded-window submission loop (the scheduling loop of the
        reference's StreamingExecutor, _scheduling_loop_step) with
        object-store backpressure: past the spill threshold, submission
        halves down to 1 in flight so consumption can drain the store
        before producers flood it (reference: the memory-aware admission
        of streaming_executor_state.py:646 select_operator_to_run)."""
        from collections import deque

        from .streaming import telemetry as tm

        ray = _ray()
        if window is _DEFAULT:
            window = max(1, self.ctx.max_tasks_in_flight)
        pending: deque = deque()         # (block_ref, meta_ref), plan order
        it = iter(thunks)
        exhausted = False
        while True:
            limit = window
            if window is not None and self._store_pressured(ray):
                limit = max(1, window // 2)
                self.backpressure_events += 1
            while not exhausted and (limit is None
                                     or len(pending) < limit):
                try:
                    thunk = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.append(thunk())
                # the dispatch-economy counter the streaming executor's
                # A/B reads: the task path pays one control dispatch
                # per block by construction
                tm.note_dispatches(1.0, "task")
            self.max_in_flight_seen = max(self.max_in_flight_seen,
                                          len(pending))
            if not pending:
                return
            # head-of-line: deliver strictly in plan order (later tasks
            # keep running in the window meanwhile)
            block_ref, meta_ref = pending.popleft()
            meta = ray.get(meta_ref)
            tm.note_blocks(1.0, "task")
            yield block_ref, meta

    def _resolve(self, pairs) -> list[tuple[Any, BlockMeta]]:
        ray = _ray()
        return [(block_ref, ray.get(meta_ref))
                for block_ref, meta_ref in pairs]

    # -- exchanges --------------------------------------------------------

    def _execute_exchange(self, node: Exchange):
        ray = _ray()
        kind = node.kwargs
        k = node.kind
        if k == "union":
            out = []
            for parent in node.inputs:
                out.extend(self.execute(parent))
            return out
        upstream = self.execute(node.inputs[0])
        if k == "limit":
            return self._limit(upstream, kind["n"])
        if k == "repartition" or k == "shuffle":
            shuffle = (k == "shuffle")
            n_out = kind.get("n") or max(1, len(upstream))
            seed = kind.get("seed") or 0
            split = ray.remote(_split_for_exchange).options(
                num_returns=n_out)
            parts = [split.remote(ref, n_out, shuffle, seed + i)
                     for i, (ref, _) in enumerate(upstream)]
            parts = [p if isinstance(p, list) else [p] for p in parts]
            combine = ray.remote(_combine_partition).options(num_returns=2)
            out = [combine.remote(shuffle, seed + 1000 + j,
                                  *[parts[i][j] for i in range(len(parts))])
                   for j in range(n_out)]
            return self._resolve(out)
        if k == "sort":
            return self._sort(upstream, kind["key"], kind["descending"])
        if k == "groupby":
            return self._groupby(upstream, kind["key"], kind["agg_fn"])
        if k == "zip":
            return self._zip(upstream, self.execute(node.inputs[1]))
        if k == "join":
            return self._join(upstream, self.execute(node.inputs[1]),
                              kind["on"], kind["how"],
                              kind.get("num_partitions"))
        raise ValueError(f"unknown exchange {k!r}")

    def _join(self, left, right, on, how, num_partitions=None):
        """Distributed hash join (reference: operators/join.py +
        hash_shuffle.py): both sides hash-partition on the key columns,
        co-partitions merge with pandas."""
        ray = _ray()
        keys = [on] if isinstance(on, str) else list(on)
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join how={how!r}")
        n_out = num_partitions or max(1, max(len(left), len(right)))
        part = ray.remote(_hash_partition_multi).options(num_returns=n_out)
        lparts = [part.remote(ref, keys, n_out) for ref, _ in left]
        rparts = [part.remote(ref, keys, n_out) for ref, _ in right]
        lparts = [p if isinstance(p, list) else [p] for p in lparts]
        rparts = [p if isinstance(p, list) else [p] for p in rparts]
        joiner = ray.remote(_join_partition).options(num_returns=2)
        out = []
        for j in range(n_out):
            lcol = [lparts[i][j] for i in range(len(lparts))]
            rcol = [rparts[i][j] for i in range(len(rparts))]
            out.append(joiner.remote(keys, how, len(lcol), *lcol, *rcol))
        return self._resolve(out)

    def _limit(self, upstream, n: int):
        ray = _ray()
        out, have = [], 0
        for ref, meta in upstream:
            if have >= n:
                break
            take = min(meta.rows, n - have)
            if take == meta.rows:
                out.append((ref, meta))
            else:
                sl = ray.remote(_slice_task).options(num_returns=2)
                b, m = sl.remote(ref, 0, take)
                out.append((b, ray.get(m)))
            have += take
        return out

    def _sort(self, upstream, key: str, descending: bool):
        ray = _ray()
        if not upstream:
            return upstream
        n_out = len(upstream)
        sampler = ray.remote(_sample_block)
        samples = np.concatenate(ray.get(
            [sampler.remote(ref, key, 20) for ref, _ in upstream]))
        if len(samples) == 0:
            return upstream
        qs = np.linspace(0, 100, n_out + 1)[1:-1]
        boundaries = np.percentile(samples, qs) if len(qs) else np.array([])
        if descending:
            boundaries = boundaries[::-1]
        part = ray.remote(_sort_and_partition).options(num_returns=n_out)
        parts = [part.remote(ref, key, descending, boundaries)
                 for ref, _ in upstream]
        parts = [p if isinstance(p, list) else [p] for p in parts]
        merge = ray.remote(_merge_sorted).options(num_returns=2)
        out = [merge.remote(key, descending,
                            *[parts[i][j] for i in range(len(parts))])
               for j in range(n_out)]
        return self._resolve(out)

    def _groupby(self, upstream, key: str, agg_fn):
        ray = _ray()
        n_out = max(1, len(upstream))
        part = ray.remote(_hash_partition).options(num_returns=n_out)
        parts = [part.remote(ref, key, n_out) for ref, _ in upstream]
        parts = [p if isinstance(p, list) else [p] for p in parts]

        def _agg_partition(kname, fn, *blocks):
            import pandas as pd
            df = B.concat(list(blocks)).to_pandas()
            if len(df) == 0:
                out = df
            else:
                # agg_fn returns a final frame including the key column
                out = fn(df.groupby(kname, sort=True))
            tbl = B.from_batch(out)
            return tbl, BlockMeta(B.num_rows(tbl), B.size_bytes(tbl))

        agg = ray.remote(_agg_partition).options(num_returns=2)
        out = [agg.remote(key, agg_fn,
                          *[parts[i][j] for i in range(len(parts))])
               for j in range(n_out)]
        return self._resolve(out)

    def _zip(self, left, right):
        """Align row ranges then column-concat (reference: zip operator)."""
        ray = _ray()
        lrows = sum(m.rows for _, m in left)
        rrows = sum(m.rows for _, m in right)
        if lrows != rrows:
            raise ValueError(f"zip requires equal row counts ({lrows} vs "
                             f"{rrows})")

        def _fetch_concat(*blocks):
            return B.concat(list(blocks))

        def _zip_all(lb, rb):
            tbl = zip_blocks(lb, rb)
            return tbl, BlockMeta(B.num_rows(tbl), B.size_bytes(tbl))

        cat = ray.remote(_fetch_concat)
        z = ray.remote(_zip_all).options(num_returns=2)
        lref = cat.remote(*[r for r, _ in left])
        rref = cat.remote(*[r for r, _ in right])
        b, m = z.remote(lref, rref)
        return [(b, ray.get(m))]


def iter_blocks(pairs) -> Iterator[B.Block]:
    """Stream concrete blocks in order (tasks run ahead concurrently)."""
    ray = _ray()
    for ref, _ in pairs:
        yield ray.get(ref)
