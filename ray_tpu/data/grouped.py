"""GroupedData aggregations (reference parity: python/ray/data/grouped_data.py
— count/sum/min/max/mean/std plus map_groups), executed as a hash-partition
exchange + per-partition pandas aggregation."""
from __future__ import annotations

from typing import Callable

from .dataset import Dataset
from .executor import Exchange


def _agg_named(ops: list[tuple[str, str]]):
    """ops: [(column, op_name)] -> fn(groupby) -> DataFrame."""
    def fn(gb):
        spec = {}
        for col, op in ops:
            spec[f"{op}({col})"] = (col, op)
        return gb.agg(**spec).reset_index()
    return fn


def _count_fn(gb):
    return gb.size().to_frame("count()").reset_index()


class GroupedData:
    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _exchange(self, agg_fn) -> Dataset:
        return Dataset(Exchange([self._ds._plan], "groupby", key=self._key,
                                agg_fn=agg_fn), self._ds._ctx)

    def count(self) -> Dataset:
        return self._exchange(_count_fn)

    def sum(self, col: str) -> Dataset:
        return self._exchange(_agg_named([(col, "sum")]))

    def min(self, col: str) -> Dataset:
        return self._exchange(_agg_named([(col, "min")]))

    def max(self, col: str) -> Dataset:
        return self._exchange(_agg_named([(col, "max")]))

    def mean(self, col: str) -> Dataset:
        return self._exchange(_agg_named([(col, "mean")]))

    def std(self, col: str) -> Dataset:
        return self._exchange(_agg_named([(col, "std")]))

    def aggregate(self, **named_ops: tuple[str, str]) -> Dataset:
        """aggregate(total=("value", "sum"), lo=("value", "min"))"""
        def fn(gb):
            return gb.agg(**named_ops).reset_index()
        return self._exchange(fn)

    def map_groups(self, fn: Callable) -> Dataset:
        """fn(pandas.DataFrame) -> DataFrame, applied per group."""
        def agg(gb):
            import pandas as pd
            frames = [fn(g) for _, g in gb]
            out = pd.concat(frames) if frames else pd.DataFrame()
            # match the exchange contract: reset_index is applied by caller,
            # so hand back something with a trivial index
            return out.reset_index(drop=True)
        return self._exchange(agg)
