"""Dataset creation (reference parity: python/ray/data/read_api.py —
range/from_items/from_numpy/from_pandas/from_arrow and file readers; file
reads become one read task per file/fragment executed as runtime tasks)."""
from __future__ import annotations

import builtins
import functools
from typing import Optional

import numpy as np

from . import block as B
from .context import DataContext
from .dataset import Dataset
from .executor import InputData, Read


def _n_blocks(n: Optional[int]) -> int:
    return n or DataContext.get_current().read_default_num_blocks


# -- in-memory sources ------------------------------------------------------

def _range_task(start, stop):
    return B.from_batch({"id": np.arange(start, stop, dtype=np.int64)})


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    k = min(_n_blocks(override_num_blocks), max(1, n))
    bounds = np.linspace(0, n, k + 1).astype(int)
    tasks = [functools.partial(_range_task, bounds[i], bounds[i + 1])
             for i in builtins.range(k)]
    return Dataset(Read(tasks, name="ReadRange"))


def from_items(items: list, *,
               override_num_blocks: Optional[int] = None) -> Dataset:
    k = min(_n_blocks(override_num_blocks), max(1, len(items)))
    bounds = np.linspace(0, len(items), k + 1).astype(int)
    tasks = [functools.partial(B.from_items, items[bounds[i]:bounds[i + 1]])
             for i in builtins.range(k)]
    return Dataset(Read(tasks, name="FromItems"))


def from_numpy(arr: np.ndarray, column: str = B.TENSOR_COLUMN,
               *, override_num_blocks: Optional[int] = None) -> Dataset:
    k = min(_n_blocks(override_num_blocks), max(1, len(arr)))
    chunks = np.array_split(arr, k)
    tasks = [functools.partial(B.from_numpy, c, column) for c in chunks]
    return Dataset(Read(tasks, name="FromNumpy"))


def from_pandas(df) -> Dataset:
    import pyarrow as pa
    tbl = pa.Table.from_pandas(df, preserve_index=False)
    return from_arrow(tbl)


def from_huggingface(hf_dataset, *, override_num_blocks: int | None = None
                     ) -> Dataset:
    """A huggingface ``datasets.Dataset`` -> ray_tpu Dataset (reference:
    ray.data.from_huggingface). HF datasets are arrow-backed; blocks come
    straight from the underlying table, split for parallelism."""
    import ray_tpu
    from .executor import BlockMeta, InputData
    if getattr(hf_dataset, "_indices", None) is not None:
        # select()/shuffle() views keep an index mapping over the raw
        # table; materialize it or we'd ship the WRONG rows
        hf_dataset = hf_dataset.flatten_indices()
    tbl = hf_dataset.data.table if hasattr(hf_dataset, "data") else None
    if tbl is None:
        raise TypeError(
            f"expected a datasets.Dataset, got {type(hf_dataset).__name__}")
    n = min(_n_blocks(override_num_blocks), max(1, tbl.num_rows))
    pairs = []
    import builtins
    step = max(1, (tbl.num_rows + n - 1) // n)  # 0-row datasets: no blocks
    for start in builtins.range(0, tbl.num_rows, step):  # range is shadowed

        # slice() is zero-copy; only the block being shipped is combined
        block = tbl.slice(start, min(step, tbl.num_rows - start))
        block = block.combine_chunks()
        ref = ray_tpu.put(block)
        pairs.append((ref, BlockMeta(block.num_rows, block.nbytes)))
    return Dataset(InputData(pairs))


def from_arrow(table) -> Dataset:
    import ray_tpu
    from .executor import BlockMeta
    ref = ray_tpu.put(table)
    return Dataset(InputData(
        [(ref, BlockMeta(table.num_rows, table.nbytes))]))


# -- file sources -----------------------------------------------------------
#
# Paths resolve through pyarrow filesystems (util/fs.py), so every reader
# accepts local paths, globs, directories, and gs://, s3://, file:// URIs,
# or an explicit `filesystem=` (reference:
# data/datasource/file_based_datasource.py + path_util.py). The resolved
# filesystem object is pickled into each read task, so workers open the
# file on whatever store it lives on.

def _read_parquet_task(fs_, path):
    import pyarrow.parquet as pq
    return pq.read_table(path, filesystem=fs_)


def _read_csv_task(fs_, path):
    import pyarrow.csv as pcsv
    with fs_.open_input_stream(path) as f:
        return pcsv.read_csv(f)


def _read_json_task(fs_, path):
    import io

    import pandas as pd
    import pyarrow as pa
    from ..util.fs import read_bytes
    raw = read_bytes(fs_, path)
    lines = (path.endswith((".jsonl", ".ndjson"))
             or not raw.lstrip().startswith(b"["))
    df = pd.read_json(io.BytesIO(raw), lines=lines)
    return pa.Table.from_pandas(df, preserve_index=False)


def _read_text_task(fs_, path):
    from ..util.fs import read_bytes
    lines = read_bytes(fs_, path).decode("utf-8").splitlines()
    return B.from_batch({"text": lines})


def _file_dataset(paths, filesystem, task_fn, name) -> Dataset:
    from ..util.fs import expand_paths
    fs_, files = expand_paths(paths, filesystem)
    return Dataset(Read([functools.partial(task_fn, fs_, f) for f in files],
                        name=name))


def read_parquet(paths, *, filesystem=None, **_ignored) -> Dataset:
    return _file_dataset(paths, filesystem, _read_parquet_task,
                         "ReadParquet")


def read_csv(paths, *, filesystem=None, **_ignored) -> Dataset:
    return _file_dataset(paths, filesystem, _read_csv_task, "ReadCSV")


def read_json(paths, *, filesystem=None, **_ignored) -> Dataset:
    return _file_dataset(paths, filesystem, _read_json_task, "ReadJSON")


def read_text(paths, *, filesystem=None, **_ignored) -> Dataset:
    return _file_dataset(paths, filesystem, _read_text_task, "ReadText")


def _read_binary_task(fs_, path):
    from ..util.fs import read_bytes
    return B.from_items([{"bytes": read_bytes(fs_, path), "path": path}])


def read_binary_files(paths, *, filesystem=None, **_ignored) -> Dataset:
    """One row per file: {bytes, path} (reference:
    data/read_api.py read_binary_files)."""
    return _file_dataset(paths, filesystem, _read_binary_task,
                         "ReadBinary")


def _read_numpy_task(fs_, path):
    import io

    import numpy as np
    from ..util.fs import read_bytes
    arr = np.load(io.BytesIO(read_bytes(fs_, path)))
    return B.from_numpy(np.asarray(arr), B.TENSOR_COLUMN)


def read_numpy(paths, *, filesystem=None, **_ignored) -> Dataset:
    """.npy files -> tensor-column rows (reference: read_numpy)."""
    return _file_dataset(paths, filesystem, _read_numpy_task, "ReadNumpy")
