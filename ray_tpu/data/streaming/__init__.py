"""ray_tpu.data.streaming — the streaming physical executor.

Operators become long-lived stage actors (one ``run_loop`` call for the
whole pipeline) connected by sealed-ring shm channels with credit-based
backpressure: ~zero control dispatches per block in steady state,
bounded memory under skew, plan-order delivery bit-identical to the
task executor. Sits behind the existing ``Dataset`` API via
``DataContext.streaming_executor`` ("auto" by default); exchanges the
pipeline can't stream (shuffle/sort/groupby/...) fall back to the task
executor at a clean plan-split boundary.
"""
from .executor import (ChannelShardFeed, PipelineFeed, StreamingPipeline,
                       compile_plan)
from .telemetry import metrics_summary

__all__ = ["ChannelShardFeed", "PipelineFeed", "StreamingPipeline",
           "compile_plan", "metrics_summary"]
