"""Block transport for streaming pipelines: sealed-ring edges between
stage actors.

An *edge* connects the P workers of one stage to the C consumers of the
next: P x C independent (data, ack) ring channels (``dag/channel.py``
protocol — ids never reused, credit-based backpressure, one shared
pipeline-wide stop flag). Messages are ``(block_idx, block)`` pairs;
``block_idx`` is the block's position in plan order, which is what lets
a downstream consumer restore the task executor's plan-order delivery
no matter which worker produced the block.

Senders:

* ``stripe`` — block ``idx`` goes to consumer ``idx % C``. Deterministic,
  so an ordered receiver knows exactly which ring its next block arrives
  on (zero reordering state). Used everywhere order is cheap to keep:
  source stages, width-1 stages.
* ``steal`` — block goes to any consumer ring with free credit
  (round-robin preference). Push-mode work stealing: a slow consumer's
  ring fills and traffic flows to the others; when every ring is full the
  sender parks in ONE multi-oid wait over every ring's retiring ack plus
  the stop flag. Used ONLY for ``streaming_split`` shards (sinks hold no
  downstream credit, so stealing cannot form a cycle there).

Receivers:

* ``stripe`` — consumer slot ``c`` owns idxs ``c (mod C)`` and reads
  them in increasing order; idx ``n`` always sits on ring ``n % P``
  (the stripe-sender contract: producer ``p`` owns idxs ``p (mod P)``).
  In-order delivery with immediate acks and no buffering. This is the
  only ordered mode ON PURPOSE: every stage worker processing its own
  idx subsequence in order is what makes the pipeline deadlock-free —
  the worker holding the globally next undelivered idx has already had
  all its earlier outputs delivered and acked, so it always owns output
  credit. (A work-stealing feed with delivery-deferred acks can park a
  worker on output credit while the next-needed block sits unread in
  its input ring — a permanent cycle.)
* ``any`` — first available block from any ring, round-robin fair,
  immediate acks (streaming_split shards: arrival order is fine, and a
  shard is a sink — it holds no downstream credit, so stealing cannot
  cycle).

End-of-stream rides ``dag.channel.seal_eos`` — a per-ring marker object
carrying the final message count, sealed WITHOUT consuming ring credit
(an idle consumer's full ring must never block another shard's EOS). A
ring is exhausted when its EOS is sealed and the cursor reached the
count; since block idxs are contiguous 0..N-1 per edge, the first
missing idx ends an ordered stream.
"""
from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

from ...core import flight
from ...core import stacks
from ...core.ids import ObjectID
from ...dag.channel import (ChannelClosed, RingWriter, drain_stale_slots,
                            eos_oid, read_eos, send_ack, signal_stop,
                            slot_oid)
from . import telemetry as tm

_WAIT_SLICE_MS = 500


@dataclasses.dataclass(frozen=True)
class EdgeSpec:
    """Picklable wiring for one stage-to-stage edge: the id bases ARE
    the channel (ships to stage actors as a plain value, the
    RolloutQueueSpec pattern)."""

    bases: tuple     # P*C data id bases, row-major [p * consumers + c]
    stop: bytes      # pipeline-wide stop flag oid bytes (shared)
    producers: int
    consumers: int
    ring: int        # per-(p,c) credit window, in blocks

    @classmethod
    def create(cls, producers: int, consumers: int, ring: int,
               stop: bytes) -> "EdgeSpec":
        return cls(bases=tuple(os.urandom(16)
                               for _ in range(producers * consumers)),
                   stop=stop, producers=producers, consumers=consumers,
                   ring=max(1, ring))

    def base(self, p: int, c: int) -> bytes:
        return self.bases[p * self.consumers + c]

    def stop_oid(self) -> ObjectID:
        return ObjectID(self.stop[:ObjectID.SIZE])


class BlockSender:
    """Producer end of an edge for ONE stage worker: fans blocks out
    over this worker's C rings."""

    def __init__(self, store, edge: EdgeSpec, producer_idx: int,
                 mode: str = "stripe"):
        if mode not in ("stripe", "steal"):
            raise ValueError(f"unknown sender mode {mode!r}")
        self.edge = edge
        self.mode = mode
        self.store = store
        stop = edge.stop_oid()
        self._writers = [RingWriter(store, edge.base(producer_idx, c),
                                    stop, edge.ring)
                         for c in range(edge.consumers)]
        self._rr = 0   # steal mode: next consumer favoured
        self._stop = stop

    def closed(self) -> bool:
        return self.store.contains(self._stop)

    def send(self, idx: int, block: Any,
             timeout_s: Optional[float] = None) -> None:
        if self.mode == "stripe":
            w = self._writers[idx % self.edge.consumers]
            if not w.credit_ready():
                tm.note_backpressure()
            w.write((idx, block), timeout_s)
            return
        # steal: first consumer ring with credit, rotating from the last
        # one served so a fast consumer can't monopolize the stream
        n = len(self._writers)
        order = [(self._rr + k) % n for k in range(n)]
        for c in order:
            if self._writers[c].credit_ready():
                self._rr = (c + 1) % n
                self._writers[c].write((idx, block), timeout_s)
                return
        # every ring full: ONE multi-oid park over each ring's retiring
        # ack + the stop flag, then retry whichever freed
        tm.note_backpressure()
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while True:
            acks = [w.pending_ack_oid() for w in self._writers]
            oids = [a for a in acks if a is not None] + [self._stop]
            slice_ms = _WAIT_SLICE_MS
            if deadline is not None:
                from ...core.object_store import GetTimeoutError
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise GetTimeoutError(
                        "timed out waiting for consumer credit")
                slice_ms = max(1, min(slice_ms, int(remain * 1000)))
            sealed = self.store.wait_sealed(oids, 1, slice_ms)
            if sealed[-1]:
                raise ChannelClosed("pipeline stop flag sealed")
            for c in order:
                if self._writers[c].credit_ready():
                    self._rr = (c + 1) % n
                    self._writers[c].write((idx, block), timeout_s)
                    return

    def finish(self, timeout_s: Optional[float] = None) -> None:
        """End the stream on every ring: TWO phases, not a per-ring
        RingWriter.finish() loop. Every consumer's EOS must seal before
        ANY consumer's acks are awaited — a sequential finish parks on
        consumer 0's trailing acks while consumer 1 has no EOS yet, so
        split shards consumed in reverse order would deadlock (the
        documented any-order contract). After both phases the edge owns
        zero store objects."""
        from ...dag.channel import seal_eos
        for w in self._writers:
            seal_eos(self.store, w.base, w.seq)
        for w in self._writers:
            w.drain_trailing(timeout_s)

    def sweep(self) -> None:
        """Teardown (stop sealed / error exit): delete this worker's
        unconsumed slots, trailing acks and EOS markers."""
        for w in self._writers:
            drain_stale_slots(self.store, [w.base, w.ack_base],
                              w.seq - self.edge.ring - 1,
                              w.seq + self.edge.ring, eos=True)


class _RingCursor:
    """Consumer-side view of one (producer, consumer) ring."""

    __slots__ = ("base", "ack_base", "seq", "count")

    def __init__(self, base: bytes):
        from ...dag.channel import ack_base_for
        self.base = base
        self.ack_base = ack_base_for(base)
        self.seq = 0
        self.count: Optional[int] = None   # final count once EOS observed

    def exhausted(self) -> bool:
        return self.count is not None and self.seq >= self.count


class BlockReceiver:
    """Consumer end of an edge for ONE consumer slot. ``mode`` is
    "stripe" / "reorder" (ordered delivery, C==1) or "any" (arrival
    order)."""

    def __init__(self, store, edge: EdgeSpec, consumer_idx: int,
                 mode: str = "stripe", zero_copy: Optional[bool] = None):
        if mode not in ("stripe", "any"):
            raise ValueError(f"unknown receiver mode {mode!r}")
        self.edge = edge
        self.mode = mode
        self.store = store
        self.zero_copy = zero_copy
        self.stop = edge.stop_oid()
        self._rings = [_RingCursor(edge.base(p, consumer_idx))
                       for p in range(edge.producers)]
        # stripe: this consumer owns idxs consumer_idx (mod C), in order
        self._next = consumer_idx
        self._step = edge.consumers
        self._rr = 0            # any: round-robin start
        self._delivered = 0
        for rc in self._rings:
            stacks.note_producer(flight.lo48(rc.ack_base))  # acks seal here

    # -- shared helpers ------------------------------------------------- #

    def _observe_eos(self, rc: _RingCursor) -> None:
        if rc.count is None:
            n = read_eos(self.store, rc.base)
            if n is not None:
                rc.count = n
                # EOS ack: tells the producer its marker was seen, so IT
                # can delete it (producer owns every object it created;
                # a consumer-side delete would race other observers)
                from ...dag.channel import EOS_SEQ
                send_ack(self.store, rc.ack_base, EOS_SEQ)

    def _read(self, rc: _RingCursor, ack: bool) -> Any:
        """Consume rc's next (already sealed) slot and delete it."""
        oid = slot_oid(rc.base, rc.seq)
        val = self.store.get(oid, timeout_ms=5000, zero_copy=self.zero_copy)
        flight.evt(flight.CHAN_WAKE, flight.lo48(rc.base), rc.seq)
        self.store.delete(oid)
        if ack:
            send_ack(self.store, rc.ack_base, rc.seq)
        rc.seq += 1
        return val

    def _wait(self, oids: list, timeout_s, deadline, on_idle) -> list:
        slice_ms = _WAIT_SLICE_MS
        if deadline is not None:
            from ...core.object_store import GetTimeoutError
            remain = deadline - time.monotonic()
            if remain <= 0:
                raise GetTimeoutError(
                    "timed out waiting for a pipeline block")
            slice_ms = max(1, min(slice_ms, int(remain * 1000)))
        sealed = self.store.wait_sealed(oids, 1, slice_ms)
        if not any(sealed) and on_idle is not None:
            on_idle()
        return sealed

    def done(self) -> bool:
        return all(rc.exhausted() for rc in self._rings)

    # -- delivery ------------------------------------------------------- #

    def next_block(self, timeout_s: Optional[float] = None,
                   on_idle=None) -> Optional[tuple[int, Any]]:
        """The next ``(idx, block)`` pair, or None at end of stream.
        Stripe delivers this consumer's idx subsequence in ascending
        order; "any" delivers arrival order. Raises ChannelClosed when
        the pipeline stop flag seals, GetTimeoutError past the deadline;
        ``on_idle`` runs between wait slices (the driver's stage-death
        probe)."""
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        if self.mode == "stripe":
            return self._next_stripe(timeout_s, deadline, on_idle)
        return self._next_any(timeout_s, deadline, on_idle)

    def _next_stripe(self, timeout_s, deadline, on_idle):
        n = self._next
        rc = self._rings[n % len(self._rings)]
        # contiguity: global idxs are 0..N-1, so if ring (n mod P) is
        # exhausted before yielding n, idx n does not exist anywhere —
        # this consumer's stream is complete
        while True:
            self._observe_eos(rc)
            if rc.exhausted():
                self._observe_all_eos(timeout_s, deadline, on_idle)
                return None
            oids = [slot_oid(rc.base, rc.seq)]
            if rc.count is None:
                oids.append(eos_oid(rc.base))
            oids.append(self.stop)
            sealed = self._wait(oids, timeout_s, deadline, on_idle)
            if sealed[0]:
                val = self._read(rc, ack=True)
                self._next = n + self._step
                self._delivered += 1
                return val
            if sealed[-1]:
                raise ChannelClosed("pipeline stop flag sealed")
            # middle oid (EOS) sealed, or slice expired: loop re-checks

    def _next_any(self, timeout_s, deadline, on_idle):
        n = len(self._rings)
        while True:
            live_idx = []
            oids = []
            for i, rc in enumerate(self._rings):
                self._observe_eos(rc)
                if rc.exhausted():
                    continue
                live_idx.append(i)
                oids.append(slot_oid(rc.base, rc.seq))
                if rc.count is None:
                    oids.append(eos_oid(rc.base))
            if not live_idx:
                return None
            oids.append(self.stop)
            sealed = self._wait(oids, timeout_s, deadline, on_idle)
            ready = []
            pos = 0
            for i in live_idx:
                if sealed[pos]:
                    ready.append(i)
                pos += 1 if self._rings[i].count is not None else 2
            if ready:
                i = min(ready, key=lambda j: (j - self._rr) % n)
                self._rr = (i + 1) % n
                val = self._read(self._rings[i], ack=True)
                self._delivered += 1
                return val
            if sealed[-1]:
                raise ChannelClosed("pipeline stop flag sealed")

    def _observe_all_eos(self, timeout_s, deadline, on_idle) -> None:
        """Stripe end-of-stream pass: every producer's finish() parks on
        its EOS ack, so the rings the stripe cursor never returned to
        still need their markers observed and acked. By the time idx n
        is known missing, every producer has delivered its last block
        and is sealing (or has sealed) EOS — this completes promptly."""
        for rc in self._rings:
            while rc.count is None:
                self._observe_eos(rc)
                if rc.count is not None:
                    break
                sealed = self._wait([eos_oid(rc.base), self.stop],
                                    timeout_s, deadline, on_idle)
                if sealed[1] and not sealed[0]:
                    raise ChannelClosed("pipeline stop flag sealed")

    # -- introspection / teardown --------------------------------------- #

    def depth(self) -> int:
        """Sealed-but-unread blocks across this consumer's rings
        (bounded probe: ring credit per producer). Telemetry only."""
        oids = [slot_oid(rc.base, rc.seq + k)
                for rc in self._rings if not rc.exhausted()
                for k in range(self.edge.ring)]
        if not oids:
            return 0
        return len(self.store.wait_sealed_indices(oids, 0, 0))

    def sweep(self) -> None:
        """Teardown sweep around every cursor: unread slots (credit
        bounds them to the window), this consumer's trailing acks and
        EOS markers."""
        for rc in self._rings:
            drain_stale_slots(self.store, [rc.base, rc.ack_base],
                              rc.seq - self.edge.ring - 1,
                              rc.seq + self.edge.ring, eos=True)


def stop_pipeline(store, edge_or_stop) -> None:
    """Seal the shared stop flag: every parked read/credit wait in the
    pipeline wakes with ChannelClosed."""
    stop = (edge_or_stop.stop_oid()
            if isinstance(edge_or_stop, EdgeSpec) else edge_or_stop)
    signal_stop(store, stop)
