"""Streaming physical executor: compile a logical plan into stage
actors wired by sealed-ring edges, drive it from the consumer side.

The Ray Data streaming_executor.py analog, rebuilt on the substrate PRs
5/6 proved out (sealed channels, credit backpressure, one long-lived
actor call per worker) instead of per-block tasks:

* ``compile_plan`` walks the same logical plan the task executor runs
  and splits it into stages: fused block-op chains ride whichever stage
  produces their input (a map/filter/flat_map pipeline still costs ZERO
  extra stages), ``ActorPoolOp`` becomes a fixed-width pool stage,
  ``repartition``/``zip`` become width-1 stages, and any other exchange
  (shuffle/sort/groupby/limit/union/join) is a **plan split**: the
  subtree below it runs on the task executor (all-to-all barriers want
  task semantics) and its materialized blocks feed the pipeline as a
  source.
* ``StreamingPipeline`` owns a run: it resolves plan-split sources,
  mints the edge id bases and the pipeline-wide stop flag, spawns the
  stage actors (ONE ``run_loop`` call each — the only control
  dispatches of the run, counter-verified via rtpu_data_*), and
  iterates the sink edge. Block payloads never touch the control plane:
  producer seals shm slot, consumer futex-wakes, zero-copy read.
* Teardown: the driver seals the stop flag; every parked worker wakes
  with ChannelClosed, sweeps its channel windows and exits, and the
  store returns to its pre-pipeline object count (the PR 5/6 contract).
  A stage worker that dies mid-run fails its run_loop ref; the driver's
  idle probe (every wait slice) surfaces the original error promptly
  and tears the rest down.

Delivery order matches the task executor's plan-order contract, so
results are bit-identical across the supported op matrix — the
``streaming_executor="auto"`` default can sit behind the existing
Dataset API without consumers noticing anything but the dispatch bill.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

from ...core import flight
from ...core.ids import ObjectID
from ...dag.channel import ChannelClosed, signal_stop
from . import telemetry as tm
from .channels import BlockReceiver, EdgeSpec
from .stage import StageSpec, run_stage_loop


class _StageDraft:
    """Driver-side stage record before edges/widths are final."""

    __slots__ = ("kind", "width", "fused", "payload", "ins")

    def __init__(self, kind: str, width: Optional[int], fused: list,
                 payload: Any, ins: list):
        self.kind = kind
        self.width = width
        self.fused = fused
        self.payload = payload
        self.ins = ins


def compile_plan(plan, ctx) -> Optional[list]:
    """Logical plan -> ordered stage drafts (last = sink producer), or
    None when streaming buys nothing (a bare materialized block list)."""
    from ..executor import (ActorPoolOp, BlockOp, Exchange, InputData,
                            Read)

    stages: list[_StageDraft] = []

    def peel(op):
        chain = []
        node = op
        while isinstance(node, BlockOp):
            chain.append(node)
            node = node.inputs[0]
        return [c.fn for c in reversed(chain)], node

    def build(node) -> int:
        fused, src = peel(node)
        if isinstance(src, Read):
            stages.append(_StageDraft("source", None, fused,
                                      ("tasks", src.read_tasks), []))
        elif isinstance(src, InputData):
            stages.append(_StageDraft("source", None, fused,
                                      ("pairs", src.refs_and_meta), []))
        elif isinstance(src, ActorPoolOp):
            up = build(src.inputs[0])
            # fixed-width pool at the pool's MAX size (the worker-budget
            # clamp in start() shrinks it on small clusters): streaming
            # has no queue-depth autoscaler, and an idle stage worker
            # costs a parked futex wait, not a core — starting at min
            # would silently forfeit the (min,max) pool's throughput
            width = max(1, getattr(src, "max_size", None) or src.size)
            stages.append(_StageDraft("pool", width, fused,
                                      src.fn_blob, [up]))
        elif isinstance(src, Exchange) and src.kind == "repartition" \
                and src.kwargs.get("n"):
            up = build(src.inputs[0])
            stages.append(_StageDraft("repartition", 1, fused,
                                      int(src.kwargs["n"]), [up]))
        elif isinstance(src, Exchange) and src.kind == "zip":
            left = build(src.inputs[0])
            right = build(src.inputs[1])
            stages.append(_StageDraft("zip", 1, fused, None,
                                      [left, right]))
        else:
            # plan split: run the subtree on the task executor, feed its
            # materialized blocks in as a source
            stages.append(_StageDraft("source", None, fused,
                                      ("plan", src), []))
        return len(stages) - 1

    build(plan)
    if len(stages) == 1 and not stages[0].fused:
        kind = stages[0].payload[0]
        if kind in ("pairs", "plan"):
            # no streaming op anywhere: the task executor (or a plain
            # ref iteration) already does this with nothing to amortize
            return None
    return stages


def _local_store():
    from ...core import runtime as rt_mod
    rt = rt_mod.get_runtime_if_exists()
    return getattr(rt, "store", None)


_active_lock = threading.Lock()
_active_workers = 0    # stage workers held by LIVE pipelines in this
#                        driver. guarded by: _active_lock


def _pool_slots() -> int:
    """Total worker processes the pool can run: CPU + 4 per node, minus
    one kept spare for foreign tasks."""
    try:
        import ray_tpu as ray
        return int(ray.cluster_resources().get("CPU", 2)) + 3
    except Exception:
        return 5


def _try_acquire_workers(n: int) -> bool:
    """Atomically claim n worker slots against the live-pipeline total
    (check-then-acquire in ONE lock hold: two pipelines starting
    concurrently must never both see the full budget)."""
    global _active_workers
    total = _pool_slots()
    with _active_lock:
        if _active_workers + n > total:
            return False
        _active_workers += n
        return True


def _release_workers(n: int) -> None:
    global _active_workers
    with _active_lock:
        _active_workers = max(0, _active_workers - n)


def worker_budget() -> int:
    """How many MORE stage workers can run concurrently right now: each
    node's pool spawns at most CPU + 4 worker processes, every stage
    worker occupies one for the whole run, and workers held by other
    live pipelines started from this driver (concurrent or NESTED
    Dataset iteration) are already spoken for."""
    with _active_lock:
        return _pool_slots() - _active_workers


class StreamingPipeline:
    """One streaming run: stage actors + edges + the sink. Create per
    consumption (pipelines are single-shot; a second epoch is a second
    pipeline)."""

    def __init__(self, drafts: list, ctx, consumers: int = 1,
                 split: bool = False):
        self._drafts = drafts
        self._ctx = ctx
        self._consumers = max(1, consumers)
        self._split = split
        self._started = False
        self._shut = False
        self._held_workers = 0
        self._sinks_done = 0   # guarded by: self._probe_lock
        self._probe_lock = threading.Lock()
        self._loop_refs: list = []
        self._hold: list = []    # materialized plan-split pairs (lifetime)
        self._recv: Optional[BlockReceiver] = None
        self._store = None
        self._ray = None
        self._stop: Optional[bytes] = None
        self.sink_edge: Optional[EdgeSpec] = None
        self.sink_mode = "stripe"

    # -- build ----------------------------------------------------------- #

    def start(self) -> "StreamingPipeline":
        if self._started:
            return self
        import ray_tpu as ray
        self._ray = ray
        store = _local_store()
        if store is None:
            raise RuntimeError(
                "streaming executor needs an initialized cluster with a "
                "shared shm object store (local_mode has none)")
        self._store = store
        self._stop = os.urandom(16)
        ctx = self._ctx
        drafts = self._drafts

        # resolve sources: plan splits materialize HERE (the all-to-all
        # barrier), widths become concrete
        resolved: list[tuple] = []   # (kind, payload) per stage
        for d in drafts:
            if d.kind != "source":
                resolved.append((d.kind, d.payload))
                continue
            kind, items = d.payload
            if kind == "plan":
                from ..executor import Executor
                pairs = Executor(ctx).execute(items)
                self._hold.append(pairs)
                kind, items = "refs", [ref for ref, _ in pairs]
            elif kind == "pairs":
                self._hold.append(items)
                kind, items = "refs", [ref for ref, _ in items]
            resolved.append((kind, items))
        widths = []
        for d, (kind, items) in zip(drafts, resolved):
            if d.kind == "source":
                widths.append(max(1, min(ctx.streaming_source_workers,
                                         len(items) or 1)))
            else:
                widths.append(d.width)
        # worker-pool budget: each node spawns at most CPU + 4 worker
        # processes, and every stage worker occupies one for the whole
        # run — a pipeline wider than the pool would park forever on
        # loops the scheduler can never start. Clamp the widest stages
        # down (width is a throughput knob, never a correctness one),
        # keeping one slot spare for foreign tasks.
        # clamp-and-claim loop: the budget snapshot and the claim must
        # agree, and another pipeline may grab slots between them —
        # retry the clamp against the fresh budget until the atomic
        # claim lands (or nothing is claimable even at width 1)
        base_widths = list(widths)
        while True:
            budget = worker_budget()
            widths = list(base_widths)
            while sum(widths) > budget:
                i = max(range(len(widths)), key=widths.__getitem__)
                if widths[i] <= 1:
                    break
                widths[i] -= 1
            if sum(widths) > budget:
                # even width-1 stages outnumber the FREE worker slots:
                # some run_loop could never be scheduled and its
                # consumers would park forever. Fail loudly — "auto"
                # plans this wide never reach here (the factory falls
                # back to the task executor)
                raise RuntimeError(
                    f"streaming pipeline needs {sum(widths)} concurrent "
                    f"stage workers but only {budget} worker slots are "
                    f"free (other live pipelines hold the rest); raise "
                    f"num_cpus or set "
                    f"DataContext.streaming_executor='off'")
            if _try_acquire_workers(sum(widths)):
                self._held_workers = sum(widths)
                break

        try:
            self._wire_and_spawn(ray, drafts, resolved, widths, ctx)
        except BaseException:
            # a failure past the slot claim (an unpicklable user fn,
            # spawn error) must not strand what already exists: release
            # the budget, wake any already-spawned loop via the stop
            # flag, and reap it — shutdown() does all three
            self._started = True
            try:
                self.shutdown(timeout_s=5.0)
            except Exception:
                pass  # best-effort unwind; the original error wins
            raise
        self._started = True
        return self

    def _wire_and_spawn(self, ray, drafts, resolved, widths, ctx) -> None:
        import cloudpickle

        # edges: every stage feeds exactly one consumer (zip consumes
        # two producers); the last stage feeds the sink
        consumer_of = {}
        for i, d in enumerate(drafts):
            for u in d.ins:
                consumer_of[u] = i
        edges: dict[int, EdgeSpec] = {}
        for u, i in consumer_of.items():
            c = widths[i] if drafts[i].kind == "pool" else 1
            edges[u] = EdgeSpec.create(widths[u], c, ctx.streaming_ring,
                                       self._stop)
        last = len(drafts) - 1
        self.sink_edge = EdgeSpec.create(widths[last], self._consumers,
                                         ctx.streaming_ring, self._stop)
        edges[last] = self.sink_edge
        # every stage edge is deterministic stripe — each worker owns
        # idxs worker (mod width) and processes them in order, which is
        # both what keeps results bit-identical to the task executor's
        # plan-order delivery AND what makes the credit graph
        # deadlock-free (see channels.py). Work-stealing fan-out exists
        # only at a split sink, where shards hold no downstream credit.
        self.sink_mode = "any" if self._split else "stripe"

        # stage workers are long-lived TASKS on the shared worker pool
        # (see run_stage_loop): one dispatch per worker for the whole
        # run, workers return to the pool when the pipeline ends.
        # max_retries=0 — a retried loop would replay moved ring cursors.
        # num_cpus=0 (the actor default): a stage worker spends its life
        # parked in channel waits; billing each one a core would
        # deadlock any pipeline wider than the CPU count
        remote_loop = ray.remote(run_stage_loop).options(max_retries=0,
                                                         num_cpus=0)
        dispatches = 0
        for i, (d, (pkind, pitems)) in enumerate(zip(drafts, resolved)):
            out_mode = "steal" if (i == last and self._split) \
                else "stripe"
            payload = (pkind, pitems) if d.kind == "source" else d.payload
            spec = StageSpec(
                kind=d.kind, idx=i, width=widths[i], fused=d.fused,
                in_edges=[edges[u] for u in d.ins],
                in_modes=["stripe" for _ in d.ins],
                out_edge=edges[i], out_mode=out_mode, payload=payload)
            blob = cloudpickle.dumps(spec)
            for w in range(widths[i]):
                self._loop_refs.append(remote_loop.remote(blob, w))
                dispatches += 1
        tm.note_dispatches(float(dispatches), "chan")

    # -- consumption ------------------------------------------------------ #

    def _probe(self) -> None:
        """Sink idle hook: surface a failed stage worker promptly (the
        <45s death contract — every wait slice re-checks) and sample the
        sink depth gauge. A run_loop that RETURNED is normal: a worker
        exits once its messages are all acked, which can precede the
        sink draining its peers. Locked: concurrently-consumed split
        shards install this hook from multiple driver threads."""
        if self._recv is not None:
            tm.note_depth(float(self._recv.depth()))
        with self._probe_lock:
            refs = list(self._loop_refs)
        if not refs:
            return
        ready, _ = self._ray.wait(refs, num_returns=1, timeout=0)
        if not ready:
            return
        ref = ready[0]
        with self._probe_lock:
            if ref not in self._loop_refs:
                return   # another shard's probe already claimed it
            self._loop_refs.remove(ref)
        self._ray.get(ref)   # raises the stage's original error

    def _raise_stage_failure(self) -> None:
        """After a ChannelClosed wake (a failing stage seals the stop
        flag), surface the ORIGINAL stage error rather than a generic
        teardown message."""
        with self._probe_lock:
            refs = list(self._loop_refs)
        if not refs:
            return
        done, _ = self._ray.wait(refs, num_returns=len(refs),
                                 timeout=2.0)
        for ref in done:
            self._ray.get(ref)   # first failure raises

    def iter_blocks(self, timeout_s: Optional[float] = None):
        """Drive the pipeline and yield blocks in plan order. Starting,
        consuming and teardown all live inside this generator: closing
        it early (``take(n)``) tears the pipeline down and the store
        still returns to baseline."""
        self.start()
        self._recv = BlockReceiver(self._store, self.sink_edge, 0,
                                   mode=self.sink_mode)
        n_stages = len(self._drafts)
        try:
            while True:
                got = self._recv.next_block(timeout_s=timeout_s,
                                            on_idle=self._probe)
                if got is None:
                    break
                idx, block = got
                tm.note_blocks(1.0, "chan")
                flight.evt(flight.DATA_BLOCK, n_stages, idx)
                yield block
        except ChannelClosed:
            self._raise_stage_failure()   # original error, if a stage died
            raise RuntimeError(
                "streaming pipeline was torn down mid-iteration "
                "(stop flag sealed)") from None
        finally:
            self.shutdown()

    def note_sink_done(self) -> None:
        """Split pipelines have no driver receiver to notice the end of
        the stream: each driver-side shard reports its completion, and
        the LAST one joins the producers (they finish within
        milliseconds of the final EOS ack) and releases the worker
        budget — instead of holding it until the shard feeds are
        garbage-collected. Remotely-consumed shards can't report;
        those pipelines release at feed GC (__del__ -> shutdown)."""
        if not self._started or self._shut:
            return
        with self._probe_lock:
            self._sinks_done += 1
            last = self._sinks_done >= self._consumers
            refs = list(self._loop_refs)
        if not last:
            return
        if refs:
            try:
                self._ray.get(refs, timeout=10.0)
            except Exception:
                pass  # a failed/straggling loop: shutdown reaps it
        self.shutdown()

    # -- teardown --------------------------------------------------------- #

    def shutdown(self, timeout_s: float = 20.0) -> None:
        """Idempotent. Clean completions just join the loop refs; aborts
        seal the stop flag first so every parked worker unwinds, then
        re-sweep the sink windows after stragglers are force-killed."""
        if not self._started or self._shut:
            return
        self._shut = True
        ray = self._ray
        clean = self._recv is not None and self._recv.done()
        stop_oid = ObjectID(self._stop[:ObjectID.SIZE])
        if not clean:
            signal_stop(self._store, stop_oid)
        joined = True
        if self._loop_refs:
            try:
                ray.get(self._loop_refs, timeout=timeout_s)
            except Exception:
                joined = False   # failed stage / wedged user fn
        if not joined:
            # force-reap only what did not unwind: cancel(force) kills
            # the worker process a wedged loop occupies (clean exits
            # already returned their worker to the pool)
            for ref in self._loop_refs:
                try:
                    done, _ = ray.wait([ref], num_returns=1, timeout=0)
                    if not done:
                        ray.cancel(ref, force=True)
                except Exception:
                    pass  # worker already dead
        if not clean:
            if not joined:
                # let the force-kills land, then catch anything a
                # straggler sealed after the first sweep
                time.sleep(0.5)
            if self._recv is not None:
                self._recv.sweep()
        try:
            self._store.delete(stop_oid)
        except Exception:
            pass  # store closing: the flag dies with it
        _release_workers(self._held_workers)
        self._held_workers = 0

    def __del__(self):
        try:
            self.shutdown(timeout_s=2.0)
        except Exception:
            pass  # interpreter teardown: the store reaps everything


class PipelineFeed:
    """Re-iterable block feed over a compiled plan: each ``iter_blocks``
    call is a fresh pipeline run (one epoch = one run). Quacks for
    DataIterator."""

    def __init__(self, make: Callable[[], StreamingPipeline]):
        self._make = make

    def iter_blocks(self):
        return self._make().iter_blocks()

    def __iter__(self):
        return self.iter_blocks()


class ChannelShardFeed:
    """One ``streaming_split`` shard on the channel transport: a
    picklable consumer slot of the sink edge. First iteration pulls
    blocks from the rings (work-stealing: whichever shard consumes gets
    fed) and CACHES them so epochs replay, like the actor-feed split.
    The driver-side original holds the pipeline alive; pickled copies
    ship only the edge spec.

    One live copy per consumer slot: pickling ships the slot, not the
    cache or the ring cursors, so a SECOND copy of a partially-consumed
    shard (e.g. a retried consumer task reusing the same pickled
    argument) would wait on slots the first copy already consumed and
    time out after ``timeout_s`` — blocks a dead consumer had read are
    not replayed. Retry-sensitive consumers should use
    ``split_transport="actor"`` (the coordinator hands out only
    unclaimed blocks)."""

    def __init__(self, edge: EdgeSpec, consumer_idx: int,
                 pipeline: Optional[StreamingPipeline] = None,
                 timeout_s: float = 600.0):
        self._edge = edge
        self._idx = consumer_idx
        self._pipeline = pipeline   # driver-side lifetime anchor
        self._timeout_s = timeout_s
        self._cache: list = []
        self._complete = False
        # ONE receiver for the feed's lifetime: ring cursors must
        # survive a partially-consumed iteration (a fresh receiver at
        # seq 0 would re-wait on slots the first pass already deleted)
        self._recv: Optional[BlockReceiver] = None

    def __reduce__(self):
        return (ChannelShardFeed, (self._edge, self._idx, None,
                                   self._timeout_s))

    def count_rows(self) -> int:
        if not self._complete:
            raise TypeError(
                "count() on an unconsumed streaming_split shard would "
                "steal the other shards' blocks; iterate it (or "
                "materialize() the dataset) first")
        return sum(b.num_rows for b in self._cache)

    def iter_blocks(self):
        yield from self._cache
        if self._complete:
            return
        if self._pipeline is not None:
            self._pipeline.start()
        if self._recv is None:
            store = _local_store()
            if store is None or os.environ.get("RTPU_OWN_STORE") == "1":
                raise RuntimeError(
                    "streaming_split(chan) shard needs a process "
                    "attached to the cluster's shared shm store "
                    "(own-store nodes see none of the sealed slots); "
                    "use split_transport='actor' there")
            self._recv = BlockReceiver(store, self._edge, self._idx,
                                       mode="any")
        recv = self._recv
        # the driver-side shard can probe stage liveness; pickled copies
        # in remote consumers rely on the stop flag + read timeout
        on_idle = self._pipeline._probe if self._pipeline is not None \
            else None
        try:
            while True:
                got = recv.next_block(timeout_s=self._timeout_s,
                                      on_idle=on_idle)
                if got is None:
                    break
                tm.note_blocks(1.0, "chan")
                self._cache.append(got[1])
                yield got[1]
        except ChannelClosed:
            if self._pipeline is not None:
                self._pipeline._raise_stage_failure()  # original error
            raise RuntimeError(
                "streaming_split pipeline was torn down mid-iteration "
                "(stop flag sealed)") from None
        self._complete = True
        if self._pipeline is not None:
            # the last driver-side shard to finish frees the worker
            # budget now, not at feed garbage-collection
            self._pipeline.note_sink_done()

    def __iter__(self):
        return self.iter_blocks()
